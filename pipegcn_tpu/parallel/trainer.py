"""SPMD trainer: the whole training job as one jitted program per epoch.

Replaces the reference's per-rank `run()` (train.py:242-400) — N Python
processes, gloo collectives, autograd hooks, CUDA streams and a thread
pool — with a single `jit(shard_map(...))` train step over a 1-D device
mesh. Everything the reference wove through mutable global state becomes
explicit dataflow in the step's carry:

  reference                                  here
  ---------                                  ----
  ctx.buffer halo recv buffers               comm carry (halo/bgrad/EMA)
  per-param backward hooks + Reducer         lax.psum(grads)/n_train
  SyncBatchNorm dist.all_reduce              psum inside the model
  epoch-pipelined transfers (threads/tags)   staleness-1 carry swap
  torch.optim.Adam                           in-repo adam (train.optim)

Pipelined mode (--enable-pipeline): graph layer i consumes the halo
features exchanged during the *previous* epoch's step and injects the
boundary gradients received then (staleness 1, zeros at epoch 0 —
reference feature_buffer.py:153-163, 219-236); this epoch's halo blocks
and boundary grads are computed alongside and carried forward. Because
next epoch's exchange does not depend on this epoch's loss, XLA can
overlap the collectives with compute inside the step. Optional EMA
smoothing of stale features/grads (--feat-corr/--grad-corr, momentum
`corr_momentum` — reference feature_buffer.py:186-191, parser.py:44-47).
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..graph.csr import Graph
from ..models.sage import ModelConfig, forward, init_norm_state, init_params
from ..obs import flight as flightrec
from ..obs.format import epoch_line, reference_eval_line, reference_train_line
from ..obs.metrics import device_info, memory_snapshot, mesh_info
from ..obs.trace import PhaseTimer, named_phase
from ..ops.spmm import spmm_mean
from ..partition.halo import ShardedGraph
from ..resilience import DivergenceError, PeerLost, Preempted, SentinelConfig
from ..resilience.storage import FAULTY_IO, IO_DEGRADED, IO_KINDS
from ..train.losses import bce_logits_sum, cross_entropy_sum
from ..train.metrics import calc_acc
from ..train.optim import adam_init, adam_update
from .halo import (
    exchange_blocks,
    halo_exchange,
    halo_transport_dtypes,
    make_stale_concat,
    return_blocks,
)
from .mesh import PARTS_AXIS, make_mesh

# 'auto' SpMM selection is table-driven (see _setup_spmm and
# ops/tuner.py): the kernel is resolved from a persisted measured cost
# table (tuning.json in the partition artifact) or a live micro-bench
# campaign — there are no hand-coded shape thresholds.


def _pad_cols(a: np.ndarray, pad: int) -> np.ndarray:
    """Zero-pad the trailing (feature) axis by `pad` columns — the
    lane_pad 128-lane alignment. Zero columns contribute nothing to any
    matmul or mean aggregation, so the padded program computes the same
    outputs on the original columns."""
    if not pad:
        return a
    a = np.asarray(a)
    return np.concatenate(
        [a, np.zeros(a.shape[:-1] + (pad,), a.dtype)], axis=-1)


@dataclasses.dataclass
class TrainConfig:
    lr: float = 1e-2
    weight_decay: float = 0.0
    n_epochs: int = 100
    enable_pipeline: bool = False
    feat_corr: bool = False
    grad_corr: bool = False
    corr_momentum: float = 0.95
    log_every: int = 10
    seed: int = 0
    eval: bool = True
    # run up to this many epochs per dispatch (lax.scan inside the jitted
    # step); 1 = one program per epoch (reference-like granularity)
    fused_epochs: int = 1
    # PRNG implementation for the per-epoch dropout keys: 'threefry'
    # (jax default — counter-based, ALU-heavy per element on TPU) or
    # 'rbg' (hardware RNG-backed, much cheaper bit generation; masks
    # differ from threefry at the same seed but are equally valid
    # dropout noise). A floor-shrink lever for the dropout-RNG share of
    # the non-SpMM epoch floor (scripts/epoch_anatomy.py measures it).
    # 'unsafe_rbg' drops the fold_in/split guarantees too (fastest;
    # fine for dropout, never for init).
    rng_impl: str = "threefry"
    # reuse each dropout mask for N consecutive epochs (0/1 = fresh
    # mask every epoch): the per-epoch key becomes fold_in(base,
    # epoch // N), so N epochs share bits and the RNG share of the
    # floor divides by N. Mild regularization change — the mask cycle
    # repeats — acceptable for large N only with measurement.
    dropout_reuse: int = 0
    # halo ppermute wire dtype: 'none' (compute dtype), 'bfloat16', or
    # 'float8' (e4m3 features / e5m2 bgrads, amax-scaled per block —
    # parallel/halo.py). Pipelined mode only: the vanilla path
    # differentiates through the exchange and must stay exact.
    halo_dtype: str = "none"
    # epochs per megastep dispatch (donated-carry lax.scan + ONE host
    # metrics sync per block). 0 = inherit fused_epochs; otherwise
    # overrides it as the block size ceiling in fit().
    epoch_block: int = 0
    # issue the layer-0 halo exchange at the top of the step (before
    # loss/grad work) so its ppermute overlaps the previous epoch's
    # tail inside a fused block. Numerically identical: layer 0's
    # exchange payload is the (pre-scaled) input features, which are
    # loop-invariant. Pipelined mode, no-pp only.
    comm_prefetch: bool = False
    # ---- numerics guardrails (resilience/numerics.py) ----
    # in-graph non-finite tripwire: cheap per-phase isfinite counts
    # (halo concat / spmm / dense / norm / logits / loss / grads) ride
    # the step metrics, so a NaN's BIRTH phase is named in fault
    # records instead of just "loss is nan"
    numerics_tripwire: bool = True
    # dynamic loss scaling: 'off' | 'auto' | a positive number (static
    # scale). Non-'off' also arms in-graph overflow-skip: a non-finite
    # reduced gradient skips that epoch's parameter update (select, no
    # extra dispatch) and the host state machine backs the scale off.
    loss_scale: str = "off"
    # ---- integrity plane (resilience/integrity.py) ----
    # epochs between silent-data-corruption checks: the static-table
    # scrub, the params/carry digest verification, and the Freivalds
    # aggregation check all run at this cadence, and the pipelined
    # halo exchange gains its wire-checksum lane. 0 (default) disables
    # everything and compiles the byte-identical pre-integrity step.
    integrity_check_every: int = 0
    # Run the P-part SPMD program on ONE device: the identical
    # per-device step is wrapped in jax.vmap(axis_name='parts') instead
    # of shard_map — vmap implements psum/ppermute/axis_index
    # semantically, so staleness/convergence studies at P>1 run on a
    # single TPU chip (the environment has exactly one) at chip speed
    # with bit-matching SPMD semantics. Params/opt/norm are stored
    # stacked [P, ...] (identical across parts after the psum'd
    # update). Not for production scaling — collectives become
    # in-device data movement.
    emulate_parts: bool = False
    # ---- training-span plane (obs/trainspan.py) ----
    # always-on per-rank span emission into the metrics sink: compute /
    # halo_exchange / bgrad_return / grad_reduce / checkpoint / eval
    # spans per dispatched block plus the tracesync clock anchors.
    # Host-side Python only — zero effect on the compiled programs
    # (tests/test_trainspan.py pins zero recompiles with spans hot).
    # --no-train-traces turns it off; inert without a metrics sink.
    train_traces: bool = True


class Trainer:
    """Owns mesh, device data, jitted step/eval, and the epoch loop."""

    def __init__(
        self,
        sg: ShardedGraph,
        cfg: ModelConfig,
        tcfg: TrainConfig,
        devices=None,
    ):
        self.sg = sg
        # training arrays from ShardedGraph are CSR-ordered per device
        self.cfg = dataclasses.replace(cfg, sorted_edges=True)
        self._eval_cfg = dataclasses.replace(cfg, sorted_edges=True)
        # lane_pad: align the input feature slab to the 128-lane TPU
        # boundary. Zero columns are appended host-side (see _pad_cols)
        # and layer_sizes[0] grows to match, so every feature buffer the
        # step donates — and every slab-gather dynamic_slice — moves
        # whole (8, 128) tiles. Eval paths pad identically.
        self._feat_pad = 0
        if getattr(cfg, "lane_pad", False):
            pad = (-cfg.layer_sizes[0]) % 128
            if pad:
                self._feat_pad = pad
                sizes = (cfg.layer_sizes[0] + pad,) \
                    + tuple(cfg.layer_sizes[1:])
                self.cfg = dataclasses.replace(self.cfg,
                                               layer_sizes=sizes)
                self._eval_cfg = dataclasses.replace(self._eval_cfg,
                                                     layer_sizes=sizes)
        self.tcfg = tcfg
        self.P = sg.num_parts
        self.emulated = tcfg.emulate_parts
        if self.emulated:
            # one device carries every part; the [P, ...] arrays live
            # whole on it and the parts axis is a vmap batch axis
            self.mesh = make_mesh(1, devices)
            self._shard = NamedSharding(self.mesh, PartitionSpec())
            self._repl = self._shard
        else:
            self.mesh = make_mesh(self.P, devices)
            self._shard = NamedSharding(self.mesh, PartitionSpec(PARTS_AXIS))
            self._repl = NamedSharding(self.mesh, PartitionSpec())

        self._setup_spmm()
        # with kernel tables active, the step (and the sharded
        # evaluator) aggregate through them and the raw edge list is
        # only needed for the one-shot pp precompute — at Reddit scale
        # the two int32 edge arrays are ~0.9 GB of HBM that would
        # otherwise sit resident for nothing (forward()'s edge args are
        # untraced when spmm_fn is set, so a token shape suffices)
        self._edges_trimmed = (self._bucket_tables is not None
                               or self._block_tables is not None
                               or self._gat_tables is not None)
        # bucket/block tables can also serve the pp precompute, so the
        # raw edges never reach the device at all
        pp_via_tables = (self._bucket_tables is not None
                         or self._block_tables is not None)
        need_edges = (not self._edges_trimmed) or \
            (cfg.use_pp and not pp_via_tables)
        self.data = self._put_data(skip_edges=not need_edges)
        if cfg.use_pp:
            self.data["feat"] = self._precompute_pp()
        if cfg.compute_dtype != jnp.float32:
            # store input features in the compute dtype so the per-epoch
            # HBM read (and layer-0 halo exchange) is half-width; the pp
            # precompute above still ran in f32
            self.data["feat"] = self.data["feat"].astype(cfg.compute_dtype)
        if self._edges_trimmed and need_edges:
            # edges were uploaded only for the precompute above; drop
            # them now
            dummy = jnp.zeros((self.P, 8), jnp.int32)
            self.data["edge_src"] = jax.device_put(dummy, self._shard)
            self.data["edge_dst"] = jax.device_put(dummy, self._shard)

        rng = jax.random.PRNGKey(tcfg.seed)
        # self.cfg, not the ctor arg: lane_pad rewrote layer_sizes[0]
        params = init_params(rng, self.cfg)
        if self.emulated:
            # replicated-by-construction: stacked copies stand in for
            # shard_map's replicated spec (the psum'd update keeps every
            # part's copy identical)
            stack = lambda t: jax.tree_util.tree_map(
                lambda v: jnp.stack([v] * self.P), t)
            params, opt, norm = (stack(params), stack(adam_init(params)),
                                 stack(init_norm_state(self.cfg)))
        else:
            opt = adam_init(params)
            norm = init_norm_state(self.cfg)
        self.state = {
            "params": jax.device_put(params, self._repl),
            "opt": jax.device_put(opt, self._repl),
            "norm": jax.device_put(norm, self._repl),
            "comm": jax.device_put(self._init_comm(), self._shard),
        }
        # ---- numerics guardrails (resilience/numerics.py) ----
        from ..resilience.numerics import LossScaleConfig, LossScaler

        # host side of the dynamic loss-scale state machine; the scale
        # is passed into every dispatch as a traced scalar (value
        # changes never recompile)
        self.loss_scaler = LossScaler(
            LossScaleConfig.parse(getattr(tcfg, "loss_scale", "off")))
        # kernel fallback ladder state: an unproven kernel's first
        # dispatch is guarded (see _dispatch); successful dispatch
        # proves it. Fallbacks taken accumulate here for fit()/bench
        # to surface as contracted `fallback` records.
        self.fallbacks: list = []
        self._kernel_proven = False
        self._inject_kernel_crash = False
        self._step = self._build_step()
        self._eval_cache: Dict[int, Any] = {}
        self._sharded_eval_cache: Dict[int, Any] = {}
        # compiled sharded-eval programs keyed on (shape, dtype, impl) —
        # ShardedEvaluator instances come and go (one per eval graph id)
        # but their jitted forward is identical whenever the data
        # signature matches, so the program outlives the evaluator
        # (compile-count pinned in tests/test_eval.py)
        self._eval_program_cache: Dict[Any, Any] = {}

        @partial(jax.jit, static_argnames=("n",))
        def _eval_run(params, norm, feat, es, ed, deg, n):
            with named_phase("eval"):
                logits, _ = forward(
                    params, self._eval_cfg, feat, es, ed, deg, n,
                    training=False, norm_state=norm,
                    eval_pp_agg=self._eval_cfg.use_pp,
                )
            return logits

        self._eval_run = _eval_run

    # ---------------- spmm kernel selection ---------------------------

    # bump when any kernel-table layout changes: stale caches must miss
    _TABLES_FORMAT = 6  # v6: slab-gather run plans (res/src/pos/cnt keys)

    def _cached_tables(self, kind: str, build_fn):
        """Disk-cache derived kernel tables next to the partition
        artifact (sg.cache_dir, set by ShardedGraph.load): the O(E)
        host builds cost minutes at 100M-edge scale and depend only on
        the artifact. The cache is stamped with (_TABLES_FORMAT,
        source_edge_checksum) and validated on load — a regenerated
        artifact or a format change must rebuild, never silently load
        tables for a different graph. Corrupt/mismatched caches fall
        back to the build. bfloat16 arrays round-trip as uint16 bit
        views (npz stores bf16 as raw void and cannot restore it);
        writes go to a temp file + atomic rename so a killed run (or a
        shared-filesystem race between hosts, halo.py save()) can never
        leave a truncated file the next run trusts."""
        import os

        import ml_dtypes

        cd = getattr(self.sg, "cache_dir", None)
        fname = os.path.join(cd, f"{kind}_tables.npz") if cd else None
        stamp = np.asarray(
            [self._TABLES_FORMAT,
             int(self.sg.source_edge_checksum) & ((1 << 64) - 1)],
            dtype=np.uint64)
        if fname and os.path.exists(fname):
            try:
                z = np.load(fname)
                if "__stamp__" in z.files and \
                        np.array_equal(z["__stamp__"], stamp):
                    bf16_keys = set(z["__bf16_keys__"].tolist())
                    return {
                        k: z[k].view(ml_dtypes.bfloat16)
                        if k in bf16_keys else z[k]
                        for k in z.files
                        if k not in ("__bf16_keys__", "__stamp__")
                    }
            except Exception:  # truncated/corrupt cache: rebuild below
                pass
        tables = build_fn()
        if fname:
            bf16_keys = [k for k, v in tables.items()
                         if v.dtype == ml_dtypes.bfloat16]
            tmp = f"{fname}.{os.getpid()}.tmp"
            try:
                with open(tmp, "wb") as f:
                    np.savez(
                        f,
                        __stamp__=stamp,
                        __bf16_keys__=np.asarray(bf16_keys, dtype="U64"),
                        **{k: (v.view(np.uint16) if k in bf16_keys else v)
                           for k, v in tables.items()},
                    )
                os.replace(tmp, fname)
            except OSError:  # read-only artifact dir: cache is optional
                pass
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        # genuinely-optional (storage-fault audit):
                        # orphaned temp in a cache dir, never read
                        pass
        return tables

    def _setup_spmm(self) -> None:
        """Resolve cfg.spmm_impl: 'bucket' builds the scatter-free
        degree-bucketed aggregation tables (ops/bucket_spmm.py), 'block'
        the hybrid dense-tile MXU kernel's (ops/block_spmm.py), 'xla'
        (default) keeps gather+segment-sum over the raw edge list.
        'auto' resolves from MEASURED cost (_resolve_auto): the
        partition artifact's persisted tuning.json when present and
        trusted, else a live micro-bench campaign (ops/tuner.py) — then
        lands on one of the three concrete impls above. No hand-coded
        shape thresholds exist on this path."""
        impl = self.cfg.spmm_impl
        self._bucket_tables = None
        self._block_tables = None
        self._block_tile = 0
        self._gat_tables = None
        self.tuning = None
        if impl not in ("xla", "auto", "bucket", "block"):
            raise ValueError(f"unknown spmm_impl: {impl}")
        if self.cfg.model == "gat":
            # per-edge attention weights run through the attention-bucket
            # kernel (ops/gat_bucket.py) — same scatter-free structure as
            # the mean path, plus per-bucket row-id tables for the
            # softmax stats. 'auto' always picks it: the raw-edge
            # segment path it replaces is the measured 19.8 s/epoch-class
            # regime (docs/PERF_NOTES.md).
            if impl in ("auto", "bucket"):
                from ..ops.gat_bucket import build_sharded_gat_tables

                self._gat_tables = self._cached_tables(
                    "gat", lambda: build_sharded_gat_tables(self.sg))
                # rem_dtype advice applies only to the bucket kernel,
                # which is what consumes it — not the raw-xla path
                if (self.cfg.rem_dtype is None
                        and float(np.mean(self.sg.edge_count)) > 2e7):
                    import warnings

                    warnings.warn(
                        "GAT at this edge count without --rem-dtype "
                        "float8: bf16 transport measured ~2x the epoch "
                        "time and crashed the tunneled TPU worker at "
                        "Reddit scale (results/gat_tpu_bench.md); fp8 "
                        "is accuracy-validated (results/"
                        "staleness_parity_gat.md)")
            return
        if impl == "auto":
            impl = self._resolve_auto()
        if impl == "bucket":
            self._use_bucket()
        elif impl == "block":
            self._use_block()

    def _slab_flag(self) -> bool:
        """Resolve cfg.slab to a concrete on/off for table builds:
        'on'/'off' are user pins, 'auto' takes the tuner winner's
        measured slab decision when one exists (self.tuning set by
        _resolve_auto) and stays off otherwise — slab plans only pay
        off when the layout has contiguous runs, which is exactly what
        the tuner measures per (reorder, shape)."""
        mode = str(getattr(self.cfg, "slab", "auto"))
        if mode == "on":
            return True
        if mode == "off":
            return False
        win = (self.tuning or {}).get("winner") or {}
        return bool(win.get("slab"))

    def _use_bucket(self, dirty=None) -> None:
        from ..ops.bucket_spmm import (build_sharded_bucket_tables,
                                       validate_bucket_tables)

        merge = int(getattr(self.cfg, "bucket_merge", 0))
        slab_on = self._slab_flag()
        kind = ("bucket" + (f"_m{merge}" if merge else "")
                + ("_slab" if slab_on else ""))
        # streaming (enable_stream) keeps a per-shard BucketPlan cache
        # so a delta batch rebuilds plans only for its dirty shards
        cache = getattr(self, "_bucket_plan_cache", None)
        self._bucket_tables = self._cached_tables(
            kind, lambda: build_sharded_bucket_tables(
                self.sg, min_width=merge, slab=slab_on,
                plan_cache=cache, dirty=dirty))
        # the kernel's clip-mode gathers are sound only for
        # in-bounds tables; a rotted cache must fail HERE, loudly,
        # not clamp to wrong rows mid-epoch
        validate_bucket_tables(self._bucket_tables, self.sg.n_max,
                               self.sg.n_max + self.sg.halo_size)

    def _use_block(self) -> None:
        from ..ops.block_spmm import build_sharded_block_tables

        w_hint = max(self.cfg.layer_sizes[:self.cfg.n_graph_layers])
        tile = self.cfg.block_tile
        nnz = self.cfg.block_nnz
        grp = self.cfg.block_group
        slab_on = self._slab_flag()
        key = (f"block_{tile}_{w_hint}" + (f"_n{nnz}" if nnz else "")
               + (f"_u{grp}" if grp > 1 else "")
               + ("_slab" if slab_on else ""))
        self._block_tables = self._cached_tables(
            key,
            lambda: build_sharded_block_tables(
                self.sg, tile=tile, n_feat_hint=w_hint,
                nnz_threshold=nnz, group=grp, slab=slab_on)[0])
        self._block_tile = tile

    def _resolve_auto(self) -> str:
        """Pick the concrete kernel for spmm_impl='auto' from measured
        cost, never from shape heuristics. Trust order: (1) the
        artifact's persisted tuning.json when tuner format, source edge
        checksum AND config signature all match; (2) a live micro-bench
        campaign (ops/tuner.py) on single-process runs with cfg.tune,
        persisted back into a disk-backed artifact; (3) the tuner's
        fixed deterministic default, with a loud warning — multi-process
        runs never live-tune (per-rank timing noise would argmin
        different kernels and desync the SPMD program). The decision
        (winner + measured cost table + source) lands in self.tuning
        for fit()/bench to emit as a contracted `tuning` record."""
        import warnings

        from ..ops import tuner

        cfg = self.cfg
        width = max(cfg.layer_sizes[:cfg.n_graph_layers])
        sig = tuner.signature_for(
            width=width, block_tile=cfg.block_tile,
            bucket_merge=getattr(cfg, "bucket_merge", 0),
            chunk_edges=cfg.spmm_chunk,
            rng_impl=getattr(self.tcfg, "rng_impl", "threefry"),
            halo_dtype=getattr(self.tcfg, "halo_dtype", "none"),
            epoch_block=int(getattr(self.tcfg, "epoch_block", 0)),
            reorder=str(getattr(self.sg, "reorder", "none")),
            layout_version=int(getattr(self.sg, "layout_version", 1)))
        cd = getattr(self.sg, "cache_dir", None)
        rec, reason = None, "no artifact directory (in-memory graph)"
        if cd:
            rec, reason = tuner.load_tuning(
                cd,
                expect_checksum=getattr(self.sg,
                                        "source_edge_checksum", -1),
                signature=sig)
        source = "artifact"
        if rec is None:
            can_tune = (bool(getattr(cfg, "tune", True))
                        and jax.process_count() == 1)
            if can_tune:
                source = "live"
                rec = tuner.tune(
                    self.sg, width, block_tile=cfg.block_tile,
                    block_nnz=cfg.block_nnz,
                    block_group=cfg.block_group,
                    rem_dtype=cfg.rem_dtype or "auto",
                    rem_amax=cfg.rem_amax,
                    chunk_edges=cfg.spmm_chunk,
                    bucket_merge=getattr(cfg, "bucket_merge", 0),
                    rng_impl=getattr(self.tcfg, "rng_impl", "threefry"),
                    halo_dtype=getattr(self.tcfg, "halo_dtype", "none"),
                    epoch_block=int(getattr(self.tcfg, "epoch_block", 0)),
                    slab=str(getattr(cfg, "slab", "auto")),
                    edge_budget=int(getattr(
                        cfg, "tuner_samples",
                        tuner.DEFAULT_EDGE_BUDGET)))
                if cd:
                    try:
                        tuner.save_tuning(cd, rec)
                    except OSError as exc:
                        # routed-through-degradation (storage-fault
                        # audit): the run proceeds on the measured
                        # in-memory table, but silently losing the
                        # sidecar means every future run re-pays the
                        # micro-bench campaign — say so
                        warnings.warn(
                            f"tuning sidecar write to {cd} failed "
                            f"({exc!r}); io-degraded — the measured "
                            f"table is session-only and the next run "
                            f"will re-tune")
            else:
                source = "default"
                why = ("tuning disabled (--no-tune)"
                       if not getattr(cfg, "tune", True)
                       else "multi-process run (live tuning would "
                            "desync ranks)")
                warnings.warn(
                    f"spmm_impl='auto' with no trusted tuning table "
                    f"({reason}) and no live tune ({why}); using the "
                    f"deterministic default {tuner.DEFAULT_IMPL!r}")
                rec = {"winner": {"name": tuner.DEFAULT_IMPL,
                                  "impl": tuner.DEFAULT_IMPL,
                                  "rem_dtype": None, "rem_amax": False,
                                  "block_group": 1, "slab": False},
                       "costs": []}
        win = dict(rec["winner"])
        self.tuning = {
            "winner": win,
            "source": source,
            "stale_reason": None if source == "artifact" else reason,
            "costs": rec.get("costs", []),
            "gather_contiguity": rec.get("gather_contiguity"),
            "emitted": False,
        }
        # fill tuner-chosen transport/group defaults — never override
        # an explicit user pin (a pinned value restricted the grid)
        repl = {}
        if cfg.rem_dtype is None and win.get("rem_dtype"):
            repl["rem_dtype"] = win["rem_dtype"]
            repl["rem_amax"] = bool(win.get("rem_amax"))
        if win["impl"] == "block" and cfg.block_group <= 1 \
                and int(win.get("block_group", 1)) > 1:
            repl["block_group"] = int(win["block_group"])
        if repl:
            self.cfg = dataclasses.replace(self.cfg, **repl)
            self._eval_cfg = dataclasses.replace(self._eval_cfg, **repl)
        return win["impl"]

    # ---------------- data placement ----------------------------------

    @classmethod
    def prewarm_tables(cls, sg: ShardedGraph, cfg: ModelConfig) -> None:
        """Build and disk-cache the kernel tables for (sg, cfg) WITHOUT
        constructing the full trainer — no full-graph device uploads,
        no pp precompute. The scarce-TPU workflow: the O(E) host builds
        run while the chip is unavailable, so the next real run only
        loads npz (docs/PERF_NOTES.md tunnel notes). spmm_impl='auto'
        additionally runs the tuner's micro-bench campaign (small
        sampled slice on the current backend) and persists tuning.json
        into the artifact, then warms the winner's tables — this is
        the artifact-build-time tuning entry point."""
        if getattr(sg, "cache_dir", None) is None:
            raise ValueError(
                "prewarm_tables needs a disk-backed artifact "
                "(sg.cache_dir unset — load the ShardedGraph from disk "
                "or set cache_dir); the build would be discarded")
        if cfg.model == "gat":
            # the gat setup branch only builds tables for auto/bucket
            # and returns early — block would silently warm nothing
            cacheable = cfg.spmm_impl in ("auto", "bucket")
        else:
            cacheable = cfg.spmm_impl in ("auto", "bucket", "block")
        if not cacheable:
            raise ValueError(
                f"spmm_impl={cfg.spmm_impl!r} does not disk-cache "
                "tables (only auto/bucket/block — and the gat kernel — "
                "do); nothing to prewarm")
        self = cls.__new__(cls)
        self.sg = sg
        self.cfg = dataclasses.replace(cfg, sorted_edges=True)
        self._eval_cfg = self.cfg
        self._setup_spmm()

    def _put_data(self, skip_edges: bool = False) -> Dict[str, jax.Array]:
        sg = self.sg
        edge_dummy = np.zeros((self.P, 8), np.int32)
        arrs = {
            "feat": _pad_cols(sg.feat, self._feat_pad),
            "label": sg.label,
            "train_mask": sg.train_mask,
            "in_deg": sg.in_deg,
            "edge_src": edge_dummy if skip_edges
            else sg.edge_src.astype(np.int32),
            "edge_dst": edge_dummy if skip_edges
            else sg.edge_dst.astype(np.int32),
            "send_idx": sg.send_idx.astype(np.int32),
            "send_mask": sg.send_mask,
            # True for real inner rows, False for padding (BN statistics)
            "row_mask": (
                np.arange(sg.n_max)[None, :] < sg.inner_count[:, None]
            ).astype(np.float32),
        }
        if self._bucket_tables is not None:
            arrs.update(self._bucket_tables)
        if self._block_tables is not None:
            arrs.update(self._block_tables)
        if self._gat_tables is not None:
            arrs.update(self._gat_tables)
        return {
            k: jax.device_put(jnp.asarray(v), self._shard)
            for k, v in arrs.items()
        }

    # ---------------- comm carry state --------------------------------

    def _graph_layer_range(self):
        """Graph layers that exchange halos: skip layer 0 under use_pp
        (reference feature_buffer.py:60-61, model.py:45-46)."""
        start = 1 if self.cfg.use_pp else 0
        return range(start, self.cfg.n_graph_layers)

    def _layer_width(self, i: int) -> int:
        # input width of graph layer i as seen by the exchange; under
        # use_pp the layer-0 input is the 2F concat but layer 0 never
        # exchanges, so plain layer_sizes applies to all exchanged layers
        return self.cfg.layer_sizes[i]

    def _init_comm(self):
        """Per-device stacked [P, ...] zero buffers for pipelined mode.
        Transport buffers (halo/bgrad) use the compute dtype; the EMA
        correction accumulators (favg/bavg) stay f32 so repeated small
        (1-momentum)-sized updates don't vanish in bf16."""
        if not self.tcfg.enable_pipeline:
            return {}
        H = self.sg.halo_size
        cdt = self.cfg.compute_dtype
        comm = {"halo": {}, "bgrad": {}}
        if self.tcfg.feat_corr:
            comm["favg"] = {}
        if self.tcfg.grad_corr:
            comm["bavg"] = {}
        for i in self._graph_layer_range():
            f = self._layer_width(i)
            # distinct host arrays per slot: aliased device buffers would
            # be donated twice in one Execute() and rejected
            comm["halo"][str(i)] = np.zeros((self.P, H, f), cdt)
            comm["bgrad"][str(i)] = np.zeros((self.P, H, f), cdt)
            if self.tcfg.feat_corr:
                comm["favg"][str(i)] = np.zeros((self.P, H, f), np.float32)
            if self.tcfg.grad_corr:
                comm["bavg"][str(i)] = np.zeros((self.P, H, f), np.float32)
        return comm

    # ---------------- streaming deltas (stream/patch.py) --------------

    def enable_stream(self, patcher) -> None:
        """Attach a GraphPatcher so apply_graph_deltas() can mutate the
        live training graph between epochs (docs/STREAMING.md). The
        patcher must wrap THIS trainer's sg. use_pp is refused: its
        one-shot feature precompute bakes the pre-delta topology into
        the layer-0 concat, which a patch cannot fix incrementally."""
        if self.cfg.use_pp:
            raise ValueError(
                "streaming deltas are incompatible with use_pp: the "
                "precomputed layer-0 aggregation would go stale on "
                "every topology change")
        if patcher.sg is not self.sg:
            raise ValueError(
                "patcher wraps a different ShardedGraph than this "
                "trainer's")
        self._stream = patcher
        # per-shard BucketPlan cache for dirty-shard-only rebuilds
        # (_use_bucket passes it through to build_sharded_bucket_tables)
        self._bucket_plan_cache: dict = {}
        # topology generation: bumped once per applied DeltaBatch, and
        # stamped into checkpoints (the journal watermark) so every
        # resume path knows which graph the params trained against
        self.topo_generation = int(getattr(self, "topo_generation", 0))

    def apply_graph_deltas(self, batch, allow_repad: bool = True):
        """Apply one DeltaBatch to the live trainer: patch the sharded
        graph in place (stream/patch.py), rebuild only the affected
        kernel tables, re-upload the data dict, and flush the pipelined
        carry rows whose halo slots changed. Compiled shapes are static
        across deltas (the step is NOT rebuilt) unless the patch
        exhausted the reserved slack and re-padded — then every shape
        grew and a recompile is the documented, loud exception.

        Returns the PatchReport (tables_rebuilt filled in)."""
        patcher = getattr(self, "_stream", None)
        if patcher is None:
            raise RuntimeError(
                "call enable_stream(patcher) before apply_graph_deltas")
        report = patcher.apply(batch, allow_repad=allow_repad)
        self.sg = patcher.sg
        rebuilt = 0
        if report.repadded:
            # padded dims grew: every table and every compiled program
            # keyed on them is invalid. Full rebuild path — identical
            # to __init__'s setup, minus use_pp (refused above).
            self._bucket_plan_cache = {}
            self._setup_spmm()
            self._edges_trimmed = (self._bucket_tables is not None
                                   or self._block_tables is not None
                                   or self._gat_tables is not None)
            rebuilt = self.P * max(
                (self._bucket_tables is not None)
                + (self._block_tables is not None)
                + (self._gat_tables is not None), 1)
            self.data = self._put_data(skip_edges=self._edges_trimmed)
            self._step = self._build_step()
            # the carry's [P, H, f] shapes changed; restart the pipeline
            # from a zero carry (one staleness-reset epoch, same as the
            # sentinel's rollback flush)
            self.state = dict(self.state)
            self.state["comm"] = jax.device_put(
                self._init_comm(), self._shard)
        else:
            dirty = report.touched_parts or None
            if self._bucket_tables is not None:
                self._use_bucket(dirty=dirty)
                rebuilt += len(dirty) if dirty else self.P
            if self._block_tables is not None:
                self._use_block()  # block plans are whole-shard; full
                rebuilt += self.P
            if self._gat_tables is not None:
                from ..ops.gat_bucket import build_sharded_gat_tables

                self._gat_tables = self._cached_tables(
                    "gat", lambda: build_sharded_gat_tables(self.sg))
                rebuilt += self.P
            self.data = self._put_data(skip_edges=self._edges_trimmed)
            self._flush_comm_rows(report)
        if self.cfg.compute_dtype != jnp.float32:
            self.data["feat"] = self.data["feat"].astype(
                self.cfg.compute_dtype)
        # the host Graph mutated in place: id-keyed eval caches would
        # serve the pre-delta topology (program cache is shape-keyed
        # and stays — that is the zero-recompile pin)
        self._eval_cache.clear()
        self._sharded_eval_cache.clear()
        report.tables_rebuilt = rebuilt
        self.topo_generation = getattr(self, "topo_generation", 0) + 1
        return report

    # ---------------- integrity plane (resilience/integrity.py) -------

    def _rebuild_static_data(self, dirty=None) -> int:
        """Rebuild the kernel tables (dirty shards only where the
        builder supports it) from the host partition artifact and
        re-upload the static data dict — the SDC scrubber's recovery
        path. Shares the dirty-shard machinery with streaming
        (apply_graph_deltas); compiled shapes are untouched, so the
        zero-recompile pin holds. Returns per-shard rebuild count."""
        dirty = sorted(int(d) for d in dirty) if dirty else None
        rebuilt = 0
        if self._bucket_tables is not None:
            self._use_bucket(dirty=dirty)
            rebuilt += len(dirty) if dirty else self.P
        if self._block_tables is not None:
            self._use_block()  # block plans are whole-shard
            rebuilt += self.P
        if self._gat_tables is not None:
            from ..ops.gat_bucket import build_sharded_gat_tables

            self._gat_tables = self._cached_tables(
                "gat", lambda: build_sharded_gat_tables(self.sg))
            rebuilt += self.P
        # re-upload, mirroring __init__'s placement dance: edges ride
        # along only when the pp precompute (or the raw-edge kernel)
        # needs them, and are trimmed back to a token shape after
        pp_via_tables = (self._bucket_tables is not None
                         or self._block_tables is not None)
        need_edges = (not self._edges_trimmed) or \
            (self.cfg.use_pp and not pp_via_tables)
        self.data = self._put_data(skip_edges=not need_edges)
        if self.cfg.use_pp:
            self.data["feat"] = self._precompute_pp()
        if self.cfg.compute_dtype != jnp.float32:
            self.data["feat"] = self.data["feat"].astype(
                self.cfg.compute_dtype)
        if self._edges_trimmed and need_edges:
            dummy = jnp.zeros((self.P, 8), jnp.int32)
            self.data["edge_src"] = jax.device_put(dummy, self._shard)
            self.data["edge_dst"] = jax.device_put(dummy, self._shard)
        if not rebuilt:
            rebuilt = self.P  # raw-edge mode: the re-upload itself
        return rebuilt

    def _inject_bitflip(self, target: str, epoch: int, log_fn) -> bool:
        """Chaos-lane SDC injection (bitflip@E[:rN]:<target>): flip one
        bit, host-side, in the named state class on THIS rank. The
        device programs are never altered (the resilience/faults.py
        invariant) — the corruption model is state rotting while it
        sits at the boundary, exactly the window the integrity plane's
        digest scrub covers."""
        from ..resilience.integrity import flip_bit

        if jax.process_count() > 1 and target != "params":
            # fetching a SHARDED array is a cross-process collective
            # only this rank would run; multi-process drills flip the
            # replicated params (locally fetchable) instead
            log_fn(f"bitflip:{target} at epoch {epoch} skipped: "
                   f"multi-process injection supports params only")
            return False
        local_devs = [d for d in self.mesh.devices.flat
                      if d.process_index == jax.process_index()]

        def _replicate_local(arr):
            shards = [jax.device_put(arr, d) for d in local_devs]
            return jax.make_array_from_single_device_arrays(
                arr.shape, self._repl, shards)

        if target == "params":
            host_p = jax.device_get(self.state["params"])
            leaves, treedef = jax.tree_util.tree_flatten(host_p)
            # a mid-mantissa bit: the corrupt value stays finite (the
            # point of SDC — the numerics tripwire must NOT see it)
            leaves[0] = flip_bit(leaves[0], bit=11, index=epoch)
            self.state = dict(self.state)
            self.state["params"] = jax.tree_util.tree_map(
                _replicate_local,
                jax.tree_util.tree_unflatten(treedef, leaves))
            return True
        if target in ("carry", "halo"):
            comm = self.state.get("comm") or {}
            group = ("halo" if target == "halo" else
                     next((k for k in sorted(comm) if k != "halo"),
                          None))
            sub = comm.get(group) if group else None
            if not sub:
                log_fn(f"bitflip:{target} at epoch {epoch} skipped: "
                       f"pipelined carry not enabled")
                return False
            key = sorted(sub)[0]
            arr = sub[key]
            host = flip_bit(jax.device_get(arr), bit=7, index=epoch)
            comm = dict(comm)
            comm[group] = dict(sub)
            comm[group][key] = jax.device_put(jnp.asarray(host),
                                              arr.sharding)
            self.state = dict(self.state)
            self.state["comm"] = comm
            return True
        if target == "tables":
            cand = [k for k in sorted(self.data)
                    if k.startswith(("bkt_", "blk_", "blkrem_",
                                     "gat_"))]
            key = cand[0] if cand else "send_idx"
            arr = self.data[key]
            host = flip_bit(jax.device_get(arr), bit=3, index=epoch)
            self.data = dict(self.data)
            self.data[key] = jax.device_put(jnp.asarray(host),
                                            arr.sharding)
            return True
        log_fn(f"bitflip:{target} at epoch {epoch} skipped: "
               f"unknown target class")
        return False

    def _flush_comm_rows(self, report) -> None:
        """Zero the pipelined carry rows invalidated by a patch: halo
        slots whose send-list entry moved/appeared/vanished carry
        features (receiver view) and boundary grads (sender view) for
        the WRONG node — one flushed row costs one epoch of staleness-1
        correction on that row, a stale-wrong-node row corrupts it."""
        comm = self.state.get("comm")
        if not comm or report.changed_send is None:
            return
        from ..stream.patch import flush_masks

        recv, send = flush_masks(report.changed_send, self.P,
                                 self.sg.b_max)
        if not (recv.any() or send.any()):
            return
        masks = {"halo": recv, "favg": recv, "bgrad": send, "bavg": send}
        new_comm = {}
        for grp, bufs in comm.items():
            m = jax.device_put(jnp.asarray(masks[grp][:, :, None]),
                               self._shard)
            new_comm[grp] = {
                k: jnp.where(m, jnp.zeros((), v.dtype), v)
                for k, v in bufs.items()
            }
        self.state = dict(self.state)
        self.state["comm"] = new_comm

    # ---------------- pp precompute -----------------------------------

    def _precompute_pp(self, sg=None, data=None) -> jax.Array:
        """One-time halo exchange + mean aggregation of raw features,
        stored as concat([feat, mean_neigh]) so layer 0 needs no
        training-time communication (reference train.py:169-189).

        Defaults to the trainer's own sharded graph/data; an explicit
        (sg, data) pair computes the same concat for another graph on the
        same mesh (the sharded evaluator's use_pp input).

        Aggregates through bucket/block kernel tables when `data`
        carries them — the raw edge list then never needs to reach the
        device at all."""
        sg = sg if sg is not None else self.sg
        data = data if data is not None else self.data
        n_max = sg.n_max
        use_tables = ("bkt_fwd_inv" in data) or ("blk_a" in data) \
            or ("blk_a_bits" in data)

        def pp(d):
            d = {k: v[0] for k, v in d.items()}
            fbuf = halo_exchange(d["feat"], d["send_idx"], d["send_mask"],
                                 PARTS_AXIS, self.P)
            if use_tables:
                spmm = self.make_device_spmm_closure(
                    d, n_max=n_max, n_src_rows=n_max + sg.halo_size,
                    transport=False)
                ah = spmm(fbuf)
            else:
                ah = spmm_mean(fbuf, d["edge_src"], d["edge_dst"],
                               d["in_deg"], n_max, self.cfg.spmm_chunk,
                               self.cfg.sorted_edges)
            return jnp.concatenate([d["feat"], ah.astype(d["feat"].dtype)],
                                   axis=1)[None]

        spec = PartitionSpec(PARTS_AXIS)
        keys = ["feat", "in_deg", "send_idx", "send_mask"]
        if use_tables:
            keys += [k for k in data
                     if k.startswith(("bkt_", "blk_", "blkrem_"))]
        else:
            keys += ["edge_src", "edge_dst"]
        d_in = {k: data[k] for k in keys}
        if self.emulated:
            # single-device parts emulation: same pp body under
            # vmap(axis_name) — see _build_step
            tm = jax.tree_util.tree_map

            def vpp(d):
                return pp(tm(lambda v: v[None], d))[0]

            fn = jax.jit(jax.vmap(vpp, axis_name=PARTS_AXIS))
            return fn(d_in)
        fn = jax.jit(
            jax.shard_map(
                pp, mesh=self.mesh,
                in_specs=(jax.tree_util.tree_map(lambda _: spec, d_in),),
                out_specs=spec,
            )
        )
        return fn(d_in)

    # ---------------- the train step ----------------------------------

    def make_device_spmm_closure(self, d: Dict[str, jax.Array],
                                 n_max: Optional[int] = None,
                                 n_src_rows: Optional[int] = None,
                                 transport: bool = True):
        """Per-device mean-aggregation closure over the stripped (no
        leading device axis) table arrays in `d` — or None when `d`
        carries no kernel tables (raw-edge XLA path). The kernel kind is
        read off the table keys present, so the same builder serves the
        train step (tables matching cfg.spmm_impl) and the sharded
        evaluator (whose foreign eval graphs carry bucket tables
        regardless of the training impl). Shape overrides cover eval
        graphs sharded differently from the training graph."""
        cfg = self.cfg
        n_max = self.sg.n_max if n_max is None else n_max
        if n_src_rows is None:
            n_src_rows = n_max + self.sg.halo_size
        # transport=False: one-shot consumers (the pp precompute of RAW
        # features) must not inherit the narrowed per-epoch gather
        # transport — their cost is irrelevant and raw feature ranges
        # can exceed e4m3's +-448
        rem_dtype = cfg.rem_dtype if transport else None
        if "bkt_fwd_inv" in d:
            from ..ops.bucket_spmm import make_device_bucket_spmm_fn

            return make_device_bucket_spmm_fn(
                d, d["in_deg"], n_src_rows, chunk_edges=cfg.spmm_chunk,
                rem_dtype=rem_dtype,
                rem_amax=cfg.rem_amax and transport,
            )
        if "blk_a" in d or "blk_a_bits" in d:
            from ..ops.block_spmm import make_device_block_spmm_fn

            return make_device_block_spmm_fn(
                d, d["in_deg"], n_max, n_src_rows, self._block_tile,
                chunk_edges=cfg.spmm_chunk, rem_dtype=rem_dtype,
                rem_amax=cfg.rem_amax and transport,
            )
        return None

    def make_device_gat_closure(self, d: Dict[str, jax.Array],
                                n_max: Optional[int] = None,
                                n_src_rows: Optional[int] = None,
                                transport: bool = True):
        """Per-device attention-aggregation closure (ops/gat_bucket.py)
        over the stripped table arrays in `d` — or None when `d`
        carries no attention-bucket tables (raw-edge GAT path).
        transport=False exempts one-shot metric-bearing consumers from
        the narrowed gather transport (same contract as
        make_device_spmm_closure)."""
        if "gat_fwd_inv" not in d:
            return None
        from ..ops.gat_bucket import make_device_gat_fn

        cfg = self.cfg
        n_max = self.sg.n_max if n_max is None else n_max
        if n_src_rows is None:
            n_src_rows = n_max + self.sg.halo_size
        return make_device_gat_fn(
            d, n_max, n_src_rows, cfg.n_heads, cfg.leaky_slope,
            chunk_edges=cfg.spmm_chunk,
            rem_dtype=cfg.rem_dtype if transport else None,
        )

    def _build_step(self):
        from ..resilience.numerics import PHASES, LossScaleConfig

        sg, cfg, tcfg, P = self.sg, self.cfg, self.tcfg, self.P
        n_max, b_max, H = sg.n_max, sg.b_max, sg.halo_size
        n_train = float(sg.n_train_global)
        multilabel = sg.multilabel
        pipeline = tcfg.enable_pipeline
        glayers = list(self._graph_layer_range())
        momentum = tcfg.corr_momentum
        # trace-time gates for the numerics guardrails: the tripwire
        # adds a handful of isfinite reductions; loss scaling adds the
        # scale multiply + the overflow-skip select. Both off -> the
        # traced program is byte-identical to the pre-guardrail step
        # (scale is a dead input).
        tripwire = bool(getattr(tcfg, "numerics_tripwire", True))
        ls_on = LossScaleConfig.parse(
            getattr(tcfg, "loss_scale", "off")).enabled
        # halo wire compression (parallel/halo.py): pipelined mode only
        # — the vanilla path differentiates through the exchange and a
        # lossy cast there would silently bias gradients
        halo_dt = getattr(tcfg, "halo_dtype", "none") or "none"
        if halo_dt != "none" and not pipeline:
            raise ValueError(
                "halo_dtype compression requires enable_pipeline: the "
                "vanilla exchange is differentiated and must stay exact")
        feat_dt, bgrad_dt = halo_transport_dtypes(halo_dt)
        # layer-0 prefetch: the layer-0 exchange payload is the
        # (pre-scaled) input features — parameter-independent — so it
        # can be issued at the very top of the step, overlapping the
        # previous epoch's tail inside a fused block. use_pp has no
        # layer-0 exchange at all.
        prefetch = (pipeline and bool(getattr(tcfg, "comm_prefetch", False))
                    and not cfg.use_pp and 0 in glayers)
        # wire-integrity checksum lane (parallel/halo.py guard=True):
        # every pipelined ring payload (halo features forward, boundary
        # grads back) ships a sender-side checksum through the same
        # permute; receiver mismatches surface as the per-epoch
        # `wire_bad` metric fit() turns into carry-flush recovery. A
        # trace-time gate like the tripwire: off (the default) compiles
        # the byte-identical pre-integrity program. Pipelined mode only
        # — the vanilla exchange is differentiated and its payloads are
        # re-verified by the desync detector instead.
        wire_guard = (pipeline and
                      int(getattr(tcfg, "integrity_check_every", 0)) > 0)

        def step(state, data, rng, scale):
            # strip the leading size-1 device axis of sharded blocks
            d = {k: v[0] for k, v in data.items()}
            comm = {
                grp: {k: v[0] for k, v in bufs.items()}
                for grp, bufs in state["comm"].items()
            }
            params, opt, norm = state["params"], state["opt"], state["norm"]
            rank = jax.lax.axis_index(PARTS_AXIS)
            rng = jax.random.fold_in(rng, rank)
            psum = lambda x: jax.lax.psum(x, PARTS_AXIS)

            fresh_halo: Dict[str, jax.Array] = {}
            wire_bad: list = []  # per-exchange checksum-mismatch counts

            cdt = cfg.compute_dtype
            if pipeline:
                # probes must be marked device-varying: their cotangents
                # (the per-device halo grads) vary over the mesh axis
                probes = {
                    str(i): jax.lax.pcast(
                        jnp.zeros((H, self._layer_width(i)), cdt),
                        PARTS_AXIS, to="varying",
                    )
                    for i in glayers
                }

                if prefetch:
                    # issue the layer-0 ring collective before any
                    # loss/grad work: its payload is the (gcn-scaled)
                    # input features, reproduced here exactly as the
                    # forward presents them to comm_update(0, ·)
                    with jax.named_scope("halo_prefetch"):
                        h0 = d["feat"].astype(cdt)
                        if cfg.model == "gcn":
                            ds0 = jnp.sqrt(d["in_deg"].astype(jnp.float32))
                            h0 = (h0.astype(jnp.float32)
                                  / ds0[: h0.shape[0], None]).astype(cdt)
                        out = exchange_blocks(
                            h0, d["send_idx"], d["send_mask"],
                            PARTS_AXIS, P, transport_dt=feat_dt,
                            guard=wire_guard,
                        )
                        if wire_guard:
                            out, wb = out
                            wire_bad.append(wb)
                        fresh_halo["0"] = out

                def comm_update(i, h):
                    k = str(i)
                    stale_halo = (
                        comm["favg"][k].astype(cdt) if tcfg.feat_corr
                        else comm["halo"][k]
                    )
                    stale_bgrad = (
                        comm["bavg"][k].astype(cdt) if tcfg.grad_corr
                        else comm["bgrad"][k]
                    )
                    if ls_on:
                        # the carry stores UNSCALED boundary grads (the
                        # scale can change between the epoch that ships
                        # them and the one that consumes them); rescale
                        # into this epoch's scaled-cotangent frame
                        stale_bgrad = (stale_bgrad.astype(jnp.float32)
                                       * scale).astype(cdt)
                    op = make_stale_concat(d["send_idx"], d["send_mask"], n_max)
                    fbuf = op(h, stale_halo, stale_bgrad, probes_in[k])
                    # this epoch's exchange, consumed next epoch; aux
                    # only. Layer 0's was already issued at step top
                    # when prefetching (identical payload).
                    if k not in fresh_halo:
                        out = exchange_blocks(
                            jax.lax.stop_gradient(h), d["send_idx"],
                            d["send_mask"], PARTS_AXIS, P,
                            transport_dt=feat_dt, guard=wire_guard,
                        )
                        if wire_guard:
                            out, wb = out
                            wire_bad.append(wb)
                        fresh_halo[k] = out
                    return fbuf
            else:
                probes = {}

                def comm_update(i, h):
                    return halo_exchange(
                        h, d["send_idx"], d["send_mask"], PARTS_AXIS, P
                    )

            spmm_fn = self.make_device_spmm_closure(d)
            gat_fn = self.make_device_gat_closure(d)

            def loss_fn(params, probes_arg):
                nonlocal probes_in
                probes_in = probes_arg
                # numerics tripwire (resilience/numerics.py): per-phase
                # non-finite element counts, collected by the forward's
                # probe hook and returned as aux — the provenance the
                # sentinel's fault record names on a NaN trip. Seeded
                # with a device-varying zero: a phase this config never
                # probes would otherwise be an unvarying constant and
                # the psum below would trip shard_map's VMA check.
                vz = (d["row_mask"][0] * 0.0).astype(jnp.int32)
                counts = {ph: vz for ph in PHASES}

                def nf_probe(name, x):
                    counts[name] = counts[name] + jnp.sum(
                        ~jnp.isfinite(x), dtype=jnp.int32)

                logits, new_norm = forward(
                    params, cfg, d["feat"], d["edge_src"], d["edge_dst"],
                    d["in_deg"], n_max, training=True, rng=rng,
                    comm_update=comm_update, norm_state=norm, psum=psum,
                    row_mask=d["row_mask"], spmm_fn=spmm_fn,
                    gat_fn=gat_fn,
                    probe=nf_probe if tripwire else None,
                )
                if multilabel:
                    loss = bce_logits_sum(logits, d["label"], d["train_mask"])
                else:
                    loss = cross_entropy_sum(logits, d["label"],
                                             d["train_mask"])
                if tripwire:
                    counts["loss"] = counts["loss"] + jnp.sum(
                        ~jnp.isfinite(loss), dtype=jnp.int32)
                # loss scaling happens HERE so every cotangent of this
                # trace (param grads AND probe/halo cotangents) carries
                # the scale; the reduction below divides it back out
                sc_loss = loss * scale if ls_on else loss
                return sc_loss, (new_norm, counts, loss)

            probes_in = probes
            (_, (new_norm, nf_counts, loss)), grads = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(params, probes)
            pgrads, probe_grads = grads

            # gradient reduction: psum of sum-loss grads / global n_train
            # (reference reducer.py:24-31 semantics, minus the threads)
            with named_phase("grad_reduce"):
                pgrads = jax.tree_util.tree_map(
                    lambda g: psum(g) / n_train, pgrads)
                if ls_on:
                    pgrads = jax.tree_util.tree_map(
                        lambda g: g / scale, pgrads)
            # global l2 norm of the reduced gradient (telemetry; the
            # grads are replicated post-psum, so this is the true
            # distributed gradient's norm, not a per-device slice's)
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree_util.tree_leaves(pgrads)))
            # non-finite count over the REDUCED gradient: the tripwire's
            # 'grads' phase and (under loss scaling) the overflow flag
            # driving the in-graph step-skip
            if tripwire or ls_on:
                gbad = sum(
                    jnp.sum(~jnp.isfinite(g), dtype=jnp.int32)
                    for g in jax.tree_util.tree_leaves(pgrads))
            if tripwire:
                # forward-phase counts are per-device partials; psum
                # makes them the global counts (replicated, like the
                # loss metric). The grads count is post-psum already.
                nf_counts = {k: psum(v) for k, v in nf_counts.items()}
                nf_counts["grads"] = gbad
            with named_phase("adam_update"):
                new_params, new_opt = adam_update(
                    pgrads, opt, params, lr=tcfg.lr,
                    weight_decay=tcfg.weight_decay,
                )
            if ls_on:
                # overflow-skip: a non-finite reduced gradient anywhere
                # keeps params/opt at their previous values — the
                # skipped step costs one epoch, not the run. The host
                # state machine (fit() + LossScaler) sees the flag and
                # backs the scale off.
                ls_ok = gbad == 0
                sel = lambda n, o: jnp.where(ls_ok, n, o)
                new_params = jax.tree_util.tree_map(sel, new_params,
                                                    params)
                new_opt = jax.tree_util.tree_map(sel, new_opt, opt)

            new_comm = {}
            if pipeline:
                new_comm = {"halo": {}, "bgrad": {}}
                if tcfg.feat_corr:
                    new_comm["favg"] = {}
                if tcfg.grad_corr:
                    new_comm["bavg"] = {}
                for i in glayers:
                    k = str(i)
                    new_comm["halo"][k] = fresh_halo[k]
                    # ship this epoch's halo cotangents to their owners
                    bg = return_blocks(probe_grads[k], PARTS_AXIS, P,
                                       b_max, transport_dt=bgrad_dt,
                                       guard=wire_guard)
                    if wire_guard:
                        bg, wb = bg
                        wire_bad.append(wb)
                    if ls_on:
                        # probe cotangents carry this epoch's loss
                        # scale; the carry stores them UNSCALED (see
                        # comm_update's rescale on consumption)
                        bg = (bg.astype(jnp.float32) / scale).astype(
                            bg.dtype)
                    new_comm["bgrad"][k] = bg
                    if tcfg.feat_corr:
                        new_comm["favg"][k] = (
                            momentum * comm["favg"][k]
                            + (1 - momentum) * fresh_halo[k]
                        )
                    if tcfg.grad_corr:
                        new_comm["bavg"][k] = (
                            momentum * comm["bavg"][k] + (1 - momentum) * bg
                        )
                new_comm = {
                    grp: {k: v[None] for k, v in bufs.items()}
                    for grp, bufs in new_comm.items()
                }

            loss_out = psum(loss) / n_train
            new_state = {
                "params": new_params,
                "opt": new_opt,
                "norm": new_norm,
                "comm": new_comm,
            }
            m = {"loss": loss_out, "grad_norm": gnorm}
            if tripwire:
                m["numerics"] = nf_counts
            if ls_on:
                m["overflow"] = (gbad > 0).astype(jnp.int32)
            if wire_guard:
                local_bad = sum(wire_bad) if wire_bad \
                    else jnp.zeros((), jnp.int32)
                m["wire_bad"] = psum(local_bad)
            return new_state, m

        if self.emulated:
            # vmap(axis_name) in place of shard_map: identical step
            # function, parts as a batch axis on one device. The step
            # strips a leading size-1 device axis from data/comm and
            # re-adds it to new comm, so the wrapper reintroduces it
            # around the vmapped slice.
            tm = jax.tree_util.tree_map

            def vstep(state, data, rng, scale):
                st = dict(state)
                st["comm"] = tm(lambda v: v[None], state["comm"])
                d1 = tm(lambda v: v[None], data)
                ns, m = step(st, d1, rng, scale)
                ns["comm"] = tm(lambda v: v[0], ns["comm"])
                return ns, m

            vm = jax.vmap(vstep, in_axes=(0, 0, None, None), out_axes=0,
                          axis_name=PARTS_AXIS)

            def emu(state, data, rng, scale):
                ns, m = vm(state, data, rng, scale)
                # psum'd: identical across parts
                return ns, tm(lambda v: v[0], m)

            def emu_multi(state, data, rngs, scale):
                def body(st, rng):
                    return emu(st, data, rng, scale)

                return jax.lax.scan(body, state, rngs)

            self._multi_step = jax.jit(emu_multi, donate_argnums=(0,))
            return jax.jit(emu, donate_argnums=(0,))

        data_spec = jax.tree_util.tree_map(
            lambda _: PartitionSpec(PARTS_AXIS), self.data
        )
        state_spec = {
            "params": jax.tree_util.tree_map(
                lambda _: PartitionSpec(), self.state["params"]
            ),
            "opt": jax.tree_util.tree_map(
                lambda _: PartitionSpec(), self.state["opt"]
            ),
            "norm": jax.tree_util.tree_map(
                lambda _: PartitionSpec(), self.state["norm"]
            ),
            "comm": jax.tree_util.tree_map(
                lambda _: PartitionSpec(PARTS_AXIS), self.state["comm"]
            ),
        }
        # every step metric is a replicated scalar (post-psum); the
        # tripwire counts and overflow flag ride the same contract
        metric_spec = {"loss": PartitionSpec(), "grad_norm": PartitionSpec()}
        if tripwire:
            metric_spec["numerics"] = {ph: PartitionSpec()
                                       for ph in PHASES}
        if ls_on:
            metric_spec["overflow"] = PartitionSpec()
        if wire_guard:
            metric_spec["wire_bad"] = PartitionSpec()
        smapped = jax.shard_map(
            step,
            mesh=self.mesh,
            in_specs=(state_spec, data_spec, PartitionSpec(),
                      PartitionSpec()),
            out_specs=(state_spec, metric_spec),
        )

        def multi(state, data, rngs, scale):
            # k epochs in one compiled program: one dispatch, and XLA can
            # schedule epoch e+1's independent work (e.g. next halo
            # exchange) behind epoch e's tail
            def body(st, rng):
                return step(st, data, rng, scale)

            return jax.lax.scan(body, state, rngs)

        smapped_multi = jax.shard_map(
            multi,
            mesh=self.mesh,
            in_specs=(state_spec, data_spec, PartitionSpec(),
                      PartitionSpec()),
            out_specs=(state_spec, metric_spec),
        )
        self._multi_step = jax.jit(smapped_multi, donate_argnums=(0,))
        return jax.jit(smapped, donate_argnums=(0,))

    # ---------------- public API --------------------------------------

    def _epoch_rng_base(self) -> jax.Array:
        # single source of the per-run base key: train_epoch and
        # train_epochs MUST fold epochs from the same base so fused and
        # unfused runs are bit-identical
        if self.tcfg.rng_impl != "threefry":
            return jax.random.key(self.tcfg.seed + 17,
                                  impl=self.tcfg.rng_impl)
        return jax.random.PRNGKey(self.tcfg.seed + 17)

    def _epoch_rng_fold(self, epoch):
        """The value folded into the base key for `epoch` (host int or
        traced). dropout_reuse=N>1 maps N consecutive epochs onto one
        fold value, so they draw the SAME dropout masks and the RNG
        bits are generated once per N epochs after CSE inside a fused
        block — the mask-reuse floor lever. 0/1 = fresh every epoch."""
        reuse = int(getattr(self.tcfg, "dropout_reuse", 0) or 0)
        return epoch // reuse if reuse > 1 else epoch

    # ---------------- kernel fallback dispatch guard -------------------

    def _current_impl(self) -> str:
        """The aggregation kernel the step is currently built on (the
        RESOLVED impl — 'auto' never survives _setup_spmm)."""
        if self._block_tables is not None:
            return "block"
        if self._bucket_tables is not None:
            return "bucket"
        if self._gat_tables is not None:
            return "gat-bucket"
        return "xla"

    def _slab_active(self) -> bool:
        """True when the current kernel tables carry slab-gather run
        plans (bkt_*res_/blkrem_*res_ keys) — the fallback ladder then
        has an extra rung ABOVE the impl downgrade: same kernel, slab
        plans stripped (cfg.slab='off'), so a dynamic_slice-path crash
        does not cost the whole bucket/block kernel."""
        for t in (self._bucket_tables, self._block_tables):
            if t is not None and any("res_" in k for k in t):
                return True
        return False

    def downgrade_kernel(self, to_impl: str, reason: str) -> dict:
        """Rebuild the trainer one rung down the kernel fallback ladder
        (resilience/numerics.fallback_ladder): swap the kernel tables on
        device, restore the raw edge list if the new impl needs it, and
        rebuild the jitted step. The trainer's state (params/opt/comm)
        is untouched — the caller restores it from a host snapshot when
        the failed dispatch may have poisoned donated buffers. Returns
        the fallback record (also appended to self.fallbacks for fit()
        / bench to emit as a contracted `fallback` metrics record)."""
        frm = self._current_impl()
        self.cfg = dataclasses.replace(self.cfg, spmm_impl=to_impl)
        self._eval_cfg = dataclasses.replace(self._eval_cfg,
                                             spmm_impl=to_impl)
        self._setup_spmm()
        keep = {k: v for k, v in self.data.items()
                if not k.startswith(("bkt_", "blk_", "blkrem_", "gat_"))}
        tables_active = False
        for t in (self._bucket_tables,
                  self._block_tables, self._gat_tables):
            if t is not None:
                tables_active = True
                for k, v in t.items():
                    keep[k] = jax.device_put(jnp.asarray(v), self._shard)
        if not tables_active and self._edges_trimmed:
            # the raw-edge XLA path needs the real edge list the table
            # kernels let the trainer trim to a token shape
            keep["edge_src"] = jax.device_put(
                jnp.asarray(np.asarray(self.sg.edge_src,
                                       dtype=np.int32)), self._shard)
            keep["edge_dst"] = jax.device_put(
                jnp.asarray(np.asarray(self.sg.edge_dst,
                                       dtype=np.int32)), self._shard)
        self._edges_trimmed = tables_active
        self.data = keep
        self._step = self._build_step()
        self._kernel_proven = False
        rec = {"from_impl": frm, "to_impl": self._current_impl(),
               "epoch": int(getattr(self, "last_epoch", 0)),
               "reason": reason, "emitted": False}
        self.fallbacks.append(rec)
        return rec

    def _dispatch(self, run_fn):
        """Run one step dispatch under the kernel fallback ladder: a
        compile-or-first-dispatch failure that looks like a
        kernel/backend error (numerics.is_kernel_error) downgrades the
        kernel and retries the same dispatch from a host snapshot,
        instead of killing the run (VERDICT r5: the block kernel
        hard-crashed the TPU backend at products shape with no
        fallback). Once a kernel has survived one dispatch it is
        'proven' and the guard (and its snapshot copy) costs nothing.
        Multi-process runs skip the guard: a unilateral downgrade would
        desync the SPMD program — there the crash propagates to the
        coordinated recovery paths instead."""
        from ..resilience.numerics import (KernelFallbackError,
                                           fallback_ladder,
                                           is_kernel_error)

        inject = self._inject_kernel_crash
        armed = ((not self._kernel_proven or inject)
                 and jax.process_count() == 1
                 and (inject or fallback_ladder(self._current_impl())
                      or self._slab_active()))
        if not armed:
            # multi-process / ladder-exhausted: the injection flag must
            # not survive to poison an unrelated later dispatch
            self._inject_kernel_crash = False
            out = run_fn()
            self._kernel_proven = True
            return out
        snap = self.host_state()
        while True:
            if self._inject_kernel_crash:
                self._inject_kernel_crash = False
                err: BaseException = RuntimeError(
                    "fault-injected kernel dispatch failure "
                    "(INTERNAL: TPU backend error)")
            else:
                try:
                    out = run_fn()
                    self._kernel_proven = True
                    return out
                except Exception as exc:  # noqa: BLE001 — classified below
                    if not is_kernel_error(exc):
                        raise
                    err = exc
            if self._slab_active():
                # first rung: same kernel, slab plans stripped — the
                # streaming dynamic_slice path is the newest code and
                # the cheapest thing to give up
                self.cfg = dataclasses.replace(self.cfg, slab="off")
                self._eval_cfg = dataclasses.replace(self._eval_cfg,
                                                     slab="off")
                self.downgrade_kernel(self._current_impl(),
                                      "slab-off: " + repr(err)[:280])
                self.restore_state(snap)
                continue
            rungs = fallback_ladder(self._current_impl())
            if not rungs:
                raise KernelFallbackError(
                    f"aggregation kernel {self._current_impl()!r} failed "
                    f"with no fallback rung left: {err!r}") from err
            self.downgrade_kernel(rungs[0], repr(err)[:300])
            # the failed dispatch may have consumed the donated state
            # buffers; re-place the pre-dispatch snapshot
            self.restore_state(snap)

    def train_epoch(self, epoch: int) -> float:
        rng = jax.random.fold_in(self._epoch_rng_base(),
                                 self._epoch_rng_fold(epoch))
        scale = jnp.float32(self.loss_scaler.scale)
        self.state, m = self._dispatch(
            lambda: self._step(self.state, self.data, rng, scale))
        # per-step telemetry (loss + grad norm, scalars) for fit()'s
        # metrics sink; train_epochs stores the [k]-array equivalents
        self._last_metrics = m
        loss = m["loss"]
        # last_epoch labels the buffers self.state now references (the
        # previous state was DONATED into the dispatch, so there is no
        # older state to fall back to). If the dispatch failed, these
        # buffers are poisoned and the crash handler's device_get raises
        # — it then skips the save rather than writing a wrong pair; if
        # it succeeded (even with the host interrupted during the
        # blocking float() below), state and label are consistent and a
        # resume neither skips nor repeats an epoch.
        self.last_epoch = epoch + 1
        return float(loss)

    def train_epochs(self, start_epoch: int, k: int) -> np.ndarray:
        """Run epochs [start_epoch, start_epoch + k) as ONE compiled
        program (lax.scan over the step). Identical numerics to k
        train_epoch calls — same per-epoch rng fold — but a single
        dispatch, so host round-trip cost is amortized k-fold and XLA
        may overlap across epoch boundaries. Returns the k losses."""
        base = self._epoch_rng_base()
        rngs = jax.vmap(
            lambda e: jax.random.fold_in(base, self._epoch_rng_fold(e)))(
            jnp.arange(start_epoch, start_epoch + k)
        )
        scale = jnp.float32(self.loss_scaler.scale)
        self.state, ms = self._dispatch(
            lambda: self._multi_step(self.state, self.data, rngs, scale))
        # ONE host sync for the whole block: pull every [k]-metric in a
        # single device_get instead of per-array transfers when fit()
        # later indexes loss/grad_norm/numerics per epoch (the megastep
        # harvest half of the dispatch-amortization lever)
        ms = jax.device_get(ms)
        self._last_metrics = ms  # [k] numpy arrays; see train_epoch
        self.last_epoch = start_epoch + k  # see train_epoch
        return np.asarray(ms["loss"])

    def host_state(self) -> Dict[str, Any]:
        """Host-side copy of the full training state — the form the
        sentinel snapshots, checkpoints and resume templates use.
        Single-process: a plain device_get. Multi-process: the sharded
        comm carry spans non-addressable devices (device_get raises),
        so its global value is reassembled with an allgather — which
        makes this a COLLECTIVE there: call it at the same program
        point on every process (fit() only does so at lockstep
        dispatch boundaries)."""
        if jax.process_count() == 1:
            return jax.device_get(self.state)
        out = {k: jax.device_get(self.state[k])
               for k in ("params", "opt", "norm")}
        comm = self.state["comm"]
        if comm:
            from jax.experimental import multihost_utils

            out["comm"] = jax.tree_util.tree_map(
                np.asarray, multihost_utils.process_allgather(comm))
        else:
            out["comm"] = {}
        return out

    def local_partition_ids(self) -> list:
        """Global partition ids whose carry rows THIS process's devices
        own under the mesh's process-major device order. This is the
        elastic-membership redistribution mechanism in one line:
        relaunching with a different world size moves these ids, and
        restore_state re-device_puts the checkpointed FULL [P, ...]
        carry under the new shardings — partition i's rows land on
        whoever owns partition i now (resilience/elastic.py)."""
        if jax.process_count() == 1:
            return list(range(self.P))
        pid = jax.process_index()
        return [i for i, d in enumerate(self.mesh.devices.flat)
                if i < self.P and d.process_index == pid]

    def restore_state(self, host_state: Dict[str, Any]) -> None:
        """Device-place a host-side state pytree (a checkpoint load or
        a sentinel last-good snapshot) with the trainer's shardings —
        the one way to put external state back under the donated-buffer
        step. Works identically for emulated trainers (their stacked
        [P, ...] replicas ride the single-device shardings).

        The comm carry is validated to span the FULL partition count
        first: checkpoints always store all P rows (host_state's
        allgather), which is exactly what makes an elastic resume
        world-size independent — a partial carry means the caller
        sliced per-rank state (use utils.checkpoint's
        load_checkpoint_carry for that) and restoring it would
        scatter the wrong partitions onto the mesh."""
        def _check(path, a):
            shape = np.shape(a)
            if shape and shape[0] != self.P:
                raise ValueError(
                    f"comm carry leaf {jax.tree_util.keystr(path)} has "
                    f"leading dim {shape[0]}, expected the full "
                    f"partition count {self.P}: elastic restores need "
                    f"the complete [P, ...] carry (this process now "
                    f"owns partitions {self.local_partition_ids()})")
            return a

        jax.tree_util.tree_map_with_path(_check, host_state["comm"])
        self.state = {
            "params": jax.device_put(host_state["params"], self._repl),
            "opt": jax.device_put(host_state["opt"], self._repl),
            "norm": jax.device_put(host_state["norm"], self._repl),
            "comm": jax.device_put(host_state["comm"], self._shard),
        }

    def reset_comm(self) -> None:
        """Zero the pipelined comm carry: the next epoch consumes zero
        halos exactly like epoch 0, restarting the staleness-1 warmup.
        The sentinel's 'flush' action — stale boundary data produced by
        a divergent trajectory never re-enters the retried epochs."""
        self.state = dict(self.state)
        self.state["comm"] = jax.device_put(self._init_comm(), self._shard)

    def set_lr(self, lr: float) -> None:
        """Change the learning rate mid-run. The LR is a trace-time
        constant of the jitted step, so this rebuilds the step (one
        recompile per change — the sentinel's backoff path, where a
        recompile per rare trip is the right trade against threading a
        traced scalar through every healthy epoch)."""
        self.tcfg = dataclasses.replace(self.tcfg, lr=float(lr))
        self._step = self._build_step()

    def fit(
        self,
        eval_graphs: Optional[Dict[str, Tuple[Graph, str]]] = None,
        log_fn=print,
        *,
        start_epoch: int = 0,
        reference_logs: bool = False,
        result_file: Optional[str] = None,
        inductive: bool = False,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 100,
        checkpoint_keep: int = 3,
        checkpoint_fallback_dir: Optional[str] = None,
        profile_dir: Optional[str] = None,
        profile_epochs: Optional[Tuple[int, int]] = None,
        staleness_probe_every: int = 0,
        measure_comm_cost: bool = False,
        sharded_eval: bool = False,
        async_eval: bool = True,
        metrics=None,
        sentinel=None,
        preemption=None,
        fault_plan=None,
        coord=None,
        stream_plan=None,
        journal=None,
    ) -> Dict[str, Any]:
        """The single epoch loop (reference train.py:327-400): periodic
        evaluation, best-val/BN-stats tracking, timing with <5-epoch
        warmup exclusion, and — for the CLI — reference-format log lines
        with measured Comm/Reduce collective costs, result files
        (train.py:33-39/54-60 formats), jax.profiler traces, and
        periodic checkpointing.

        `eval_graphs` maps split name -> (graph, mask key); must contain
        'val' (and usually 'test').

        `async_eval=True` (default) keeps evaluation off the critical
        path the way the reference's background eval thread does
        (train.py:327-328, 377-389): the eval computation is dispatched
        (with a device-side snapshot of params/BN stats) and its scalar
        is harvested at the NEXT log boundary, so the epoch loop never
        blocks on eval. Log lines/history for epoch e therefore appear
        one log period later; best-val tracking uses the snapshot, like
        the reference's deep-copied model (train.py:383).

        `sharded_eval=True` evaluates through the training mesh
        (parallel/evaluator.py) instead of one device — required when
        the full eval graph exceeds a single device's memory.

        `metrics` (an obs.MetricsLogger or None) appends structured
        JSONL telemetry: a run header (written here only if the caller
        has not already written a richer one), one record per epoch
        (step time, loss, grad norm, halo bytes, staleness age, HBM
        watermarks), one record per harvested evaluation, and a final
        run summary — the schema in obs/schema.py and
        docs/OBSERVABILITY.md. The sink never changes the log_fn
        stream: --reference-logs output stays byte-identical.

        Resilience (docs/RESILIENCE.md):

        `sentinel` (resilience.DivergenceSentinel or None) checks every
        dispatched block's loss/grad-norm; on trip, fit restores the
        last good in-memory snapshot, scales the LR down, optionally
        flushes the pipelined comm carry, and retries — bounded by the
        sentinel's max_retries, then DivergenceError. Fault/recovery
        records ride the metrics sink.

        `preemption` (resilience.PreemptionHandler or None) is polled
        at each dispatch boundary; a shutdown request checkpoints via
        the crash handler (rank-0 save) and raises Preempted, which the
        CLI maps to the resumable exit status EXIT_PREEMPTED.

        `fault_plan` (resilience.FaultPlan or None) injects
        deterministic host-side faults into the harvested metrics, the
        epoch boundary, and the checkpoint path — chaos testing only;
        the compiled device program is never altered.

        `coord` (resilience.Coordinator or None) makes every recovery
        decision above a cross-rank AGREEMENT in jax.distributed runs:
        at each dispatch boundary the ranks OR-reduce a small fault
        word (one tiny jitted psum), so a sentinel trip or preemption
        request on ANY rank executes its rollback / checkpoint+exit on
        ALL ranks in lockstep — a unilateral action would deadlock the
        next collective. The coordinator also arms the heartbeat
        watchdog (silent peers raise PeerLost instead of hanging the
        pod) and the param-digest desync detector. An inactive
        (single-process) coordinator degenerates to no-ops, so this
        path is identical to coord=None.

        `checkpoint_keep` bounds the on-disk checkpoint generations
        (keep-last-N; utils/checkpoint.py rotation).

        `checkpoint_fallback_dir` names a second directory (ideally a
        different volume) to save into when a checkpoint write into
        `checkpoint_dir` fails with OSError. With or without it, a
        failed periodic save degrades loudly instead of aborting the
        run: an ``io-degraded`` fault record is emitted, the previous
        on-disk generation stays the authoritative resume point, and
        the save is retried with FRESH state at subsequent epoch
        boundaries until one lands (``io-degraded`` recovery record).

        Profiling (docs/OBSERVABILITY.md "Profiling"):

        `profile_epochs=(A, B)` with `profile_dir` captures a
        ``jax.profiler`` device trace around the dispatched blocks of
        epochs [A, B) (epoch-granular inside the window), then folds
        the captured trace against the step's compiled HLO into a
        contracted ``profile`` record: MEASURED per-phase device time
        (spmm / dense / halo collectives / optimizer / ...) and the
        measured comm/compute overlap fraction — the quantity the
        report CLI previously only estimated. Without `profile_epochs`
        the legacy auto-window (epochs start+6..start+8) applies, and
        the same analysis runs on it. The record rides the metrics
        sink and the returned result dict ("profile").

        `stream_plan` (stream.StreamPlan or None) applies graph delta
        batches at their scheduled epoch boundaries via
        apply_graph_deltas (enable_stream must have been called).
        Fused blocks are clamped so no block straddles a scheduled
        delta, delta epochs run unfused with a forced staleness probe
        (the probe's drift IS the per-delta drift measurement), and
        each application emits a contracted ``stream`` record
        (docs/STREAMING.md).

        `staleness_probe_every=N` (pipelined mode only) measures, every
        N epochs, the per-layer relative drift between the STALE
        boundary features the step consumed and the FRESH ones it
        shipped: ``||h_stale - h_fresh|| / ||h_fresh||``. The stale
        buffers are snapshotted before the dispatch (they are donated
        into it) and compared against the post-step carry — exact, and
        the only cost is one halo-buffer copy + a small jitted norm
        program on probe epochs. Emits a ``staleness`` record per
        probe; probe epochs dispatch unfused (chunk=1)."""
        from ..utils.checkpoint import save_checkpoint

        tcfg = self.tcfg
        if metrics is not None and not metrics.header_written:
            # direct-API callers (tests, bench) get a header derived
            # from the trainer's own config; the CLI writes its richer
            # args-level header before calling fit()
            metrics.run_header(
                config={"model": dataclasses.asdict(self.cfg),
                        "train": dataclasses.asdict(self.tcfg)},
                device=device_info(), mesh=mesh_info(self.mesh))
        # ---- tuner decision (set at _setup_spmm for spmm_impl='auto'):
        # surface WHY this kernel dispatches, once per run ----
        if getattr(self, "tuning", None) is not None and \
                not self.tuning.get("emitted"):
            self.tuning["emitted"] = True
            w = self.tuning["winner"]
            log_fn(f"spmm auto-tuner: kernel={w['name']} "
                   f"(source={self.tuning['source']}"
                   + (f", {self.tuning['stale_reason']}"
                      if self.tuning.get("stale_reason") else "")
                   + ")")
            if metrics is not None:
                metrics.tuning(
                    winner=dict(w), source=self.tuning["source"],
                    stale_reason=self.tuning.get("stale_reason"),
                    costs=self.tuning.get("costs", []))
        halo_bytes = self.est_halo_bytes_per_epoch()
        # with --halo-dtype compression active, record the uncompressed
        # figure alongside so the report can print the wire ratio
        halo_unc = self.est_halo_bytes_per_epoch(compressed=False)
        halo_extra = ({"halo_bytes_uncompressed": halo_unc}
                      if halo_unc != halo_bytes else {})
        best_val, best_params, best_norm, best_epoch = 0.0, None, None, -1
        durs = []
        eval_durs = []
        history = []
        pending = None  # dispatched-but-unharvested evaluation

        def _dispatch_eval(at_epoch, at_loss, at_dur):
            handles = {}
            for split in ("val",) if (inductive or not reference_logs) \
                    else ("val", "test"):
                if split in eval_graphs:
                    g, mask = eval_graphs[split]
                    handles[split] = self.eval_dispatch(
                        g, mask, sharded=sharded_eval)
            if async_eval:
                # device-side copies: best-val harvesting needs the
                # params AS OF dispatch time (the reference deep-copies
                # the model into its eval thread, train.py:383)
                snap_p = jax.tree_util.tree_map(
                    jnp.copy, self.state["params"])
                snap_n = jax.tree_util.tree_map(jnp.copy, self.state["norm"])
            else:
                snap_p, snap_n = self.state["params"], self.state["norm"]
            return {"epoch": at_epoch, "loss": at_loss, "dur": at_dur,
                    "handles": handles, "snap_p": snap_p, "snap_n": snap_n}

        def _harvest_eval(p):
            nonlocal best_val, best_params, best_norm, best_epoch
            # plain perf_counter: an epoch boundary can harvest AND run
            # a sync eval in one iteration, so no phase key fits
            t0 = time.perf_counter()
            acc = self.eval_finish(p["handles"]["val"])
            eval_wait = time.perf_counter() - t0
            eval_durs.append(eval_wait)
            e = p["epoch"]
            eval_extra = {}
            if reference_logs:
                if inductive:
                    buf = reference_eval_line(e, acc)
                else:
                    t_acc = self.eval_finish(p["handles"]["test"])
                    buf = reference_eval_line(e, acc, t_acc)
                    eval_extra["test_acc"] = float(t_acc)
                if result_file:
                    with open(result_file, "a+") as f:
                        f.write(buf + "\n")
                log_fn(buf)
            else:
                log_fn(epoch_line(e + 1,
                                  float(np.mean(durs or [p["dur"]])),
                                  p["loss"], acc))
            if metrics is not None:
                metrics.eval_record(e, eval_wait, float(acc),
                                    **eval_extra)
            if tspan is not None:
                tspan.eval_span(e, eval_wait)
            history.append((e + 1, p["loss"], acc))
            if acc > best_val:
                best_val = acc
                best_epoch = e + 1
                # snapshot BN running stats with the params (the
                # reference deep-copies the whole model incl. buffers,
                # train.py:383)
                best_params = jax.device_get(p["snap_p"])
                best_norm = jax.device_get(p["snap_n"])
        # "bgrad" present from the start: a resumed run can hit its
        # first reference-log boundary BEFORE the one-shot measurement
        # (start_epoch + 5) and must print zeros, not KeyError
        comm_cost = {"comm": 0.0, "reduce": 0.0, "bgrad": 0.0}
        comm_measured = False
        timer = PhaseTimer()
        # ---- training-span plane (obs/trainspan.py): always-on
        # per-rank spans + tracesync clock anchors into the metrics
        # sink. Host-side only — nothing here touches a traced
        # program, so the zero-recompile pins hold with spans hot ----
        tspan = None
        if metrics is not None and getattr(tcfg, "train_traces", True):
            from ..obs.trainspan import TrainSpanPlane
            tspan = TrainSpanPlane(
                metrics, rank=jax.process_index(),
                generation=(max(coord.cfg.generation, 0)
                            if coord is not None else 0))
        profiling = False
        n_epochs = tcfg.n_epochs
        # ---- profiling window + staleness probes (obs/profiler.py) ----
        prof_window = None
        if profile_epochs is not None:
            a, b = int(profile_epochs[0]), int(profile_epochs[1])
            a, b = max(a, start_epoch), min(b, n_epochs)
            if b > a:
                prof_window = (a, b)
            else:
                log_fn(f"warning: --profile-epochs window "
                       f"{profile_epochs} is outside the run "
                       f"[{start_epoch}, {n_epochs}); no trace captured")
            if not profile_dir:
                log_fn("warning: profile_epochs set without "
                       "profile_dir; no trace captured")
                prof_window = None
        prof_started_at = None   # first epoch inside the live capture
        prof_record = None       # the parsed profile record (result)
        probe_every = max(int(staleness_probe_every), 0)
        if probe_every and not tcfg.enable_pipeline:
            log_fn("warning: staleness probes need --enable-pipeline "
                   "(vanilla exchanges are synchronous — drift is 0 by "
                   "construction); probes disabled")
            probe_every = 0

        def _finish_profile(window):
            """Stop + fold the live capture into a profile record."""
            jax.profiler.stop_trace()
            log_fn(f"profiler trace written to {profile_dir}")
            try:
                body = self._profile_analysis(profile_dir)
            except Exception as exc:  # noqa: BLE001 — telemetry only
                log_fn(f"profile analysis failed: {exc!r}")
                return None
            if body is None:
                log_fn("profile analysis found no parsable trace "
                       "events (backend without Chrome-trace export?)")
                return None
            body["epoch_start"], body["epoch_end"] = window
            log_fn(f"profile window [{window[0]}, {window[1]}): "
                   f"measured overlap "
                   f"{body['overlap_fraction']:.1%} "
                   f"(comm {body['comm_s']:.4f}s device, compute "
                   f"{body['compute_s']:.4f}s)")
            if metrics is not None:
                extras = {k: v for k, v in body.items()
                          if k not in ("phases", "comm_s", "compute_s",
                                       "overlap_fraction")}
                metrics.profile(body["phases"], body["comm_s"],
                                body["compute_s"],
                                body["overlap_fraction"], **extras)
            return body

        # megastep block size: --epoch-block overrides --fused-epochs
        # when set (same scan machinery; the separate knob lets the
        # floor-lever sweep vary block size without touching the
        # numerics-labeled fused_epochs config)
        fused = max(1, int(getattr(tcfg, "epoch_block", 0)
                           or getattr(tcfg, "fused_epochs", 1)))
        # per-epoch work (logs/eval/checkpoint/profiler) happens at these
        # period boundaries; fused blocks must not cross one
        periods = [tcfg.log_every]
        if reference_logs:
            periods.append(10)
        if checkpoint_dir:
            periods.append(checkpoint_every)

        epoch = start_epoch
        seen_chunks = set()  # scan lengths already compiled
        # True while a dispatched-but-unfinished eval occupies the device
        # stream (its time would contaminate the next block's timing)
        eval_in_stream = False
        # ---- resilience state (docs/RESILIENCE.md) ----
        retries = 0          # consecutive sentinel rollbacks
        trip_horizon = None  # first epoch past the last trip: passing it
        #                      healthy = recovered (resets the counter)
        last_good = None     # (epoch, host snapshot) rollback target
        coord_on = coord is not None and coord.active
        # ---- integrity plane (resilience/integrity.py): SDC
        # detectors driven at every boundary (cheap dynamic digests)
        # and at --integrity-check-every cadence (table scrub +
        # Freivalds). Cadence boundaries are period boundaries: a
        # fused block must not straddle one ----
        integ = None
        integ_every = max(int(getattr(
            tcfg, "integrity_check_every", 0) or 0), 0)
        if integ_every > 0:
            from ..resilience.integrity import (
                SDC_CODES, SDC_NAMES, IntegrityPlane,
                request_quarantine)
            integ = IntegrityPlane(integ_every,
                                   rank=jax.process_index(),
                                   log=log_fn)
            periods.append(integ_every)
            integ.baseline(self)
        # a consensus-propagated peer trip (or an SDC params rollback)
        # needs the same rollback machinery whether or not the LOCAL
        # sentinel is armed
        if sentinel is not None or coord_on or integ is not None:
            last_good = (start_epoch, self.host_state())
        snap_every = max(int((sentinel.cfg if sentinel is not None
                              else SentinelConfig()).snapshot_every), 1)
        # ---- storage-fault state (resilience/storage.py) ----
        io_armed: Dict[str, int] = {}  # armed IO kind -> disarm epoch
        ckpt_pending = None  # epoch of a failed periodic save awaiting
        #                      retry; the previous generation stays the
        #                      authoritative resume point until it lands
        # ---- delta-journal state (stream/journal.py) ----
        journal_pending_since = None  # epoch of the first append the
        #                               degraded disk rejected
        last_ckpt_seq = -1  # stream seq the newest checkpoint covers
        if journal is not None and getattr(self, "_stream", None) is None:
            raise ValueError(
                "fit(journal=...) requires enable_stream(patcher): the "
                "journal records applied DeltaBatches")
        if journal is not None:
            # the CLI replays to the checkpoint watermark before fit;
            # everything journaled now is covered by that checkpoint
            last_ckpt_seq = int(self._stream.last_seq)

        def _stream_watermark():
            """Checkpoint extras pairing the state with its topology
            position (None outside streaming runs — zero npz delta)."""
            p = getattr(self, "_stream", None)
            if p is None:
                return None
            return {"__stream_seq__": np.asarray(int(p.last_seq),
                                                 np.int64),
                    "__topo_generation__": np.asarray(
                        int(getattr(self, "topo_generation", 0)),
                        np.int64)}
        if fault_plan is not None:
            # a resumed run gets the same --fault-plan; entries it
            # already lived through must not re-fire
            fault_plan.skip_before(start_epoch)
        if stream_plan is not None and journal is None:
            # LEGACY (journal-less) resume: assume the pre-start_epoch
            # deltas are already in the graph and drop them. With a
            # journal the CLI has already replayed to the checkpoint
            # watermark and called skip_journaled(); every seq past the
            # watermark stays scheduled, whatever its epoch — the WAL
            # rollback re-delivers it at the boundary it belongs to.
            stream_plan.skip_before(start_epoch)
        if coord is not None:
            coord.start()
            coord.set_checkpoint(checkpoint_dir, checkpoint_keep)
            coord.note_progress(start_epoch)
            if last_good is not None:
                coord.note_snapshot(*last_good)
            if coord_on and coord.cfg.desync_every > 0:
                # digest agreement is a collective: every rank must
                # reach it at the same epoch, so fused blocks must not
                # straddle the cadence boundary
                periods.append(coord.cfg.desync_every)
        # ---- flight recorder (obs/flight.py): host-side breadcrumbs,
        # on by default, zero effect on traced programs. The stall
        # detector is the opt-in sub-watchdog forensics thread
        # (PIPEGCN_STALL_S seconds of breadcrumb silence -> stack dump
        # WITHOUT dying); the hang@E:<ms> fault exercises it ----
        frec = flightrec.get_recorder()
        frec.crumb("fit-start", epoch=start_epoch, n_epochs=n_epochs)
        stall_det = None
        try:
            stall_s = float(os.environ.get("PIPEGCN_STALL_S", "0") or 0)
        except ValueError:
            stall_s = 0.0
        if stall_s > 0 and frec.enabled:
            stall_det = flightrec.StallDetector(frec, stall_s).start()
        try:
            while epoch < n_epochs:
                # ---- boundary faults / preemption: the one point where
                # the donated state is consistent and labeled ----
                frec.crumb("boundary", epoch=epoch)
                if coord is not None:
                    coord.note_progress(epoch)
                    # a dead peer can never complete a collective:
                    # raise PeerLost BEFORE dispatching anything
                    coord.check_peers()
                # ---- storage faults: arm/disarm the process-wide IO
                # shim at the boundary. The window closes at the next
                # checkpoint boundary (next epoch when checkpointing is
                # off) so each run exercises BOTH the degradation and
                # the recovery side of every writer's policy ----
                for kind, until in list(io_armed.items()):
                    if epoch >= until:
                        FAULTY_IO.disarm(kind)
                        del io_armed[kind]
                        log_fn(f"storage fault {kind} window closed at "
                               f"epoch {epoch}")
                if fault_plan is not None:
                    for kind in IO_KINDS:
                        arg = fault_plan.due_arg(kind, epoch)
                        if arg is None:
                            continue
                        FAULTY_IO.arm(kind, ms=arg)
                        io_armed[kind] = (epoch + checkpoint_every
                                          if checkpoint_dir else epoch + 1)
                        log_fn(f"fault-injected {kind} at epoch {epoch} "
                               f"(window closes at epoch "
                               f"{io_armed[kind]})")
                        if metrics is not None:
                            metrics.fault(kind="injected", epoch=epoch,
                                          reason=kind)
                if (ckpt_pending is not None and checkpoint_dir
                        and jax.process_count() == 1):
                    # retry the failed periodic save with FRESH state.
                    # Multi-process runs retry at the next checkpoint
                    # boundary instead: host_state() is a lockstep
                    # allgather, and only rank 0 knows a save failed
                    try:
                        save_checkpoint(checkpoint_dir,
                                        self.host_state(), epoch,
                                        keep=checkpoint_keep)
                    except OSError as io_exc:
                        log_fn(f"checkpoint retry at epoch {epoch} "
                               f"still failing ({io_exc!r})")
                    else:
                        log_fn(f"checkpoint save recovered at epoch "
                               f"{epoch} (pending since epoch "
                               f"{ckpt_pending})")
                        if metrics is not None:
                            metrics.recovery(kind=IO_DEGRADED,
                                             epoch=epoch,
                                             pending_since=ckpt_pending)
                        ckpt_pending = None
                # ---- SDC chaos + detection (resilience/integrity):
                # inject scheduled bit flips FIRST (the corruption
                # model is state rotting while parked at the
                # boundary), then run the detectors BEFORE anything
                # legitimately mutates state below (stream deltas,
                # desync chaos) — so a mismatch is attributable ----
                local_sdc_code = 0
                sdc_results: list = []
                if fault_plan is not None:
                    flip_target = fault_plan.due_str_arg(
                        "bitflip", epoch)
                    if flip_target is not None and self._inject_bitflip(
                            flip_target, epoch, log_fn):
                        log_fn(f"fault-injected bitflip:{flip_target} "
                               f"at epoch {epoch}")
                        frec.crumb("bitflip-injected", epoch=epoch,
                                   target=flip_target)
                        if metrics is not None:
                            metrics.fault(
                                kind="injected", epoch=epoch,
                                reason=f"bitflip:{flip_target}")
                if integ is not None:
                    deep = integ.due(epoch)
                    sdc_results = integ.run_checks(self, epoch,
                                                   deep=deep)
                    for res in sdc_results:
                        if res.outcome == "mismatch":
                            frec.crumb("sdc-detected", epoch=epoch,
                                       check=res.check,
                                       target=res.target)
                            log_fn(f"integrity: {res.check} mismatch "
                                   f"on {res.target} at epoch {epoch}"
                                   f" ({res.detail})")
                        # ok records only for the deep (cadence)
                        # checks: per-boundary ok digests would drown
                        # the stream
                        if metrics is not None and (
                                res.outcome == "mismatch" or deep):
                            metrics.integrity(
                                epoch=epoch, check=res.check,
                                outcome=res.outcome,
                                target=res.target,
                                cadence=integ.check_every,
                                overhead_s=round(res.overhead_s, 6),
                                detail=res.detail,
                                dirty_shards=list(res.dirty_shards))
                    bad = [r for r in sdc_results
                           if r.outcome == "mismatch"]
                    if bad:
                        local_sdc_code = SDC_CODES.get(
                            bad[0].target, 0)
                # ---- streaming deltas: the graph changes HERE, at the
                # boundary where the donated state is consistent.
                # WAL-first when a journal is attached: a batch is made
                # durable BEFORE it mutates the topology; an append the
                # degraded disk rejects queues the batch (degrade-not-
                # lose) and the apply waits for a later boundary ----
                stream_reports = []
                stream_due = [] if stream_plan is None else \
                    stream_plan.due(epoch)
                if (stream_due or journal_pending_since is not None
                        or (fault_plan is not None and
                            fault_plan.peek("graph-delta", epoch))) \
                        and pending is not None:
                    # an in-flight async eval was dispatched against the
                    # pre-patch topology; finish it before the graph (and
                    # the host-side eval context) grows under it
                    _harvest_eval(pending)
                    pending = None

                def _journal_gate(db):
                    """WAL-first: True = durable, apply now. False =
                    queued pending (or a batch ahead of it is) — do NOT
                    apply; order is preserved by the queue."""
                    nonlocal journal_pending_since
                    if journal is None:
                        return True
                    gen = (self.topo_generation + 1
                           + journal.pending_count)
                    if journal.append(db, gen):
                        if metrics is not None:
                            metrics.journal(
                                op="append", seq=int(db.seq),
                                topo_generation=gen, n_records=1,
                                lag_seqs=max(
                                    journal.last_seq() - last_ckpt_seq,
                                    0))
                        return True
                    if journal_pending_since is None:
                        journal_pending_since = epoch
                        log_fn(f"JOURNAL APPEND FAILED at epoch "
                               f"{epoch} (seq={db.seq}); io-degraded "
                               f"— delta queued, NOT applied (WAL-"
                               f"first), retrying at later boundaries")
                        if metrics is not None:
                            metrics.fault(kind=IO_DEGRADED, epoch=epoch,
                                          reason="journal append failed",
                                          component="journal")
                            metrics.journal(
                                op="degraded", seq=int(db.seq),
                                topo_generation=self.topo_generation,
                                n_records=journal.pending_count)
                    return False

                if journal is not None and journal.pending_count:
                    # the disk may have recovered: retry queued appends
                    # in order; whatever becomes durable applies now
                    drained = journal.drain_pending()
                    for db, _g in drained:
                        rep = self.apply_graph_deltas(db)
                        stream_reports.append(rep)
                        if rep.repadded:
                            seen_chunks.clear()
                    if drained and not journal.pending_count:
                        log_fn(f"journal recovered at epoch {epoch}: "
                               f"{len(drained)} queued delta(s) made "
                               f"durable and applied")
                        if metrics is not None:
                            metrics.recovery(
                                kind=IO_DEGRADED, epoch=epoch,
                                pending_since=journal_pending_since
                                if journal_pending_since is not None
                                else epoch,
                                component="journal")
                            metrics.journal(
                                op="recovered", seq=journal.last_seq(),
                                topo_generation=self.topo_generation,
                                n_records=len(drained))
                        journal_pending_since = None
                if stream_plan is not None:
                    for sb in stream_due:
                        if not _journal_gate(sb):
                            continue
                        rep = self.apply_graph_deltas(sb)
                        log_fn(
                            f"stream delta seq={rep.seq} at epoch "
                            f"{epoch}: +{rep.edges_added}/-"
                            f"{rep.edges_deleted} edges, "
                            f"+{rep.nodes_added} nodes, "
                            f"{rep.patch_ms:.1f} ms patch"
                            + (" [re-padded: recompile]"
                               if rep.repadded else ""))
                        stream_reports.append(rep)
                        if rep.repadded:
                            # the rebuilt step recompiles; keep its
                            # first blocks out of the timing stats
                            seen_chunks.clear()
                if fault_plan is not None and \
                        fault_plan.due("graph-delta", epoch):
                    # chaos lane: an unscheduled synthetic delta batch
                    # hits the live graph mid-run (scripts/chaos.sh)
                    if getattr(self, "_stream", None) is None:
                        log_fn(f"fault graph-delta at epoch {epoch} "
                               f"skipped: streaming not enabled")
                    else:
                        from ..graph.synthetic import \
                            synthetic_delta_schedule

                        # seq must clear everything applied AND
                        # everything journaled-but-queued ahead of it
                        base = self._stream.last_seq
                        if journal is not None:
                            base = max(base, journal.last_seq())
                            if journal.pending:
                                base = max(base,
                                           journal.pending[-1][0].seq)
                        fb = synthetic_delta_schedule(
                            self._stream.g, n_batches=1,
                            edges_per_batch=4, dels_per_batch=2,
                            nodes_per_batch=1, seed=epoch,
                            start_seq=base + 1)[0]
                        if metrics is not None:
                            metrics.fault(kind="injected", epoch=epoch,
                                          reason="graph-delta")
                        if _journal_gate(fb):
                            rep = self.apply_graph_deltas(fb)
                            log_fn(f"fault-injected graph delta at "
                                   f"epoch {epoch} (seq={rep.seq})")
                            stream_reports.append(rep)
                            if rep.repadded:
                                seen_chunks.clear()
                if fault_plan is not None and \
                        fault_plan.due("journal-torn", epoch):
                    # chaos lane: the newest journal segment loses its
                    # tail (interrupted append / disk corruption); the
                    # next resume must walk back to the surviving
                    # prefix and re-derive the rest from the plan
                    if journal is None:
                        log_fn(f"fault journal-torn at epoch {epoch} "
                               f"skipped: no delta journal")
                    else:
                        lost = journal.tear_newest_segment()
                        log_fn(f"fault-injected journal tear at epoch "
                               f"{epoch}: {lost} record(s) lost from "
                               f"the newest segment")
                        if metrics is not None:
                            metrics.fault(kind="injected", epoch=epoch,
                                          reason="journal-torn")
                if integ is not None and stream_reports:
                    # the deltas legitimately rebuilt tables and
                    # flushed carry rows: re-baseline, forget the
                    # now-stale dynamic digests
                    integ.baseline(self)
                    integ.drop_dynamic()
                if fault_plan is not None and fault_plan.due("crash", epoch):
                    raise RuntimeError(
                        f"fault-injected crash at epoch {epoch}")
                if fault_plan is not None and fault_plan.due("kill", epoch):
                    # hard SIGKILL: no handlers, no atexit, no
                    # checkpoint — the process vanishes like an
                    # OOM-killed rank, so the PEERS' watchdog and the
                    # elastic supervisor must do ALL the recovery
                    import os as _os
                    import signal as _signal
                    import sys as _sys

                    log_fn(f"fault-injected SIGKILL at epoch {epoch}")
                    if metrics is not None:
                        metrics.fault(kind="injected", epoch=epoch,
                                      reason="kill")
                    _sys.stdout.flush()
                    _sys.stderr.flush()
                    _os.kill(_os.getpid(), _signal.SIGKILL)
                if fault_plan is not None and \
                        fault_plan.due("kernel-crash", epoch):
                    # the next dispatch raises a simulated TPU-backend
                    # error; the _dispatch guard must absorb it via the
                    # kernel fallback ladder (resilience/numerics.py)
                    log_fn(f"fault-injected kernel crash at epoch {epoch}")
                    self._inject_kernel_crash = True
                hang_ms = (fault_plan.due_arg("hang", epoch)
                           if fault_plan is not None else None)
                if hang_ms:
                    # bounded sub-watchdog stall (hang@E[:rN]:<ms>):
                    # heartbeats keep flowing and the loop RESUMES, so
                    # only the flight recorder's stall detector — never
                    # the peers' PeerLost path — sees it
                    log_fn(f"fault-injected {hang_ms} ms stall at "
                           f"epoch {epoch}")
                    frec.crumb("stall-injected", epoch=epoch,
                               stall_ms=hang_ms)
                    time.sleep(hang_ms / 1000.0)
                    frec.crumb("stall-resumed", epoch=epoch)
                elif hang_ms is not None:
                    # simulate a wedged process: heartbeats stop too, so
                    # the PEERS' watchdogs — not this rank — must act.
                    # The open collective span is what the black-box
                    # dump's stack annotation names as the wedged phase
                    log_fn(f"fault-injected hang at epoch {epoch}")
                    frec.enter("collective", phase="fault-hang",
                               epoch=epoch)
                    if coord is not None:
                        coord.suspend_heartbeat()
                    time.sleep(3600)
                    raise RuntimeError("fault-injected hang expired")
                if fault_plan is not None and \
                        fault_plan.due("desync", epoch):
                    # silently perturb THIS rank's replicated params —
                    # the cross-rank divergence the desync detector
                    # exists to catch. Rebuilt from LOCAL single-device
                    # arrays: a device_put onto the global replicated
                    # sharding is a cross-process collective, which
                    # only this rank would run — the injection must
                    # desynchronize the STATE, not the program
                    host_p = jax.device_get(self.state["params"])
                    host_p = jax.tree_util.tree_map(
                        lambda a: (np.asarray(a)
                                   * np.asarray(1.001, np.asarray(a).dtype)),
                        host_p)
                    local_devs = [d for d in self.mesh.devices.flat
                                  if d.process_index == jax.process_index()]

                    def _replicate_local(arr):
                        shards = [jax.device_put(arr, d)
                                  for d in local_devs]
                        return jax.make_array_from_single_device_arrays(
                            arr.shape, self._repl, shards)

                    self.state = dict(self.state)
                    self.state["params"] = jax.tree_util.tree_map(
                        _replicate_local, host_p)
                    log_fn(f"fault-injected param desync at epoch {epoch}")
                    if integ is not None:
                        # the perturbation targets the DESYNC detector;
                        # forget the params digests so the integrity
                        # plane doesn't claim the other lane's fault
                        integ.drop_dynamic()
                preempt_reason = (preemption.reason
                                  if preemption is not None
                                  and preemption.requested else None)
                if fault_plan is not None and \
                        fault_plan.due("sigterm", epoch):
                    preempt_reason = preempt_reason or "fault-plan sigterm"
                preempt_extra = {}
                sdc_code = local_sdc_code
                sdc_rank = (jax.process_index()
                            if local_sdc_code else -1)
                if coord_on:
                    # boundary consensus: a shutdown request on ANY rank
                    # checkpoints + exits 75 on ALL ranks, in lockstep —
                    # one rank leaving unilaterally deadlocks the rest.
                    # The SDC code rides the same word so every rank
                    # executes the identical recovery below
                    agreed = coord.agree_boundary(
                        preempt=preempt_reason is not None,
                        sdc_code=local_sdc_code)
                    if agreed.sdc:
                        sdc_code = agreed.sdc_code
                        sdc_rank = agreed.sdc_rank
                    if agreed.preempt:
                        preempt_extra = {"agreed": True,
                                         "source_rank": agreed.preempt_rank}
                        if preempt_reason is None:
                            preempt_reason = (
                                f"peer preemption (rank "
                                f"{agreed.preempt_rank})"
                                if agreed.preempt_rank >= 0 else
                                "peer preemption (multiple ranks)")
                if preempt_reason is not None:
                    frec.crumb("preempt", epoch=epoch,
                               reason=str(preempt_reason)[:120])
                    log_fn(f"preemption requested ({preempt_reason}); "
                           f"checkpointing at epoch boundary {epoch}")
                    if metrics is not None:
                        metrics.fault(kind="preemption", epoch=epoch,
                                      reason=preempt_reason,
                                      **preempt_extra)
                    if jax.process_count() > 1 and last_good is not None:
                        # multi-process: the crash handler cannot fetch
                        # the sharded comm carry directly; materialize
                        # the boundary state HERE (every rank reaches
                        # this point — the allgather is lockstep) so
                        # the fallback save is exact, not stale
                        last_good = (epoch, self.host_state())
                        if coord is not None:
                            coord.note_snapshot(*last_good)
                    # the crash handler below does the rank-0 save
                    raise Preempted(epoch, preempt_reason)
                # ---- SDC containment & recovery: agreed above, so the
                # action below runs in lockstep on every rank ----
                if integ is not None and sdc_code:
                    sdc_target = SDC_NAMES.get(sdc_code, "params")
                    dirty = tuple(sorted({
                        int(s) for r in sdc_results
                        if r.outcome == "mismatch"
                        for s in r.dirty_shards}))
                    frec.crumb("sdc-recover", epoch=epoch,
                               target=sdc_target)
                    if metrics is not None:
                        metrics.fault(
                            kind="sdc", epoch=epoch,
                            target=sdc_target,
                            source_rank=sdc_rank,
                            strikes=integ.total_detections(),
                            agreed=coord_on)
                    # containment first: a member that keeps detecting
                    # SDC is the defective one — ask to leave the
                    # fleet (durable marker the elastic supervisor
                    # consumes at its next replan) before recovering
                    if (integ.should_quarantine()
                            and local_sdc_code
                            and coord is not None
                            and getattr(coord.cfg, "dir", "")):
                        # elastic.MEMBER_ENV: the supervisor's member
                        # id for this process (falls back to the rank
                        # outside supervised runs)
                        member = int(os.environ.get(
                            "PIPEGCN_ELASTIC_MEMBER",
                            jax.process_index()))
                        marker = request_quarantine(
                            coord.cfg.dir, member,
                            reason="recurring silent data corruption",
                            strikes=integ.total_detections(),
                            targets=sorted(integ.detections))
                        log_fn(f"integrity: recurring SDC "
                               f"({integ.total_detections()} strikes)"
                               f"; quarantine requested for member "
                               f"{member} ({marker})")
                        if metrics is not None:
                            metrics.fault(
                                kind="quarantine-request",
                                epoch=epoch, member=member,
                                strikes=integ.total_detections(),
                                targets=sorted(integ.detections))
                        if jax.process_count() > 1 \
                                and last_good is not None:
                            last_good = (epoch, self.host_state())
                            if coord is not None:
                                coord.note_snapshot(*last_good)
                        raise Preempted(
                            epoch, "recurring silent data corruption")
                    if sdc_target == "tables":
                        n_reb = self._rebuild_static_data(
                            dirty or None)
                        integ.baseline(self)
                        log_fn(f"integrity: rebuilt "
                               f"{'shards ' + str(list(dirty)) if dirty else 'all shards'}"
                               f" from the host artifact at epoch "
                               f"{epoch}")
                        if metrics is not None:
                            metrics.recovery(
                                kind="sdc", epoch=epoch,
                                target=sdc_target,
                                tables_rebuilt=n_reb,
                                dirty_shards=list(dirty))
                    elif sdc_target in ("halo", "carry"):
                        # poisoned boundary data: flush the pipelined
                        # carry (epoch-0 warmup semantics) instead of
                        # training on it for one more epoch
                        if tcfg.enable_pipeline:
                            self.reset_comm()
                        integ.drop_dynamic()
                        log_fn(f"integrity: flushed pipelined carry "
                               f"at epoch {epoch} ({sdc_target} "
                               f"corruption)")
                        if metrics is not None:
                            metrics.recovery(kind="sdc", epoch=epoch,
                                             target=sdc_target,
                                             flushed=True)
                    elif last_good is not None:  # params
                        rollback_to, good_state = last_good
                        log_fn(f"integrity: params corruption at "
                               f"epoch {epoch}; rolling back to "
                               f"epoch {rollback_to}")
                        self.restore_state(good_state)
                        self.last_epoch = rollback_to
                        if tcfg.enable_pipeline:
                            self.reset_comm()
                        integ.drop_dynamic()
                        if metrics is not None:
                            metrics.recovery(
                                kind="sdc", epoch=epoch,
                                target=sdc_target,
                                rollback_epoch=rollback_to)
                        pending = None  # in-flight eval snapshot is
                        #                 from the corrupt timeline
                        eval_in_stream = False
                        epoch = rollback_to
                        continue
                if profile_dir and not profiling:
                    if prof_window is not None:
                        if prof_window[0] <= epoch < prof_window[1]:
                            jax.profiler.start_trace(profile_dir)
                            profiling = True
                            prof_started_at = epoch
                    elif epoch >= min(start_epoch + 6, n_epochs - 1):
                        jax.profiler.start_trace(profile_dir)
                        profiling = True
                        prof_started_at = epoch
                chunk = min(fused, n_epochs - epoch)
                for m in periods:
                    to_boundary = m - epoch % m
                    chunk = min(chunk, to_boundary)
                if stream_plan is not None:
                    # a fused block must not straddle a scheduled delta
                    nxt = stream_plan.next_epoch(epoch + 1)
                    if nxt is not None:
                        chunk = min(chunk, nxt - epoch)
                if prof_window is not None and not profiling and \
                        epoch < prof_window[0]:
                    # a fused block must not straddle the window start
                    chunk = min(chunk, prof_window[0] - epoch)
                if profiling or (profile_dir and prof_window is None
                                 and epoch < start_epoch + 10):
                    chunk = 1  # epoch-granular around the profiled window
                # staleness probe: snapshot the stale halo carry BEFORE
                # the dispatch donates it (obs docs: drift is old vs
                # new carry — exchange(h[e-1]) vs exchange(h[e]))
                # delta epochs always probe: the drift across the first
                # post-patch step IS the per-delta drift measurement
                probe_due = ((probe_every > 0
                              and epoch % probe_every == 0
                              or bool(stream_reports))
                             and bool(self.state.get("comm")))
                old_halo = None
                if probe_due:
                    chunk = 1
                    old_halo = jax.tree_util.tree_map(
                        jnp.copy, self.state["comm"]["halo"])
                slow_ms = (fault_plan.due_arg("slow-rank", epoch)
                           if fault_plan is not None else None)
                if slow_ms:
                    # deterministic straggler (slow-rank@E[:rN]:<ms>):
                    # this rank arrives late at the dispatch boundary,
                    # so every peer waits on its collectives inside the
                    # compiled step. The training-span plane's aligned
                    # compute-window starts attribute the gap to this
                    # rank (obs/trainspan.py straggler attribution)
                    log_fn(f"fault-injected {slow_ms} ms straggle at "
                           f"epoch {epoch}")
                    frec.crumb("slow-rank-injected", epoch=epoch,
                               slow_ms=slow_ms)
                    time.sleep(slow_ms / 1000.0)
                timer.clear()
                # dispatch span left OPEN across the step: if the
                # program wedges inside (a dead collective), the crash
                # dump's annotation names this epoch and phase
                frec.enter("dispatch", epoch=epoch, chunk=chunk)
                # annotate=True: the host span shows up in --profile-dir
                # traces next to the named device phases
                with timer.phase("step", annotate=True):
                    if chunk == 1:
                        loss = self.train_epoch(epoch)
                        blk_losses = np.asarray([loss])
                    else:
                        blk_losses = np.asarray(
                            self.train_epochs(epoch, chunk))
                        loss = float(blk_losses[-1])
                    jax.block_until_ready(self.state["params"])
                frec.exit("dispatch", epoch=epoch)
                if tspan is not None:
                    # the block's spans: the real dispatch->harvest wall
                    # window, plus (once measure_comm landed) the comm
                    # tail ending at the harvest barrier
                    tspan.block(epoch, chunk, timer.durations()["step"])
                dur = timer.durations()["step"] / chunk
                stop_profile = profiling and (
                    epoch + chunk >= prof_window[1]
                    if prof_window is not None
                    else epoch >= start_epoch + 8)
                if stop_profile:
                    profiling = False
                    prof_record = _finish_profile(
                        (prof_started_at, epoch + chunk)) or prof_record
                # first 5 epochs after (re)start excluded from averaged
                # timings — they include jit compilation (the reference
                # excludes epochs <5 and log epochs, train.py:364). A chunk
                # length seen for the first time also compiles (one scan
                # program per distinct length) — exclude that block too. And
                # a block right after an async eval dispatch waits on the
                # eval's device time (enqueued ahead of it on the same
                # stream), so exclude it as well — the reference's Time(s)
                # likewise excludes eval (it runs on the CPU thread).
                first_of_len = chunk not in seen_chunks
                seen_chunks.add(chunk)
                if epoch >= start_epoch + 5 and not first_of_len \
                        and not eval_in_stream:
                    durs.extend([dur] * chunk)
                eval_in_stream = False
                # ---- kernel fallbacks taken during the dispatch:
                # surface them as contracted `fallback` records ----
                fb_new = False
                for fb in self.fallbacks:
                    if not fb.get("emitted"):
                        fb["emitted"] = True
                        fb_new = True
                        frec.crumb("fallback", epoch=epoch,
                                   from_impl=fb["from_impl"],
                                   to_impl=fb["to_impl"])
                        log_fn(f"kernel fallback: {fb['from_impl']} -> "
                               f"{fb['to_impl']} ({fb['reason'][:120]})")
                        if metrics is not None:
                            metrics.fallback(
                                epoch=epoch, from_impl=fb["from_impl"],
                                to_impl=fb["to_impl"],
                                reason=fb["reason"])
                        # the downgraded step recompiles; exclude its
                        # first blocks from the timing stats
                        seen_chunks.clear()
                if fb_new and integ is not None:
                    # the fallback rebuilt tables one rung down: the
                    # static baseline (and the carry it flushed) are
                    # legitimately different now
                    integ.baseline(self)
                    integ.drop_dynamic()
                # ---- halo wire checksum lane (parallel/halo.py):
                # harvested from the step metrics; a nonzero count
                # means a ppermute payload arrived with a different
                # checksum than it left with — flush the poisoned
                # carry rather than consume it next epoch ----
                if integ is not None and "wire_bad" in self._last_metrics:
                    wb_n = int(np.sum(np.asarray(
                        self._last_metrics["wire_bad"])))
                    if wb_n:
                        integ.detections["halo"] = \
                            integ.detections.get("halo", 0) + 1
                        frec.crumb("wire-bad", epoch=epoch,
                                   blocks=wb_n)
                        log_fn(f"integrity: halo wire checksum "
                               f"mismatch in {wb_n} distance block(s)"
                               f" at epoch {epoch}; flushing carry")
                        if metrics is not None:
                            metrics.integrity(
                                epoch=epoch, check="wire",
                                outcome="mismatch", target="halo",
                                cadence=integ.check_every,
                                overhead_s=0.0,
                                blocks=wb_n)
                            metrics.fault(kind="sdc", epoch=epoch,
                                          target="halo", check="wire",
                                          blocks=wb_n,
                                          agreed=False)
                        if tcfg.enable_pipeline:
                            self.reset_comm()
                        integ.drop_dynamic()
                # grad norms ride the step output ([k] arrays for fused
                # blocks) — harvested here for the metrics records AND
                # the sentinel check
                gn = np.atleast_1d(np.asarray(
                    self._last_metrics["grad_norm"], np.float64))
                frec.crumb("metrics-harvest", epoch=epoch + chunk - 1,
                           loss=float(loss), step_time_s=round(dur, 4))
                # ---- injected metric faults (host-side only: the
                # compiled device program is what production runs) ----
                if fault_plan is not None:
                    j = fault_plan.due_in("nan-loss", epoch, epoch + chunk)
                    if j is not None:
                        blk_losses = np.array(blk_losses, np.float64)
                        blk_losses[j - epoch] = np.nan
                        loss = float(blk_losses[-1])
                        log_fn(f"fault-injected nan loss at epoch {j}")
                    j = fault_plan.due_in("nan-grad", epoch, epoch + chunk)
                    if j is not None:
                        gn = np.array(gn, np.float64)
                        gn[min(j - epoch, gn.size - 1)] = np.nan
                        log_fn(f"fault-injected nan grad norm at epoch {j}")
                # ---- loss-scale state machine (resilience/numerics):
                # harvested overflow flags drive backoff / skip
                # accounting / regrowth; overflow epochs are HANDLED
                # events the sentinel must not mistake for divergence
                ovf = None
                if self.loss_scaler.cfg.enabled:
                    ovf = np.atleast_1d(np.asarray(
                        self._last_metrics.get("overflow", 0)))
                    if fault_plan is not None:
                        j = fault_plan.due_in("overflow", epoch,
                                              epoch + chunk)
                        if j is not None:
                            ovf = np.array(ovf)
                            ovf[min(j - epoch, ovf.size - 1)] = 1
                            log_fn(f"fault-injected loss-scale overflow "
                                   f"at epoch {j}")
                    for ev in self.loss_scaler.update(epoch, ovf):
                        frec.crumb("loss-scale", event=ev["kind"],
                                   epoch=ev["epoch"])
                        if ev["kind"] == "overflow":
                            log_fn(
                                f"loss-scale overflow at epoch "
                                f"{ev['epoch']}: step skipped, scale "
                                f"{ev['scale']:g}"
                                + (f" -> {ev['new_scale']:g}"
                                   if "new_scale" in ev else ""))
                        else:
                            log_fn(f"loss-scale regrown to "
                                   f"{ev['scale']:g} at epoch "
                                   f"{ev['epoch']}")
                        if metrics is not None:
                            metrics.numerics(**ev)
                if metrics is not None:
                    # one record per epoch in the block; the HBM
                    # watermark is sampled once per dispatch
                    mem = memory_snapshot()
                    for j in range(chunk):
                        e_j = epoch + j
                        metrics.epoch(
                            epoch=e_j,
                            step_time_s=dur,
                            loss=float(blk_losses[j]),
                            grad_norm=float(gn[j] if gn.size > 1
                                            else gn[0]),
                            halo_bytes=halo_bytes,
                            # pipelined mode consumes epoch e-1's
                            # boundary data (zeros at the very first
                            # epoch); vanilla exchanges synchronously
                            staleness_age=int(
                                1 if tcfg.enable_pipeline and e_j > 0
                                else 0),
                            memory=mem,
                            **halo_extra,
                        )
                # ---- staleness probe: relative drift between the
                # stale halo features this epoch consumed (snapshotted
                # above) and the fresh ones it shipped ----
                stream_drift = None
                if probe_due and old_halo is not None:
                    layers, max_rel = self._staleness_drift(
                        old_halo, self.state["comm"]["halo"])
                    stream_drift = float(max_rel)
                    if metrics is not None:
                        metrics.staleness(epoch=epoch, layers=layers,
                                          max_rel_drift=max_rel)
                    else:
                        log_fn(f"staleness probe epoch {epoch}: max "
                               f"relative drift {max_rel:.4f}")
                    old_halo = None
                # ---- contracted `stream` records for this boundary's
                # delta applications (drift measured by the forced
                # probe above; None when the pipeline is off) ----
                if stream_reports and metrics is not None:
                    for rep in stream_reports:
                        metrics.stream(
                            epoch=epoch, seq=rep.seq,
                            edges_added=rep.edges_added,
                            edges_deleted=rep.edges_deleted,
                            nodes_added=rep.nodes_added,
                            patch_ms=rep.patch_ms,
                            tables_rebuilt=rep.tables_rebuilt,
                            repadded=rep.repadded,
                            slack_remaining=rep.slack_remaining,
                            drift=stream_drift)
                # ---- divergence sentinel: check the block, roll back
                # on trip (restore last good snapshot, back the LR off,
                # flush the stale halo carry), bounded retries. With an
                # active coordinator the trip VERDICT is agreed across
                # ranks first, so the rollback below runs in lockstep
                # on the whole pod whichever rank tripped. ----
                reason = None
                trip_extra = {}
                if sentinel is not None:
                    chk_l, chk_g = blk_losses, gn
                    if ovf is not None and np.any(ovf):
                        # overflow-skipped epochs were handled by the
                        # loss scaler; mask them out of the sentinel's
                        # view (their non-finite grad norm is expected)
                        from ..resilience.numerics import \
                            sanitize_for_sentinel

                        chk_l, chk_g = sanitize_for_sentinel(
                            blk_losses, gn, ovf)
                    if chk_l is not None:
                        reason = sentinel.check(epoch, chk_l, chk_g)
                if reason is not None:
                    # ---- NaN provenance (resilience/numerics): the
                    # step's tripwire counts name the phase where the
                    # non-finite value was BORN ----
                    from ..resilience.numerics import (
                        epoch_nonfinite_counts, first_nonfinite_phase)

                    nm = self._last_metrics.get("numerics") \
                        if isinstance(self._last_metrics, dict) else None
                    if nm is not None:
                        phase = first_nonfinite_phase(nm)
                        if phase is not None:
                            bad = ~np.isfinite(np.atleast_1d(np.asarray(
                                blk_losses, np.float64)))
                            j = int(np.argmax(bad)) if bad.any() else 0
                            trip_extra["phase"] = phase
                            if metrics is not None:
                                metrics.numerics(
                                    kind="tripwire", epoch=epoch + j,
                                    phase=phase,
                                    counts=epoch_nonfinite_counts(nm, j))
                            log_fn(f"numerics tripwire: first non-finite "
                                   f"phase = {phase}")
                if coord_on:
                    desync_local = False
                    if coord.desync_due(epoch + chunk):
                        desync_local = coord.desync_check(
                            jax.device_get(self.state["params"]))
                    agreed = coord.agree_step(trip_reason=reason,
                                              desync=desync_local)
                    if agreed.desync:
                        if metrics is not None:
                            metrics.fault(
                                kind="desync", epoch=epoch + chunk - 1,
                                local_mismatch=bool(desync_local),
                                mismatched_leaves=int(
                                    coord.last_desync_mismatch),
                                # the mismatching leaf NAMES (bounded):
                                # postmortem evidence distinguishing
                                # one-tensor corruption from full
                                # divergence
                                leaves=list(coord.last_desync_leaves),
                                source_rank=agreed.desync_rank,
                                agreed=True)
                        if coord.cfg.desync_resync:
                            log_fn(f"cross-rank param desync detected "
                                   f"(source rank {agreed.desync_rank}); "
                                   f"resyncing every rank from rank 0")
                            coord.resync(self, epoch + chunk)
                            if integ is not None:
                                integ.drop_dynamic()
                            if metrics is not None:
                                metrics.recovery(kind="desync",
                                                 epoch=epoch + chunk - 1,
                                                 agreed=True)
                        else:
                            log_fn("cross-rank param desync detected; "
                                   "aborting resumably (rank 0's state "
                                   "rides the crash checkpoint)")
                            if jax.process_count() > 1 \
                                    and last_good is not None:
                                # lockstep materialization, as in the
                                # preemption branch
                                last_good = (epoch + chunk,
                                             self.host_state())
                                if coord is not None:
                                    coord.note_snapshot(*last_good)
                            raise Preempted(
                                epoch + chunk,
                                "cross-rank parameter desync")
                    if agreed.trip:
                        trip_extra = {"agreed": True,
                                      "source_rank": agreed.trip_rank}
                        if reason is None:
                            # a PEER tripped: execute the identical
                            # rollback here or the pod desynchronizes
                            reason = agreed.trip_reason()
                if reason is not None:
                    scfg = (sentinel.cfg if sentinel is not None
                            else SentinelConfig())
                    retries += 1
                    frec.crumb("sentinel-trip", epoch=epoch,
                               reason=str(reason)[:120], retry=retries)
                    rollback_to, good_state = last_good
                    new_lr = (self.tcfg.lr * scfg.lr_backoff
                              if scfg.lr_backoff < 1.0 else self.tcfg.lr)
                    log_fn(f"divergence sentinel tripped ({reason}); "
                           f"retry {retries}/{scfg.max_retries}: "
                           f"rollback to epoch {rollback_to}, "
                           f"lr -> {new_lr:g}")
                    if metrics is not None:
                        metrics.fault(
                            kind="divergence", epoch=epoch,
                            reason=reason, retry=retries,
                            rollback_epoch=rollback_to, lr=new_lr,
                            **trip_extra)
                    # restore BEFORE a possible give-up so the crash
                    # handler checkpoints the healthy state, not the
                    # divergent one
                    self.restore_state(good_state)
                    self.last_epoch = rollback_to
                    if integ is not None:
                        integ.drop_dynamic()
                    if retries > scfg.max_retries:
                        raise DivergenceError(
                            f"training diverged and "
                            f"{scfg.max_retries} recovery retries "
                            f"were exhausted: {reason}")
                    if scfg.lr_backoff < 1.0:
                        self.set_lr(new_lr)
                        # the rebuilt step recompiles once per scan
                        # length; exclude those blocks from timing
                        seen_chunks.clear()
                    if scfg.flush_on_trip and tcfg.enable_pipeline:
                        self.reset_comm()
                    trip_horizon = epoch + chunk
                    pending = None  # in-flight eval snapshot is
                    #                 from the rolled-back timeline
                    eval_in_stream = False
                    epoch = rollback_to
                    continue
                if last_good is not None:
                    if trip_horizon is not None and \
                            epoch + chunk >= trip_horizon:
                        log_fn(f"recovered past epoch {trip_horizon - 1} "
                               f"after rollback")
                        if metrics is not None:
                            metrics.recovery(kind="divergence",
                                             epoch=epoch + chunk - 1,
                                             retries=retries)
                        retries = 0
                        trip_horizon = None
                    # healthy: refresh the rollback snapshot on cadence
                    if epoch + chunk - last_good[0] >= snap_every:
                        last_good = (epoch + chunk, self.host_state())
                        if coord is not None:
                            coord.note_snapshot(*last_good)
                if integ is not None:
                    # capture params+carry digests at their production
                    # point; the NEXT boundary verifies state survived
                    # its parked window unchanged
                    integ.note_dynamic(self)
                epoch += chunk - 1  # body below sees the block's last epoch
                if measure_comm_cost and not comm_measured and \
                        epoch >= min(start_epoch + 5, n_epochs - 1):
                    # standalone collective cost, measured once post-compile
                    # (the reference reports per-epoch exposed comm/reduce
                    # waits, train.py:366-371; SPMD overlaps those inside
                    # the step, so we report the collectives' own cost)
                    comm_cost = self.measure_comm()
                    comm_measured = True
                    if tspan is not None:
                        # arm the comm tail: standalone per-epoch costs
                        # apportioned over the exchanged layers by wire
                        # bytes (the same arithmetic as
                        # est_halo_bytes_per_epoch, kept per-layer)
                        item = 4 if self.cfg.compute_dtype == jnp.float32 \
                            else 2
                        hdt = (getattr(tcfg, "halo_dtype", "none")
                               or "none") if tcfg.enable_pipeline \
                            else "none"
                        if hdt == "float8":
                            item = 1
                        elif hdt == "bfloat16":
                            item = min(item, 2)
                        tspan.set_comm(
                            comm_cost,
                            [(i, 2 * self.P * self.sg.halo_size
                              * self._layer_width(i) * item)
                             for i in self._graph_layer_range()],
                            hdt if hdt != "none" else
                            ("float32" if item == 4 else "bfloat16"))
                    if reference_logs:
                        # semantics differ from the reference: its Comm(s)
                        # is per-epoch EXPOSED wait around blocking
                        # transfers (helper/timer/comm_timer.py); SPMD
                        # overlaps those inside the jitted step, so the
                        # fields below are the collectives' standalone
                        # cost. Annotate the stream so reference-format
                        # consumers don't compare unlike quantities.
                        log_fn("# note: Comm(s)/Reduce(s) = standalone "
                               "collective cost (not exposed wait; SPMD "
                               "overlaps comm inside the step); Comm = "
                               "forward halo ring + cotangent return "
                               "ring (both modes move both)")

                if reference_logs and (epoch + 1) % 10 == 0:
                    # reference log line format (train.py:369-371,
                    # pinned byte-exact in obs/format.py); rank is
                    # always 0 in SPMD (one controller)
                    log_fn(reference_train_line(
                        0, epoch, float(np.mean(durs or [dur])),
                        comm_cost["comm"] + comm_cost["bgrad"],
                        comm_cost["reduce"], loss))

                if (epoch + 1) % tcfg.log_every == 0:
                    do_eval = tcfg.eval and eval_graphs and "val" in eval_graphs
                    if do_eval:
                        if pending is not None:
                            _harvest_eval(pending)
                            pending = None
                        p = _dispatch_eval(epoch, loss, dur)
                        if async_eval:
                            pending = p
                            eval_in_stream = True
                        else:
                            _harvest_eval(p)
                    else:
                        history.append((epoch + 1, loss, None))
                        if not reference_logs:
                            log_fn(epoch_line(
                                epoch + 1,
                                float(np.mean(durs or [dur])), loss))

                if checkpoint_dir and (epoch + 1) % checkpoint_every == 0:
                    # every process materializes (host_state is a
                    # lockstep allgather when the comm carry spans
                    # processes); only process 0 writes (reference
                    # semantics, and N-1 fewer multi-GB writes to the
                    # shared filesystem)
                    frec.enter("checkpoint-io", epoch=epoch + 1)
                    ck_t0 = tspan.clock() if tspan is not None else 0.0
                    host = self.host_state()
                    if jax.process_index() == 0:
                        try:
                            save_checkpoint(checkpoint_dir, host,
                                            epoch + 1,
                                            keep=checkpoint_keep,
                                            extra=_stream_watermark())
                        except OSError as io_exc:
                            # storage degradation, never an abort: the
                            # previous generation stays the
                            # authoritative resume point; retried with
                            # fresh state at later boundaries
                            was_pending = ckpt_pending
                            ckpt_pending = epoch + 1
                            log_fn(f"CHECKPOINT SAVE FAILED at epoch "
                                   f"{epoch + 1} ({io_exc!r}); "
                                   f"io-degraded — the previous "
                                   f"generation stays authoritative, "
                                   f"retrying at the next boundary")
                            if metrics is not None and was_pending is None:
                                metrics.fault(kind=IO_DEGRADED,
                                              epoch=epoch + 1,
                                              reason=repr(io_exc),
                                              component="checkpoint")
                            if checkpoint_fallback_dir:
                                try:
                                    save_checkpoint(
                                        checkpoint_fallback_dir, host,
                                        epoch + 1,
                                        keep=checkpoint_keep,
                                        extra=_stream_watermark())
                                    log_fn(
                                        f"checkpoint epoch {epoch + 1} "
                                        f"saved to fallback dir "
                                        f"{checkpoint_fallback_dir}")
                                except OSError as fb_exc:
                                    log_fn(f"fallback checkpoint dir "
                                           f"{checkpoint_fallback_dir} "
                                           f"also failed ({fb_exc!r})")
                        else:
                            if ckpt_pending is not None:
                                log_fn(f"checkpoint save recovered at "
                                       f"epoch {epoch + 1} (pending "
                                       f"since epoch {ckpt_pending})")
                                if metrics is not None:
                                    metrics.recovery(
                                        kind=IO_DEGRADED,
                                        epoch=epoch + 1,
                                        pending_since=ckpt_pending)
                                ckpt_pending = None
                            if journal is not None:
                                # the new generation covers everything
                                # applied so far: advance the durable
                                # watermark and report the replay lag a
                                # crash right now would incur
                                last_ckpt_seq = int(
                                    self._stream.last_seq)
                                if metrics is not None:
                                    metrics.journal(
                                        op="watermark",
                                        seq=last_ckpt_seq,
                                        topo_generation=int(
                                            self.topo_generation),
                                        n_records=0,
                                        lag_seqs=max(
                                            journal.last_seq()
                                            - last_ckpt_seq, 0))
                            if fault_plan is not None and \
                                    fault_plan.due("corrupt-ckpt",
                                                   epoch + 1):
                                from ..resilience.faults import \
                                    corrupt_latest_checkpoint

                                p = corrupt_latest_checkpoint(
                                    checkpoint_dir)
                                log_fn(f"fault-injected checkpoint "
                                       f"corruption: {p}")
                                if metrics is not None:
                                    metrics.fault(kind="injected",
                                                  epoch=epoch + 1,
                                                  reason="corrupt-ckpt")
                    frec.exit("checkpoint-io", epoch=epoch + 1)
                    if tspan is not None:
                        t1 = tspan.clock()
                        tspan.checkpoint_span(
                            epoch + 1, t1 - ck_t0, t_end=t1,
                            status=("error"
                                    if ckpt_pending == epoch + 1
                                    else "ok"))
                epoch += 1

        except BaseException as exc:
            # crash-resilient training (the reference's collectives
            # hang on any rank failure, SURVEY §5): best-effort save of
            # the last good state so --resume restarts from it, not
            # epoch 0. Preemption rides the same path — the boundary
            # check above raises Preempted with the state consistent.
            # last_epoch labels self.state's buffers (see train_epoch);
            # if those buffers come from a FAILED dispatch, device_get
            # below raises and the save falls back to the last host
            # snapshot when one exists — the previous periodic
            # checkpoint survives either way (saves are atomic, and the
            # generation rotation keeps the older good ones).
            if tspan is not None:
                # fault path: make the spans already emitted durable
                # before any recovery/exit handling can end the process
                try:
                    tspan.flush()
                except Exception:  # noqa: BLE001
                    pass
            converted = None
            if (coord is not None and coord.active
                    and not isinstance(exc, (Preempted, PeerLost,
                                             DivergenceError,
                                             KeyboardInterrupt))):
                # a failed collective looks like a generic runtime
                # error; ask the watchdog whether a peer actually died
                # before reporting it as a local crash
                lost = coord.await_peer_verdict()
                if lost is not None:
                    log_fn(f"dispatch failed and peer rank {lost[0]} "
                           f"stopped heartbeating ({lost[1]:.0f}s); "
                           f"reporting PeerLost instead of a crash")
                    converted = PeerLost(*lost)
            eff = converted if converted is not None else exc
            # black-box dump BEFORE the checkpoint attempts: the
            # forensics must survive even when the save path itself is
            # what's wedged. Directory preference: the configured dump
            # dir (cli/main points it at the coordination dir), else
            # the checkpoint dir, else beside the metrics stream; a
            # bare fit() with none of those skips the dump rather than
            # littering the working directory.
            try:
                done_e = int(getattr(self, "last_epoch", start_epoch))
                frec.crumb("crash", epoch=done_e,
                           error=f"{type(eff).__name__}: {eff}"[:200])
                bb_dir = frec.dump_dir or checkpoint_dir or (
                    os.path.dirname(os.fspath(metrics.path)) or "."
                    if metrics is not None
                    and getattr(metrics, "path", None) else None)
                if bb_dir:
                    flightrec.dump_blackbox(
                        "preemption" if isinstance(eff, Preempted)
                        else "fault" if isinstance(eff, PeerLost)
                        else "exception",
                        directory=bb_dir, epoch=done_e,
                        error=f"{type(eff).__name__}: {eff}"[:200])
            except Exception:  # noqa: BLE001 — never mask the fault
                pass
            if metrics is not None and isinstance(eff, PeerLost):
                try:
                    metrics.fault(kind="peer-lost",
                                  epoch=int(getattr(self, "last_epoch",
                                                    start_epoch)),
                                  peer_rank=eff.rank,
                                  silent_s=eff.silent_s)
                except Exception:  # noqa: BLE001 — still checkpoint
                    pass
            # every surviving rank saves on PeerLost (rank 0 may be the
            # dead one); otherwise rank 0 only, as before
            if checkpoint_dir and (jax.process_index() == 0
                                   or isinstance(eff, PeerLost)):
                tag = ("preemption" if isinstance(eff, Preempted)
                       else "peer-lost" if isinstance(eff, PeerLost)
                       else "crash")
                try:
                    done = int(getattr(self, "last_epoch",
                                       start_epoch))
                    save_checkpoint(checkpoint_dir,
                                    jax.device_get(self.state), done,
                                    keep=checkpoint_keep,
                                    extra=_stream_watermark())
                    log_fn(f"{tag} checkpoint saved to "
                           f"{checkpoint_dir} (epoch {done})")
                except Exception as save_exc:  # noqa: BLE001
                    if last_good is not None:
                        # poisoned buffers: the host-side snapshot is
                        # still a valid, older resume point. The live
                        # topology is never rolled back in-process, so
                        # the CURRENT watermark is the graph these
                        # params were last training against
                        try:
                            save_checkpoint(checkpoint_dir,
                                            last_good[1], last_good[0],
                                            keep=checkpoint_keep,
                                            extra=_stream_watermark())
                            log_fn(f"{tag} checkpoint fell back to the "
                                   f"epoch-{last_good[0]} snapshot "
                                   f"({save_exc!r})")
                        except Exception as snap_exc:  # noqa: BLE001
                            log_fn(f"{tag} checkpoint failed: "
                                   f"{snap_exc!r}")
                    else:
                        log_fn(f"{tag} checkpoint failed: {save_exc!r}")
            if converted is not None:
                raise converted from exc
            raise
        finally:
            # a fit-armed storage fault must never outlive fit: the
            # shim is process-wide, and later in-process work (tests,
            # a clean resume in the same interpreter) would otherwise
            # inherit a permanently "full" disk. The crash handler
            # above runs BEFORE this, still degraded — exactly like a
            # real host whose disk is full when it dies
            for kind in list(io_armed):
                FAULTY_IO.disarm(kind)
            io_armed.clear()
            if stall_det is not None:
                stall_det.stop()
            frec.crumb("fit-end", epoch=epoch)

        if pending is not None:
            # harvest the final in-flight evaluation
            _harvest_eval(pending)
            pending = None
        if (tcfg.eval and eval_graphs and "val" in eval_graphs
                and n_epochs > start_epoch
                and n_epochs % tcfg.log_every != 0):
            # the run's final epochs lie past the last log boundary, so
            # the FINAL state was never scored (with log_every >
            # n_epochs, no eval happened at all and the summary would
            # be silently empty); always evaluate it before reporting
            _harvest_eval(_dispatch_eval(epoch - 1, loss, dur))

        if profiling:
            # run ended inside the trace window; finalize + analyze
            profiling = False
            prof_record = _finish_profile(
                (prof_started_at, epoch)) or prof_record
        if profile_dir and prof_record is None and \
                n_epochs - start_epoch <= 0:
            log_fn("warning: run too short, no profiler trace captured")

        result = {
            "best_val": best_val,
            "best_epoch": best_epoch,
            "best_params": best_params,
            "best_norm": best_norm,
            # short runs can have every block excluded (warmup /
            # first-of-scan-length); fall back to the last block's
            # per-epoch time (compile-inclusive) rather than None
            "epoch_time": float(np.mean(durs)) if durs
            else (dur if n_epochs > start_epoch else None),
            # async mode: mean EXPOSED harvest wait (the eval's device
            # time hides behind subsequent epochs); sync mode: full eval
            # wall-clock like the reference's evaluate() span
            "eval_time": float(np.mean(eval_durs)) if eval_durs else None,
            "comm_cost": comm_cost if comm_measured else None,
            "history": history,
            # the parsed profiling-window record (measured per-phase
            # device time + overlap fraction), None when no window ran
            "profile": prof_record,
        }
        if tcfg.eval and eval_graphs and "test" in eval_graphs and \
                best_params is not None:
            g, mask = eval_graphs["test"]
            result["test_acc"] = self.evaluate(g, mask, params=best_params,
                                               norm=best_norm,
                                               sharded=sharded_eval)
        if metrics is not None:
            summ: Dict[str, Any] = {
                "n_epochs": n_epochs - start_epoch,
                "epoch_time_s": result["epoch_time"],
                "best_val": float(best_val),
                "best_epoch": int(best_epoch),
                "eval_time_s": result["eval_time"],
                "comm_cost": comm_cost if comm_measured else None,
            }
            if "test_acc" in result:
                summ["test_acc"] = float(result["test_acc"])
            if 1 in seen_chunks:
                # XLA's own per-epoch FLOP count (whole-job scale) so
                # the report CLI can derive MFU. Only when the run
                # already compiled the single-epoch program — cost
                # analysis on a fused-only run would pay a whole extra
                # compile for a telemetry extra. Best-effort: some
                # backends expose no analysis.
                try:
                    ca = self.step_cost_analysis()
                    if ca.get("flops"):
                        summ["flops_per_epoch"] = \
                            float(ca["flops"]) * self.P
                except Exception:
                    pass
            metrics.summary(**summ)
        return result

    # ---------------- profiling / staleness ---------------------------

    def step_compiled_text(self) -> str:
        """Optimized-HLO text of the single-epoch train step (the
        metadata op_name scopes are the join key between trace events
        and named phases — obs/profiler.py / obs/anatomy.py). Hits
        jax's compile cache when the step already ran unfused."""
        rng = jax.random.fold_in(self._epoch_rng_base(), 0)
        return self._step.lower(self.state, self.data, rng,
                                jnp.float32(self.loss_scaler.scale)) \
            .compile().as_text()

    def _profile_analysis(self, profile_dir: str):
        """Fold the newest capture under `profile_dir` against the
        compiled step; returns a profile-record body or None."""
        from ..obs.profiler import analyze_trace_dir

        return analyze_trace_dir(profile_dir, self.step_compiled_text())

    def _staleness_drift(self, old_halo, new_halo):
        """Per-layer relative drift between the stale halo carry
        consumed this epoch (`old_halo`, snapshotted pre-dispatch) and
        the fresh one the step shipped (`new_halo`): the approximation
        error the staleness-1 pipeline pays. Returns ({layer:
        {rel_drift, fresh_norm}}, max_rel_drift). The norm program is
        jitted once and reused (cached by pytree structure)."""
        fn = getattr(self, "_staleness_norm_fn", None)
        if fn is None:
            @jax.jit
            def fn(old, new):
                out = {}
                for k in old:
                    d = (new[k].astype(jnp.float32)
                         - old[k].astype(jnp.float32))
                    out[k] = (jnp.sqrt(jnp.sum(d * d)),
                              jnp.sqrt(jnp.sum(jnp.square(
                                  new[k].astype(jnp.float32)))))
                return out

            self._staleness_norm_fn = fn
        norms = jax.device_get(fn(old_halo, new_halo))
        layers = {}
        max_rel = 0.0
        for k, (dn, fresh) in sorted(norms.items()):
            dn, fresh = float(dn), float(fresh)
            # degenerate all-zero fresh buffer: report 1.0 (total
            # drift) rather than an inf that breaks strict JSON readers
            rel = dn / fresh if fresh > 0 else (0.0 if dn == 0.0
                                               else 1.0)
            layers[k] = {"rel_drift": rel, "fresh_norm": fresh}
            max_rel = max(max_rel, rel)
        return layers, max_rel

    # ---------------- cost analysis -----------------------------------

    def step_cost_analysis(self) -> Dict[str, float]:
        """XLA's own cost model for ONE epoch of the train step (keys
        like 'flops' and 'bytes accessed'), for MFU / bandwidth
        reporting. Compiles the single-epoch program if it isn't already
        cached; returns {} when the backend doesn't expose an analysis."""
        rng = jax.random.fold_in(self._epoch_rng_base(), 0)
        ca = self._step.lower(self.state, self.data, rng,
                              jnp.float32(self.loss_scaler.scale)) \
            .compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca:
            return {}
        return {k: float(v) for k, v in ca.items()
                if isinstance(v, (int, float))}

    def est_halo_bytes_per_epoch(self, compressed: bool = True) -> int:
        """Estimated halo wire bytes per epoch: per exchanged graph
        layer, every device ships its halo block forward and the
        boundary gradients back (2x). This is the metrics records'
        `halo_bytes` field; est_ici_bytes_per_epoch adds the gradient
        all-reduce on top. `compressed=True` (default) accounts for the
        --halo-dtype wire narrowing (1 byte under float8, 2 under
        bfloat16); compressed=False gives the uncompressed figure the
        report's compression-ratio line compares against."""
        if self.P == 1:
            return 0
        item = 4 if self.cfg.compute_dtype == jnp.float32 else 2
        if compressed:
            hdt = getattr(self.tcfg, "halo_dtype", "none") or "none"
            if hdt == "float8":
                item = 1
            elif hdt == "bfloat16":
                item = min(item, 2)
        total = 0
        for i in self._graph_layer_range():
            total += 2 * self.P * self.sg.halo_size * self._layer_width(i) \
                * item
        return int(total)

    def est_ici_bytes_per_epoch(self) -> int:
        """Estimated inter-device traffic per epoch: the per-layer halo
        exchange (est_halo_bytes_per_epoch) plus the ring all-reduce of
        the grads (~2x param bytes per device)."""
        if self.P == 1:
            return 0
        n_params = sum(
            int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(self.state["params"])
        )
        return self.est_halo_bytes_per_epoch() + int(2 * self.P * n_params * 4)

    # ---------------- comm cost measurement ---------------------------

    def measure_comm(self, repeats: int = 5) -> Dict[str, float]:
        """Standalone timing of the step's collectives: per-layer halo
        exchange ('comm', the analogue of the reference's exposed
        forward/backward transfer waits, helper/timer/comm_timer.py) and
        the gradient psum ('reduce', reference reducer timing
        train.py:359-361). In pipelined mode the real step overlaps these
        with compute, so this measures the collective cost, not exposed
        wait time."""
        if self.emulated:
            raise RuntimeError(
                "measure_comm is meaningless under emulate_parts: the "
                "collectives are in-device data movement")
        P = self.P
        spec = PartitionSpec(PARTS_AXIS)

        cdt = self.cfg.compute_dtype
        # probe through the same wire dtypes as the train step, so the
        # timed exchange moves the same bytes (incl. --halo-dtype
        # compression in pipelined mode)
        feat_dt, bgrad_dt = halo_transport_dtypes(
            getattr(self.tcfg, "halo_dtype", "none")
            if self.tcfg.enable_pipeline else "none")

        def comm_fn(feat, send_idx, send_mask):
            feat, send_idx, send_mask = feat[0], send_idx[0], send_mask[0]
            outs = []
            for i in self._graph_layer_range():
                w = self._layer_width(i)
                # probe in the compute dtype so the timed exchange moves
                # the same bytes the train step's halo transport does
                h = feat[:, :1].astype(cdt) * jnp.ones((1, w), cdt)
                blocks = exchange_blocks(h, send_idx, send_mask,
                                         PARTS_AXIS, P,
                                         transport_dt=feat_dt)
                outs.append(blocks.sum())
            return jnp.stack(outs).sum()[None] if outs else \
                jnp.zeros((1,), jnp.float32)

        comm_jit = jax.jit(jax.shard_map(
            comm_fn, mesh=self.mesh, in_specs=(spec,) * 3, out_specs=spec,
        ))

        def bgrad_fn(feat):
            # the reverse ring shipping each epoch's halo cotangents
            # back to their owners. BOTH modes move it — vanilla
            # through halo_exchange's VJP, pipelined through the comm
            # carry's explicit return_blocks — so it belongs in
            # Comm(s) for both. The EMA corrections are local
            # arithmetic — no wire traffic.
            feat = feat[0]
            outs = []
            for i in self._graph_layer_range():
                w = self._layer_width(i)
                hg = feat[:1, :1].astype(cdt) * jnp.ones(
                    ((P - 1) * self.sg.b_max, w), cdt)
                outs.append(
                    return_blocks(hg, PARTS_AXIS, P, self.sg.b_max,
                                  transport_dt=bgrad_dt).sum())
            return jnp.stack(outs).sum()[None] if outs else \
                jnp.zeros((1,), jnp.float32)

        bgrad_jit = jax.jit(jax.shard_map(
            bgrad_fn, mesh=self.mesh, in_specs=(spec,), out_specs=spec,
        ))

        def reduce_fn(params):
            return jax.tree_util.tree_map(
                lambda p: jax.lax.psum(p, PARTS_AXIS), params
            )

        reduce_jit = jax.jit(jax.shard_map(
            reduce_fn, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(
                lambda _: PartitionSpec(), self.state["params"]),),
            out_specs=jax.tree_util.tree_map(
                lambda _: PartitionSpec(), self.state["params"]),
        ))

        d = self.data
        args = (d["feat"], d["send_idx"], d["send_mask"])
        jax.block_until_ready(comm_jit(*args))  # compile
        jax.block_until_ready(reduce_jit(self.state["params"]))

        def _med(fn, *a):
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*a))
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        jax.block_until_ready(bgrad_jit(d["feat"]))  # compile
        return {
            "comm": _med(comm_jit, *args),
            "reduce": _med(reduce_jit, self.state["params"]),
            "bgrad": _med(bgrad_jit, d["feat"]),
        }

    # ---------------- evaluation --------------------------------------

    def evaluate(self, g: Graph, mask_key: str, params=None,
                 norm=None, sharded: bool = False) -> float:
        """Evaluate `g` and block for the scalar.

        sharded=False: full-graph eval on one device (reference evaluates
        the full graph on rank 0's CPU, train.py:20-61; we use the
        accelerator). sharded=True: partition-parallel eval through the
        training mesh (parallel/evaluator.py) — use for graphs too big
        for one device."""
        return self.eval_finish(
            self.eval_dispatch(g, mask_key, params, norm, sharded))

    def eval_dispatch(self, g: Graph, mask_key: str, params=None,
                      norm=None, sharded: bool = False):
        """Start an evaluation WITHOUT blocking (jax async dispatch);
        returns an opaque handle for eval_finish. The computation is
        enqueued on the devices before any subsequent train step, so
        later buffer donation cannot race it."""
        if params is None:
            params = self.state["params"]
        if norm is None:
            norm = self.state["norm"]
        if self.emulated:
            # emulate-mode params/norm are ALWAYS the stacked [P, ...]
            # replicas (state, fit snapshots); take one copy for the
            # single-device eval
            params = jax.tree_util.tree_map(lambda v: v[0], params)
            norm = jax.tree_util.tree_map(lambda v: v[0], norm)
        if sharded:
            if self.emulated:
                raise RuntimeError(
                    "sharded eval needs the real device mesh; "
                    "emulate_parts trainers evaluate full-graph")
            ev = self._get_sharded_evaluator(g)
            return ("sharded", ev, ev.counts(mask_key, params, norm))
        c = self._full_eval_cache(g)
        logits = self._eval_run(params, norm, c["feat"], c["edge_src"],
                                c["edge_dst"], c["in_deg"], c["n"])
        return ("full", c, logits, mask_key)

    def eval_finish(self, handle) -> float:
        """Resolve a dispatched evaluation to its scalar metric (blocks
        only if the device computation hasn't completed yet)."""
        if handle[0] == "sharded":
            _, ev, counts = handle
            return ev.finish(counts)
        _, c, logits, mask_key = handle
        logits = np.asarray(logits)
        m = np.asarray(c["graph"].ndata[mask_key])
        return calc_acc(logits[m], c["label"][m])

    def _get_sharded_evaluator(self, g: Graph):
        from .evaluator import ShardedEvaluator

        key = id(g)
        if key not in self._sharded_eval_cache:
            self._sharded_eval_cache[key] = (
                ShardedEvaluator.for_graph(self, g), g)
        return self._sharded_eval_cache[key][0]

    def _full_eval_cache(self, g: Graph):
        key = id(g)
        if key not in self._eval_cache:
            from ..native import stable_argsort

            n = g.num_nodes
            # CSR-sort eval edges so the sorted segment reduction applies
            order = stable_argsort(g.dst)
            self._eval_cache[key] = {
                "graph": g,  # strong ref: keeps id(g) valid while cached
                # lane_pad trainers rewrote layer_sizes[0]; eval input
                # must be padded to the same width
                "feat": jnp.asarray(_pad_cols(
                    g.ndata["feat"], getattr(self, "_feat_pad", 0))),
                "label": g.ndata["label"],
                "edge_src": jnp.asarray(g.src[order].astype(np.int32)),
                "edge_dst": jnp.asarray(g.dst[order].astype(np.int32)),
                "in_deg": jnp.asarray(
                    np.maximum(g.in_degrees(), 1).astype(np.float32)
                ),
                "n": n,
            }
        return self._eval_cache[key]
