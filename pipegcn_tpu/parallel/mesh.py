"""Device mesh utilities.

The reference runs one OS process per partition connected by gloo
(main.py:44-59, train.py:408-416). Here the whole job is a single SPMD
program over a 1-D `jax.sharding.Mesh` with axis 'parts' — one device per
graph partition; collectives ride ICI/DCN and XLA schedules the overlap.
Multi-host works the same way: `jax.distributed.initialize` makes
`jax.devices()` span hosts, and the mesh covers the global device list.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

PARTS_AXIS = "parts"


def make_mesh(n_parts: int, devices=None) -> Mesh:
    """1-D mesh over the first `n_parts` devices."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_parts:
        raise ValueError(
            f"need {n_parts} devices for {n_parts} partitions, have "
            f"{len(devices)} (hint: XLA_FLAGS=--xla_force_host_platform_"
            f"device_count=N emulates N devices on CPU)"
        )
    return Mesh(np.array(devices[:n_parts]), (PARTS_AXIS,))
