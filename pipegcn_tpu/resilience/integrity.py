"""Silent-data-corruption (SDC) defense: the integrity plane.

Every defense before this PR catches *loud* failures — non-finite
values (the numerics tripwire), cross-rank divergence (the desync
detector), corruption *at rest* on disk (checkpoint CRC digests).
Nothing catches **wrong-but-finite** device state: a flipped bit in an
uploaded gather table, a corrupted halo payload, a defective core
computing plausible garbage ("Cores that don't count", HotOS '21).
This module adds three independent detectors plus the containment
bookkeeping, all cadence-gated by ``--integrity-check-every N``:

  fletcher digests   order-independent two-accumulator bit sums
                     (uint32 wraparound — any single bit flip changes
                     the sum with certainty) computed by a tiny jitted
                     program on device and by bit-identical numpy on
                     the host, so device state can be compared against
                     host-built references and across time
  IntegrityPlane     the per-trainer orchestrator fit() drives at
                     check boundaries: scrubs static device tables
                     against their baselines, verifies the pipelined
                     carry (halo features attributed separately from
                     the rest) and the replicated params against
                     digests captured when they were last produced,
                     and runs the Freivalds-style SpMM verification
  freivalds check    probabilistic algebraic verification of the
                     production aggregation kernel: project the
                     feature matrix onto a per-epoch random +-1 vector
                     r, aggregate the single-column result through the
                     PRODUCTION kernel (tables and all), and compare
                     against an independent raw-edge host reference —
                     O(nnz + n*d) instead of re-running the epoch.
                     A flipped gather-table index routes the wrong row
                     and the projections disagree; a defective core
                     miscomputing the kernel disagrees the same way.

Coverage window, stated honestly (docs/RESILIENCE.md): the digest
scrub compares state at dispatch boundaries, so it catches corruption
of boundary-resident state (exactly where host-side bit-flip injection
lands, and where DMA'd state sits between programs); mid-scan HBM is
ECC territory. The wire checksum lane (parallel/halo.py) covers the
ICI transport inside the step; Freivalds covers the compute datapath.

Recovery is per target class: ``tables`` rebuilds the dirty shard's
device tables from the host artifact (the PR-13 dirty-shard path),
``halo``/``carry`` flush the pipelined carry (epoch-0 warmup
semantics), ``params`` roll back to the last good snapshot — agreed
across ranks through the widened FaultConsensus word so the pod moves
in lockstep. Recurring SDC on one rank writes a quarantine request
marker the elastic supervisor consumes (resilience/elastic.py).

Host-side orchestration; the only device work is the small jitted
digest/projection programs, dispatched at cadence only.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# target classes the chaos grammar can flip and the records attribute
TARGETS = ("params", "carry", "tables", "halo")

# SDC codes riding the consensus word (coord.IDX_SDC_CODE): 0 = none
SDC_CODES = {t: i + 1 for i, t in enumerate(TARGETS)}
SDC_NAMES = {v: k for k, v in SDC_CODES.items()}

# a member whose run detects this many SDC events is asked to leave
# the fleet (quarantine marker, consumed by the elastic supervisor)
QUARANTINE_STRIKES = 2


# ---------------- fletcher digests ------------------------------------

def _as_u32(a: np.ndarray) -> np.ndarray:
    """Host bit view of any array as a flat uint32 vector (sub-word
    dtypes zero-extend per element, so the view — and therefore the
    digest — is identical to the device program's)."""
    a = np.ascontiguousarray(a)
    if a.dtype == np.bool_:
        a = a.astype(np.uint8)
    size = a.dtype.itemsize
    if size == 1:
        return a.reshape(-1).view(np.uint8).astype(np.uint32)
    if size == 2:
        return a.reshape(-1).view(np.uint16).astype(np.uint32)
    if size == 4:
        return a.reshape(-1).view(np.uint32)
    # 8-byte dtypes: fold the two 32-bit halves
    u = a.reshape(-1).view(np.uint32)
    return u


def host_digest(a: np.ndarray) -> np.ndarray:
    """[2] uint32 fletcher-style digest of an array's bits: a plain
    wraparound sum and an odd-weighted sum. Order-independent (integer
    wraparound addition commutes), so the device reduction — whatever
    order XLA picks — produces the identical pair. Any single bit flip
    changes the plain sum by +-2^k != 0 (mod 2^32), so detection of
    the one-flip fault model is certain, not probabilistic."""
    u = _as_u32(np.asarray(a))
    with np.errstate(over="ignore"):
        n = u.shape[0]
        w = (np.arange(n, dtype=np.uint32) << np.uint32(1)) | np.uint32(1)
        s1 = np.add.reduce(u, dtype=np.uint32) if n else np.uint32(0)
        s2 = (np.add.reduce(u * w, dtype=np.uint32) if n
              else np.uint32(0))
    return np.asarray([s1, s2], np.uint32)


def device_digest(x):
    """Jittable counterpart of :func:`host_digest` — same bit view,
    same two wraparound sums, returned as a [2] uint32 array."""
    import jax
    import jax.numpy as jnp

    x = x.reshape(-1)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    size = jnp.dtype(x.dtype).itemsize
    # bitcast to the same-width unsigned view, then widen to uint32
    if size == 1:
        u = jax.lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    elif size == 2:
        u = jax.lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    elif size == 4:
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    else:
        u = jax.lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
    n = u.shape[0]
    if n == 0:
        return jnp.zeros((2,), jnp.uint32)
    w = (jnp.arange(n, dtype=jnp.uint32) << jnp.uint32(1)) | jnp.uint32(1)
    s1 = jnp.sum(u, dtype=jnp.uint32)
    s2 = jnp.sum(u * w, dtype=jnp.uint32)
    return jnp.stack([s1, s2])


def _spans_processes(a) -> bool:
    """True for a jax.Array whose shards live partly on OTHER
    processes (fetching it whole would need a collective). Each rank
    then digests only its addressable shards — it guards its own
    rows, and the fault consensus aggregates detection across ranks."""
    import jax

    return (isinstance(a, jax.Array)
            and not a.is_fully_addressable
            and not a.is_fully_replicated)


def digest_tree(tree: Any) -> Dict[str, np.ndarray]:
    """{path: [2] uint32} device digests of every leaf of a pytree of
    device (or host) arrays — one jitted program per distinct leaf
    structure, cached by jax's own jit cache. Leaves sharded across
    processes fold the wraparound digests of the LOCAL shards only
    (order-independent, so the fold is stable across time as long as
    the sharding is — which is exactly the comparison window)."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: Dict[str, np.ndarray] = {}
    fn = _digest_many()
    keys = [jax.tree_util.keystr(p) for p, _ in leaves]
    vals = [v for _, v in leaves]
    whole = [(k, v) for k, v in zip(keys, vals)
             if not _spans_processes(v)]
    if whole:
        for (k, _), d in zip(whole, fn([v for _, v in whole])):
            out[k] = np.asarray(d)
    one = _digest_one()
    for k, v in zip(keys, vals):
        if not _spans_processes(v):
            continue
        acc = np.zeros(2, np.uint32)
        for sh in v.addressable_shards:
            acc = acc + np.asarray(one(sh.data))  # uint32 wraps
        out[k] = acc
    return out


_DIGEST_FN = None


def _digest_many():
    """The shared jitted list-of-arrays digest program."""
    global _DIGEST_FN
    if _DIGEST_FN is None:
        import jax

        _DIGEST_FN = jax.jit(
            lambda arrs: [device_digest(a) for a in arrs])
    return _DIGEST_FN


_DIGEST_ONE = None


def _digest_one():
    """Jitted single-array digest — the per-local-shard program the
    multi-process paths use (a shard is a plain one-device array)."""
    global _DIGEST_ONE
    if _DIGEST_ONE is None:
        import jax

        _DIGEST_ONE = jax.jit(device_digest)
    return _DIGEST_ONE


def shard_digests(a) -> np.ndarray:
    """[P, 2] uint32 per-leading-index digests of a stacked [P, ...]
    device array — the dirty-shard attribution the table scrubber
    needs (which shard's rows rotted decides which shard rebuilds).
    When the array spans processes, only this rank's rows are digested
    (the rest stay zero in BOTH the baseline and the current pass, so
    they always compare equal): every shard is still guarded, by the
    rank that owns it."""
    fn = _shard_digest_fn()
    if _spans_processes(a):
        out = np.zeros((int(a.shape[0]), 2), np.uint32)
        for sh in a.addressable_shards:
            start = sh.index[0].start or 0
            d = np.asarray(fn(sh.data))
            out[start:start + d.shape[0]] = d
        return out
    return np.asarray(fn(a))


_SHARD_DIGEST_FN = None


def _shard_digest_fn():
    global _SHARD_DIGEST_FN
    if _SHARD_DIGEST_FN is None:
        import jax

        _SHARD_DIGEST_FN = jax.jit(
            lambda a: jax.vmap(device_digest)(a))
    return _SHARD_DIGEST_FN


# ---------------- bit-flip injection (chaos) --------------------------

def _local_rows(a) -> Tuple[List[int], np.ndarray]:
    """(global row indices, host rows) of the process-local shards of
    a stacked [P, ...] array — the multi-process-safe fetch. Single
    process (or replicated): every row."""
    if _spans_processes(a):
        pairs = []
        for sh in a.addressable_shards:
            start = sh.index[0].start or 0
            data = np.asarray(sh.data)
            for i in range(data.shape[0]):
                pairs.append((start + i, data[i]))
        pairs.sort(key=lambda t: t[0])
        return ([p for p, _ in pairs],
                np.stack([d for _, d in pairs]))
    arr = np.asarray(a)
    return list(range(arr.shape[0])), arr


def flip_bit(a: np.ndarray, *, bit: int = 0, index: int = 0) -> np.ndarray:
    """Return a copy of `a` with one bit flipped in the element at flat
    position `index` — the chaos lane's host-side SDC model. `bit`
    counts from the element's LSB; out-of-range values wrap."""
    a = np.array(a, copy=True)
    flat = a.reshape(-1)
    if flat.size == 0:
        return a
    index = int(index) % flat.size
    view = _as_u32_inplace(flat)
    width = 8 * min(a.dtype.itemsize, 4)
    view[index % view.size] ^= np.uint32(1) << np.uint32(bit % width)
    return a


def _as_u32_inplace(flat: np.ndarray) -> np.ndarray:
    size = flat.dtype.itemsize
    if flat.dtype == np.bool_:
        return flat.view(np.uint8)
    if size == 1:
        return flat.view(np.uint8)
    if size == 2:
        return flat.view(np.uint16)
    return flat.view(np.uint32)


# ---------------- quarantine markers ----------------------------------

def quarantine_marker_path(coord_dir: str, member: int) -> str:
    return os.path.join(coord_dir, f"quarantine-m{int(member)}.json")


def request_quarantine(coord_dir: str, member: int, *, reason: str,
                       strikes: int, targets: List[str]) -> str:
    """Durable quarantine request for `member`, consumed by the
    elastic supervisor at its next membership replan. Written with the
    temp+rename discipline every durable artifact here uses."""
    os.makedirs(coord_dir, exist_ok=True)
    path = quarantine_marker_path(coord_dir, member)
    payload = {"member": int(member), "reason": str(reason),
               "strikes": int(strikes),
               "targets": sorted(set(targets)),
               "time_unix": time.time()}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_quarantines(coord_dir: str) -> Dict[int, Dict[str, Any]]:
    """{member: marker payload} for every quarantine marker present.
    Unreadable markers still quarantine (fail-closed: a half-written
    marker means the member WAS asking to leave)."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = os.listdir(coord_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("quarantine-m")
                and name.endswith(".json")):
            continue
        try:
            member = int(name[len("quarantine-m"):-len(".json")])
        except ValueError:
            continue
        try:
            with open(os.path.join(coord_dir, name)) as f:
                out[member] = json.load(f)
        except (OSError, ValueError):
            out[member] = {"member": member, "reason": "unreadable marker"}
    return out


def clear_quarantine(coord_dir: str, member: int) -> bool:
    """Operator-initiated release: remove the marker so the next
    rejoin request can fold the member back in. Returns True when a
    marker was actually removed."""
    try:
        os.remove(quarantine_marker_path(coord_dir, member))
        return True
    except OSError:
        return False


# ---------------- the plane -------------------------------------------

@dataclasses.dataclass
class CheckResult:
    """One detector's verdict at one check boundary."""

    check: str                   # scrub | freivalds | wire
    outcome: str                 # ok | mismatch
    target: Optional[str] = None  # params | carry | tables | halo
    detail: str = ""
    dirty_shards: Tuple[int, ...] = ()
    overhead_s: float = 0.0


class IntegrityPlane:
    """Per-trainer SDC detector set, driven by fit() at cadence.

    Lifecycle: ``baseline(trainer)`` captures the static-data digests
    once (and again after any table rebuild / graph delta);
    ``note_dynamic(trainer)`` captures params+carry digests right
    after a dispatch lands (the state's production point);
    ``check(trainer, epoch)`` at the NEXT boundary re-digests and
    compares, plus scrubs the static tables and runs Freivalds.
    """

    # relative tolerance for the Freivalds projection comparison: the
    # kernel accumulates in f32 while the host reference uses f64, so
    # exact equality is not the contract — a flipped table index
    # mis-routes whole rows and lands orders of magnitude above this
    FREIVALDS_RTOL = 5e-2

    def __init__(self, check_every: int, *, rank: int = 0,
                 log: Callable[[str], None] = print):
        self.check_every = max(int(check_every), 0)
        self.rank = int(rank)
        self.log = log
        self._static_refs: Optional[Dict[str, np.ndarray]] = None
        self._dynamic_refs: Optional[Dict[str, Dict[str, np.ndarray]]] = None
        # detection counters for containment (quarantine strikes)
        self.detections: Dict[str, int] = {}
        self.checks_run = 0

    @property
    def enabled(self) -> bool:
        return self.check_every > 0

    def due(self, epoch: int) -> bool:
        return (self.enabled and epoch > 0
                and epoch % self.check_every == 0)

    # ---------------- baselines ---------------------------------------

    @staticmethod
    def _static_keys(trainer) -> List[str]:
        """Every static device array the scrubber guards: kernel
        gather tables, CSR slabs, send-lists, masks, degrees, features
        — everything in trainer.data (all of it is static between
        graph deltas; params/opt/carry live in trainer.state)."""
        return sorted(trainer.data.keys())

    def baseline(self, trainer) -> float:
        """(Re)capture the static-data digest baseline. Called at
        plane arm time and after any legitimate table rebuild."""
        t0 = time.perf_counter()
        self._static_refs = {
            k: shard_digests(trainer.data[k])
            for k in self._static_keys(trainer)
        }
        return time.perf_counter() - t0

    def note_dynamic(self, trainer) -> float:
        """Capture params + carry digests at their production point
        (right after a dispatch at a check boundary). The next
        boundary's check() compares against these."""
        t0 = time.perf_counter()
        refs: Dict[str, Dict[str, np.ndarray]] = {
            "params": digest_tree(trainer.state["params"]),
        }
        comm = trainer.state.get("comm") or {}
        if comm:
            refs["halo"] = digest_tree(comm.get("halo", {}))
            rest = {k: v for k, v in comm.items() if k != "halo"}
            refs["carry"] = digest_tree(rest)
        self._dynamic_refs = refs
        return time.perf_counter() - t0

    def drop_dynamic(self) -> None:
        """Forget the params/carry baselines (rollback, carry flush,
        restore — the state legitimately changed outside a dispatch)."""
        self._dynamic_refs = None

    # ---------------- checks ------------------------------------------

    def scrub_static(self, trainer) -> CheckResult:
        """Compare every static device table against its baseline;
        mismatches name the dirty shards for the rebuild path."""
        t0 = time.perf_counter()
        if self._static_refs is None:
            self.baseline(trainer)
            return CheckResult("scrub", "ok", target="tables",
                               detail="baseline captured",
                               overhead_s=time.perf_counter() - t0)
        bad: List[str] = []
        dirty: set = set()
        for k in self._static_keys(trainer):
            ref = self._static_refs.get(k)
            if ref is None:  # new key (table rebuild added it)
                continue
            cur = shard_digests(trainer.data[k])
            if cur.shape != ref.shape:
                bad.append(k)
                dirty.update(range(cur.shape[0]))
                continue
            rows = np.nonzero(np.any(cur != ref, axis=-1))[0]
            if rows.size:
                bad.append(k)
                dirty.update(int(r) for r in rows)
        dt = time.perf_counter() - t0
        if not bad:
            return CheckResult("scrub", "ok", target="tables",
                               overhead_s=dt)
        return CheckResult(
            "scrub", "mismatch", target="tables",
            detail="digest mismatch in " + ", ".join(sorted(bad)[:6]),
            dirty_shards=tuple(sorted(dirty)), overhead_s=dt)

    def verify_dynamic(self, trainer) -> List[CheckResult]:
        """Compare params and carry digests against their production
        baselines — the boundary-resident at-rest window."""
        t0 = time.perf_counter()
        if self._dynamic_refs is None:
            return []
        out: List[CheckResult] = []
        cur: Dict[str, Dict[str, np.ndarray]] = {
            "params": digest_tree(trainer.state["params"]),
        }
        comm = trainer.state.get("comm") or {}
        if comm and "halo" in self._dynamic_refs:
            cur["halo"] = digest_tree(comm.get("halo", {}))
            rest = {k: v for k, v in comm.items() if k != "halo"}
            cur["carry"] = digest_tree(rest)
        dt = time.perf_counter() - t0
        for target, refs in self._dynamic_refs.items():
            now = cur.get(target)
            if now is None:
                continue
            bad = [k for k, v in refs.items()
                   if not np.array_equal(now.get(k), v)]
            if bad:
                out.append(CheckResult(
                    "scrub", "mismatch", target=target,
                    detail="digest mismatch in "
                           + ", ".join(sorted(bad)[:6]),
                    overhead_s=dt))
            else:
                out.append(CheckResult("scrub", "ok", target=target,
                                       overhead_s=dt))
        return out

    def freivalds(self, trainer, epoch: int) -> Optional[CheckResult]:
        """Randomized algebraic verification of the production SpMM:
        aggregate the feature matrix projected onto a random +-1
        vector through the PRODUCTION kernel (gather tables, slab
        plans and all), and compare against an independent raw-edge
        reference computed on the host from the partition artifact.
        O(nnz + n*d). GAT aggregation is parameter-dependent and is
        covered by the scrubber only."""
        if getattr(trainer.cfg, "model", "") == "gat" or \
                getattr(trainer, "_gat_tables", None) is not None:
            return None
        t0 = time.perf_counter()
        sg = trainer.sg
        rng = np.random.default_rng(
            (int(epoch) * 1000003 + 12345) & 0xFFFFFFFF)
        feat_w = int(trainer.data["feat"].shape[-1])
        r = rng.integers(0, 2, size=feat_w).astype(np.float32) * 2 - 1
        try:
            u, w_fbuf = self._freivalds_device(trainer, r)
        except Exception as exc:  # noqa: BLE001 — detector, not a crash
            return CheckResult(
                "freivalds", "ok", target="tables",
                detail=f"skipped: {exc!r}"[:160],
                overhead_s=time.perf_counter() - t0)
        # multi-process runs verify the LOCAL shards only (each rank
        # guards its own; the consensus word aggregates detection)
        rows, u = _local_rows(u)               # [k, n_max]
        _, w_fbuf = _local_rows(w_fbuf)        # [k, n_src_rows]
        u = u.astype(np.float64)
        w_fbuf = w_fbuf.astype(np.float64)
        # host reference: raw-edge mean aggregation per shard from the
        # partition artifact (an independent code path end to end)
        es = np.asarray(sg.edge_src)
        ed = np.asarray(sg.edge_dst)
        deg = np.asarray(sg.in_deg, np.float64)
        n_max = sg.n_max
        worst = 0.0
        for j, p in enumerate(rows):
            acc = np.zeros(n_max + 1, np.float64)
            np.add.at(acc, ed[p], w_fbuf[j][es[p]])
            v = acc[:n_max] / deg[p]
            scale = max(float(np.max(np.abs(v))), 1.0)
            worst = max(worst, float(np.max(np.abs(u[j] - v))) / scale)
        dt = time.perf_counter() - t0
        if worst > self.FREIVALDS_RTOL:
            return CheckResult(
                "freivalds", "mismatch", target="tables",
                detail=f"projection residual {worst:.3e} "
                       f"(rtol {self.FREIVALDS_RTOL:g})",
                overhead_s=dt)
        return CheckResult("freivalds", "ok", target="tables",
                           detail=f"residual {worst:.3e}",
                           overhead_s=dt)

    def _freivalds_device(self, trainer, r: np.ndarray):
        """Device half of the Freivalds check: project, halo-exchange
        the projection, aggregate through the production kernel.
        Returns (u [P, n_max], w_fbuf [P, n_src_rows]) on host."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec

        from ..parallel.halo import halo_exchange
        from ..parallel.mesh import PARTS_AXIS
        from ..ops.spmm import spmm_mean

        sg = trainer.sg
        n_max, P = sg.n_max, trainer.P
        n_src = n_max + sg.halo_size
        data = trainer.data
        use_tables = ("bkt_fwd_inv" in data) or ("blk_a" in data) \
            or ("blk_a_bits" in data)
        keys = ["feat", "in_deg", "send_idx", "send_mask"]
        if use_tables:
            keys += [k for k in data
                     if k.startswith(("bkt_", "blk_", "blkrem_"))]
        else:
            keys += ["edge_src", "edge_dst"]
        d_in = {k: data[k] for k in keys}
        r_dev = jnp.asarray(r)

        def body(d):
            d = {k: v[0] for k, v in d.items()}
            w = (d["feat"].astype(jnp.float32) @ r_dev)[:, None]
            wb = halo_exchange(w, d["send_idx"], d["send_mask"],
                               PARTS_AXIS, P)
            if use_tables:
                # transport=False: the verification must exercise the
                # table STRUCTURE in clean precision, not the narrowed
                # gather transport (whose quantization is by design)
                spmm = trainer.make_device_spmm_closure(
                    d, n_max=n_max, n_src_rows=n_src, transport=False)
                agg = spmm(wb)
            else:
                agg = spmm_mean(
                    wb, d["edge_src"], d["edge_dst"], d["in_deg"],
                    n_max, trainer.cfg.spmm_chunk,
                    trainer.cfg.sorted_edges)
            return agg[:, 0][None], wb[:, 0][None]

        spec = PartitionSpec(PARTS_AXIS)
        if trainer.emulated:
            tm = jax.tree_util.tree_map

            def vbody(d):
                a, b = body(tm(lambda v: v[None], d))
                return a[0], b[0]

            fn = jax.jit(jax.vmap(vbody, axis_name=PARTS_AXIS))
        else:
            fn = jax.jit(jax.shard_map(
                body, mesh=trainer.mesh,
                in_specs=(jax.tree_util.tree_map(lambda _: spec, d_in),),
                out_specs=(spec, spec)))
        u, wb = fn(d_in)
        return jax.device_get(u), jax.device_get(wb)

    # ---------------- the per-boundary driver -------------------------

    def run_checks(self, trainer, epoch: int, *,
                   deep: bool = True) -> List[CheckResult]:
        """Detectors in attribution order. The dynamic digest compare
        is cheap and runs at EVERY boundary (the params/carry refs are
        re-captured after every dispatch, so any boundary can verify
        them); the static-table scrub and the Freivalds projection are
        the expensive half and run only when ``deep`` (the cadence
        boundaries). Mismatch counters feed the quarantine-strike
        policy."""
        self.checks_run += 1
        results: List[CheckResult] = []
        results.extend(self.verify_dynamic(trainer))
        if deep:
            results.append(self.scrub_static(trainer))
            fr = self.freivalds(trainer, epoch)
            if fr is not None:
                results.append(fr)
        for res in results:
            if res.outcome == "mismatch" and res.target:
                self.detections[res.target] = \
                    self.detections.get(res.target, 0) + 1
        return results

    def total_detections(self) -> int:
        return sum(self.detections.values())

    def should_quarantine(self) -> bool:
        return self.total_detections() >= QUARANTINE_STRIKES
