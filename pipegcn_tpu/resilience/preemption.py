"""Preemption-aware shutdown.

TPU pods get preempted routinely; a SIGTERM that kills the process
mid-epoch loses everything since the last periodic checkpoint, and a
run supervisor cannot tell "crashed, don't retry" from "preempted,
resume me" without a distinct exit status.

The handler turns SIGTERM/SIGINT into a *request* flag; the epoch loop
checks it at each dispatch boundary (the only point where the donated
device state is consistent and labeled) and raises :class:`Preempted`,
which rides the trainer's existing crash-checkpoint path — process 0
saves, every process exits. The CLI maps :class:`Preempted` to
:data:`EXIT_PREEMPTED` (75, EX_TEMPFAIL: "transient failure, retry"),
so ``run.sh || [ $? -eq 75 ] && rerun --resume`` is all a supervisor
needs.

Handler installation is opt-in and guarded: only the CLI installs, only
in the main thread (signal.signal raises elsewhere), never when
``PIPEGCN_NO_SIGNAL_HANDLERS=1`` (nested launchers / test harnesses
that own their signals), and the previous handlers are restored on
exit. A second SIGINT raises KeyboardInterrupt immediately so an
impatient Ctrl-C Ctrl-C still kills the run the normal way.

Multi-host SPMD: the platform delivers SIGTERM to every host; each
process trips its own flag at the same epoch boundary (the SPMD loop is
lockstep), process 0 writes the checkpoint (trainer crash-handler
guard), and all ranks exit 75 — no collective is entered one-sided.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
from typing import Optional

# EX_TEMPFAIL: the conventional "transient, please retry" status
EXIT_PREEMPTED = 75


def classify_exit(returncode: int) -> str:
    """Fold a child's exit status into the three outcomes the elastic
    supervisor (resilience/elastic.py) acts on:

      "completed"  0 — training finished, don't relaunch
      "resumable"  75 — checkpointed and asked to be resumed (the
                   survivor side of a PeerLost, or a preemption)
      "dead"       anything else, including negative codes (killed by
                   signal: SIGKILL'd, OOM'd, crashed) — a membership
                   event: redistribute its partitions
    """
    if returncode == 0:
        return "completed"
    if returncode == EXIT_PREEMPTED:
        return "resumable"
    return "dead"


class Preempted(Exception):
    """Raised at an epoch boundary after a shutdown request.

    `epoch` is the number of completed epochs — the resumable
    checkpoint (when a checkpoint dir is configured) carries the same
    value, so `--resume` continues exactly where the run stopped.
    """

    def __init__(self, epoch: int, reason: str = "signal"):
        super().__init__(f"preempted at epoch {epoch} ({reason})")
        self.epoch = int(epoch)
        self.reason = reason


class PreemptionHandler:
    """Shutdown-request flag + optional signal installation."""

    def __init__(self):
        self._reason: Optional[str] = None

    @property
    def requested(self) -> bool:
        return self._reason is not None

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    def request(self, reason: str) -> None:
        """Ask for a checkpoint + exit at the next epoch boundary.
        Idempotent; callable from signal context (only sets a flag)."""
        if self._reason is None:
            self._reason = reason

    @contextlib.contextmanager
    def installed(self, enabled: bool = True):
        """Context manager installing SIGTERM/SIGINT handlers around a
        training run, restoring the previous handlers on exit. A no-op
        (flag-only operation still works) when `enabled` is False, when
        not in the main thread, or under PIPEGCN_NO_SIGNAL_HANDLERS=1."""
        if (not enabled
                or os.environ.get("PIPEGCN_NO_SIGNAL_HANDLERS") == "1"
                or threading.current_thread() is not threading.main_thread()):
            yield self
            return

        def _on_signal(signum, frame):
            if self.requested and signum == signal.SIGINT:
                # second Ctrl-C: the user wants out NOW
                raise KeyboardInterrupt
            try:
                name = signal.Signals(signum).name
            except ValueError:
                name = str(signum)
            self.request(name)

        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.getsignal(sig)
            signal.signal(sig, _on_signal)
        try:
            yield self
        finally:
            for sig, h in prev.items():
                signal.signal(sig, h)
