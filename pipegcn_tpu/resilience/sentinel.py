"""Divergence sentinel: host-side health checks over per-epoch metrics.

The jitted step already returns the global mean loss and the l2 norm of
the reduced gradient (trainer.py step metrics, PR 1), so detection is
free — no extra device work, just float comparisons on scalars the
epoch loop was going to harvest anyway. The sentinel is a pure host
object: fit() asks it to `check` each dispatched block and performs the
rollback itself (restore last good state, scale the LR down, optionally
flush the stale halo carry), bounded by `max_retries` consecutive
failed attempts.

Trip conditions, in order:
  - non-finite loss or grad norm (always on)
  - grad norm above `grad_norm_max` (absolute cap; 0 disables)
  - loss above `loss_factor` x the median of the recent healthy-loss
    window (relative explosion; needs `warmup` healthy epochs first so
    the noisy first epochs never trip it; 0 disables)

Only healthy blocks feed the baseline window, so a slow upward drift
into divergence cannot drag the baseline up with it.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import numpy as np


class DivergenceError(RuntimeError):
    """Training diverged and the bounded retries were exhausted."""


@dataclasses.dataclass
class SentinelConfig:
    # relative explosion threshold: loss > loss_factor * median(recent
    # healthy losses); 0 disables the relative check
    loss_factor: float = 10.0
    # absolute grad-norm cap; 0 disables
    grad_norm_max: float = 0.0
    # consecutive failed recovery attempts before giving up
    max_retries: int = 3
    # LR multiplier applied on every trip (1.0 = no backoff)
    lr_backoff: float = 0.5
    # zero the pipelined comm carry on rollback: the retried epoch then
    # consumes zero halos exactly like epoch 0 — the staleness-1
    # pipeline restarts its warmup instead of re-ingesting boundary
    # data produced by the divergent trajectory
    flush_on_trip: bool = True
    # epochs between in-memory last-good snapshots (a host copy of the
    # full state; cadence bounds both the copy cost and the work lost
    # to a rollback)
    snapshot_every: int = 25
    # healthy epochs required before the relative loss check arms
    warmup: int = 5
    # healthy-loss window the baseline median is taken over
    window: int = 32


class DivergenceSentinel:
    """Stateful checker; one instance per fit() run."""

    def __init__(self, cfg: Optional[SentinelConfig] = None):
        self.cfg = cfg or SentinelConfig()
        self._healthy = deque(maxlen=max(int(self.cfg.window), 1))
        self.trips = 0

    def baseline(self) -> Optional[float]:
        """Median of the recent healthy losses, or None pre-warmup."""
        if len(self._healthy) < max(int(self.cfg.warmup), 1):
            return None
        return float(np.median(np.asarray(self._healthy)))

    def check(self, first_epoch: int, losses, grad_norms) -> Optional[str]:
        """Inspect one dispatched block (epochs [first_epoch,
        first_epoch + k)). Returns a human-readable trip reason, or
        None when healthy — in which case the losses join the baseline
        window."""
        cfg = self.cfg
        losses = np.atleast_1d(np.asarray(losses, np.float64))
        gn = np.atleast_1d(np.asarray(grad_norms, np.float64))
        bad = ~np.isfinite(losses)
        if bad.any():
            e = first_epoch + int(np.argmax(bad))
            self.trips += 1
            return f"non-finite loss {float(losses[np.argmax(bad)])} " \
                   f"at epoch {e}"
        bad = ~np.isfinite(gn)
        if bad.any():
            e = first_epoch + int(np.argmax(bad))
            self.trips += 1
            return f"non-finite grad norm at epoch {e}"
        if cfg.grad_norm_max > 0:
            bad = gn > cfg.grad_norm_max
            if bad.any():
                e = first_epoch + int(np.argmax(bad))
                self.trips += 1
                return (f"grad norm {gn[np.argmax(bad)]:.4g} > cap "
                        f"{cfg.grad_norm_max:.4g} at epoch {e}")
        base = self.baseline() if cfg.loss_factor > 0 else None
        if base is not None and base > 1e-12:
            bad = losses > cfg.loss_factor * base
            if bad.any():
                e = first_epoch + int(np.argmax(bad))
                self.trips += 1
                return (f"loss {losses[np.argmax(bad)]:.4g} > "
                        f"{cfg.loss_factor:g}x healthy median "
                        f"{base:.4g} at epoch {e}")
        self._healthy.extend(losses.tolist())
        return None
