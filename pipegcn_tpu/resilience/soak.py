"""Seeded full-stack chaos soak: composed fault schedules + invariants.

The unit tests in tests/ each drill ONE recovery path; this module
drills their COMPOSITION. A soak run is a sequence of episodes; each
episode derives a deterministic fault schedule from
``seed * 1000003 + episode`` (same seed -> same schedules -> same
verdict), runs an elastic-supervised trainer (cli.elastic subprocess)
with a streaming delta plan and the schedule as ``--fault-plan``, then
a final clean ``--resume`` (cli.main subprocess), and checks five
structural invariants over the artifacts left behind:

  checkpoint  the newest digest-valid generation exists and verifies
              (utils/checkpoint.py per-leaf CRCs); after the clean
              resume it sits at the nominal epoch count
  ledger      membership generations are contiguous from 0 and every
              record is CRC-clean (resilience/elastic.py)
  metrics     every metrics JSONL parses (a torn FINAL line is the one
              legal wound — SIGKILL mid-write) and the union of epoch
              records across generations + the resume covers every
              epoch exactly 0..n_epochs-1: nothing silently lost, even
              through the io-degraded ring-buffer path (obs/metrics.py)
  tickets     (``serve`` episodes only) the serving fleet drill's
              summary reports conserved=drained=True — zero accepted
              tickets lost (serve/fleet.py)
  autoscale   (``autoscale`` episodes only) the closed-loop drill —
              flash-crowd traffic over a 1-replica fleet with one
              replica-kill and one mid-crowd net-partition — ends with
              the replica-count trajectory matching the ledger's
              spawn/retire records one-to-one with the ``autoscale``
              decision records, at least one crowd-provoked scale-up,
              and tickets conserved through the scale events
              (serve/autoscale.py, check_autoscale)
  journal     (invariant #9) the clean resume's post-run rebuild audit
              — the ``journal`` op="verify" record — shows the
              trainer's topo_generation at the NOMINAL delta count
              (every scheduled delta applied exactly once through any
              composition of WAL replay, plan re-derivation, and live
              delivery) and the patched device tables digest-matching
              a from-scratch ShardedGraph.build (stream/journal.py)
  resume      the final clean ``--resume`` exits 0 and reaches
              n_epochs
  diagnosis   the automated postmortem (obs/postmortem.py) over the
              episode dir reaches the RIGHT verdict: ``clean-exit``
              when the first five invariants are green (every injected
              fault was recovered and the resume completed), or a
              class consistent with the injected schedule when they
              are red — every red episode must yield an explained
              black-box bundle, not just a pile of artifacts. The run
              summary reports ``diagnosis_accuracy`` (matched
              fraction across episodes).

Schedule composition rules (all deterministic per episode seed):

  * terminal kinds (kill / sigterm / crash) land only on checkpoint-
    boundary epochs, so the boundary-kind retirement in FaultPlan
    .skip_before stops them from re-firing forever on resume — every
    terminal fault costs exactly one restart budget unit (plus one
    more when a corrupt-ckpt forces the loader one generation back)
  * the streaming delta epoch is UNCONSTRAINED: the write-ahead delta
    journal (stream/journal.py) makes deltas durable before they are
    applied, and every resume path replays seqs at-or-under the
    checkpoint watermark before training continues — so a delta may
    land before, between, or after restart boundaries (the PR-14
    "after the last terminal epoch" rule is retired)
  * hang / desync / replica-kill / rejoin are excluded from the
    default pool — the episodes run one member (streaming is single-
    process), where those kinds either stall on the watchdog horizon
    or are inert; force them via ``force_faults`` when running a
    multi-member config
  * the storage kinds (resilience/storage.py) ride the same grammar;
    ``force_faults=("enospc@4",)`` is the acceptance proof that the
    previous checkpoint generation stays loadable and the re-drained
    metrics records survive

Each episode emits a schema-contracted ``soak`` record
(obs/schema.py) and the run writes ``soak-seed<seed>.json`` next to
the episode dirs.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import random
import shutil
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .storage import IO_KINDS

# terminal kinds end the generation; the supervisor relaunches
TERMINAL_KINDS = ("kill", "sigterm", "crash")
# in-process kinds: the run recovers without a restart (slow-rank is
# a pure perturbation — a host-side sleep at one dispatch boundary
# that the training-span plane must attribute, obs/trainspan.py)
SOFT_KINDS = ("nan-loss", "kernel-crash", "corrupt-ckpt",
              "graph-delta", "slow-rank", "journal-torn") + IO_KINDS

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class SoakConfig:
    """One soak run: `episodes` episodes derived from `seed`."""

    seed: int = 0
    episodes: int = 5
    n_epochs: int = 8
    n_parts: int = 2
    checkpoint_every: int = 2
    out_dir: str = os.path.join("results", "soak")
    dataset: str = "synthetic:300:6:8:3"
    # entries prepended VERBATIM to every episode's schedule (e.g.
    # ("enospc@4",) for the storage-fault acceptance proof)
    force_faults: Tuple[str, ...] = ()
    # adds the serving-fleet ticket-conservation drill to each episode
    serve: bool = False
    # adds the closed-loop autoscale drill: flash-crowd traffic over a
    # 1-replica fleet with --autoscale, one replica-kill and one mid-
    # crowd net-partition; invariant #7 (check_autoscale) demands the
    # replica-count trajectory match the ledger's spawn/retire records
    # and ticket conservation hold through the scale events
    autoscale: bool = False
    # adds the silent-data-corruption drill: one seeded bitflip per
    # episode (random target class: params / carry / tables / halo)
    # with --enable-pipeline and --integrity-check-every; invariant #8
    # (check_integrity) demands every injected flip be detected within
    # the cadence, attributed to the right class, and the episode
    # still resume green
    integrity: bool = False
    integrity_every: int = 2
    max_restarts: int = 6
    episode_timeout_s: float = 900.0
    keep_dirs: bool = False  # keep green episode dirs for inspection


def episode_seed(cfg: SoakConfig, episode: int) -> int:
    return cfg.seed * 1000003 + episode


def compose_schedule(cfg: SoakConfig, episode: int) \
        -> Tuple[List[str], int]:
    """(fault entries, stream-delta epoch) for one episode — a pure
    function of (cfg.seed, episode), never of wall clock or pid."""
    rng = random.Random(episode_seed(cfg, episode))
    entries = list(cfg.force_faults)
    boundaries = list(range(cfg.checkpoint_every,
                            cfg.n_epochs - 1, cfg.checkpoint_every))
    n_term = rng.randint(0, min(2, len(boundaries)))
    term_epochs = sorted(rng.sample(boundaries, n_term))
    for b in term_epochs:
        entries.append(f"{rng.choice(TERMINAL_KINDS)}@{b}")
    for kind in rng.sample(SOFT_KINDS, rng.randint(1, 2)):
        if kind == "corrupt-ckpt":
            e = rng.choice(boundaries)
        else:
            e = rng.randrange(1, cfg.n_epochs - 1)
        if kind == "slow-fs":
            entries.append(f"slow-fs@{e}:{rng.choice((5, 20))}")
        elif kind == "slow-rank":
            # ms of injected dispatch-boundary straggle (slow-rank@E:ms)
            entries.append(f"slow-rank@{e}:{rng.choice((50, 200))}")
        else:
            entries.append(f"{kind}@{e}")
    if cfg.integrity:
        # drawn AFTER the base kinds so non-integrity schedules stay
        # bit-identical for a given seed; one flip per episode keeps
        # the per-process strike count below the quarantine threshold
        e = rng.randrange(1, cfg.n_epochs - 1)
        cls = rng.choice(("params", "carry", "tables", "halo"))
        entries.append(f"bitflip@{e}:{cls}")
    # delta placement is unconstrained: the WAL journal + watermark
    # replay make a delta before (or between) restart boundaries
    # exactly as recoverable as one after them
    stream_epoch = rng.randrange(1, cfg.n_epochs - 1)
    return entries, stream_epoch


# ---------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------


def _inv(ok: bool, **detail) -> Dict:
    return {"ok": bool(ok), **detail}


def check_checkpoint(ck_dir: str,
                     want_epoch: Optional[int] = None) -> Dict:
    """Newest digest-valid generation verifies; optionally it must sit
    at `want_epoch` (after the clean resume)."""
    from ..utils.checkpoint import CheckpointCorrupt, verify_checkpoint

    gens = sorted(glob.glob(os.path.join(ck_dir, "state-*.npz")),
                  reverse=True)
    if not gens:
        return _inv(False, error="no checkpoint generations on disk")
    for path in gens:
        try:
            epoch = verify_checkpoint(path)
        except CheckpointCorrupt as exc:
            # a corrupt-ckpt fault may leave the newest torn; the walk
            # below must find a valid older generation
            last_err = repr(exc)
            continue
        ok = want_epoch is None or epoch == want_epoch
        return _inv(ok, path=os.path.basename(path), epoch=epoch,
                    **({} if ok else {"error": f"epoch {epoch} != "
                                               f"{want_epoch}"}))
    return _inv(False, error=f"every generation corrupt ({last_err})")


def check_ledger(coord_dir: str) -> Dict:
    """Generations contiguous from 0, every record CRC-clean."""
    from .elastic import LedgerCorrupt, MembershipLedger

    led = MembershipLedger(coord_dir)
    gens = led.generations()
    if gens != list(range(len(gens))) or not gens:
        return _inv(False, generations=gens,
                    error="generations not contiguous from 0")
    prev = -1
    for g in gens:
        try:
            rec = led.read(g)
        except LedgerCorrupt as exc:
            return _inv(False, generations=gens, error=repr(exc))
        if rec["generation"] <= prev:
            return _inv(False, generations=gens,
                        error=f"generation {g} not monotonic")
        prev = rec["generation"]
    return _inv(True, generations=gens)


def check_metrics(paths: Sequence[str], n_epochs: int) -> Dict:
    """Every line parses (one torn FINAL line per file tolerated —
    SIGKILL lands mid-write) and epoch records cover 0..n_epochs-1."""
    seen: set = set()
    torn = 0
    n_files = 0
    for path in paths:
        if not os.path.exists(path):
            continue
        n_files += 1
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    torn += 1  # the one legal wound
                    continue
                return _inv(False, file=os.path.basename(path),
                            error=f"unparseable line {i + 1} (not the "
                                  f"file tail)")
            if rec.get("event") == "epoch":
                seen.add(int(rec["epoch"]))
    if not n_files:
        return _inv(False, error="no metrics files found")
    missing = sorted(set(range(n_epochs)) - seen)
    return _inv(not missing, files=n_files, torn_tails=torn,
                epochs_seen=len(seen),
                **({"missing": missing} if missing else {}))


# injected fault kind -> postmortem verdict classes that correctly
# explain it (obs/postmortem.py). Several kinds legitimately map to
# more than one class: a SIGKILL'd member leaves either a generic
# crash picture or (when a peer's watchdog dumped first) a
# wedged-collective one.
_KIND_TO_CLASS: Dict[str, Tuple[str, ...]] = {
    "corrupt-ckpt": ("corrupt-artifact",),
    "nan-loss": ("divergence",),
    "kernel-crash": ("fallback-exhausted", "crash"),
    "hang": ("wedged-collective",),
    "desync": ("desync",),
    "enospc": ("storage-fault",),
    "torn-write": ("storage-fault", "corrupt-artifact"),
    "ro-dir": ("storage-fault",),
    "slow-fs": ("storage-fault",),
    "kill": ("crash", "wedged-collective", "preemption"),
    "sigterm": ("preemption", "crash"),
    "crash": ("crash", "preemption"),
    "bitflip": ("sdc",),
    # a torn journal tail alone is recoverable (replay falls back to
    # the plan's delta files); if the episode still went red, the
    # rollback picture is the consistent explanation
    "journal-torn": ("topo-rollback", "crash"),
}


def expected_classes(schedule: Sequence[str]) -> List[str]:
    """Postmortem verdicts that would correctly explain a red episode
    running `schedule` (sorted; never empty — an unscheduled death is
    still a crash)."""
    out: set = set()
    for entry in schedule:
        out.update(_KIND_TO_CLASS.get(entry.split("@", 1)[0], ()))
    return sorted(out) if out else ["crash"]


def check_diagnosis(ep_dir: str, pre_verdict: str,
                    schedule: Sequence[str]) -> Dict:
    """Invariant #6: the automated postmortem over the episode dir
    reaches the right verdict — ``clean-exit`` on a green episode
    (dumps from recovered faults must NOT outrank the completed
    resume), a schedule-consistent class on a red one."""
    try:
        from ..obs.postmortem import diagnose_run

        diag = diagnose_run(ep_dir)
    except Exception as exc:  # noqa: BLE001
        return _inv(False, error=f"postmortem failed: {exc!r}")
    expected = (["clean-exit"] if pre_verdict == "green"
                else expected_classes(schedule))
    ok = diag["verdict"] in expected
    return _inv(ok, verdict=diag["verdict"],
                confidence=round(float(diag["confidence"]), 3),
                deterministic=diag["deterministic"],
                expected=expected,
                **({} if ok else
                   {"error": f"verdict {diag['verdict']!r} not in "
                             f"{expected}",
                    "evidence": list(diag["evidence"])[:4]}))


def check_tickets(fleet_summary: Optional[Dict]) -> Dict:
    """Zero accepted tickets lost in the serving drill (skipped —
    vacuously green — when the episode did not serve)."""
    if fleet_summary is None:
        return _inv(True, skipped=True)
    ok = (fleet_summary.get("conserved") is True
          and fleet_summary.get("drained") is True
          and fleet_summary.get("n_submitted")
          == fleet_summary.get("n_served", 0)
          + fleet_summary.get("n_shed", 0))
    return _inv(ok, conserved=fleet_summary.get("conserved"),
                drained=fleet_summary.get("drained"),
                n_submitted=fleet_summary.get("n_submitted"),
                n_served=fleet_summary.get("n_served"),
                n_shed=fleet_summary.get("n_shed"))


def check_autoscale(fleet_summary: Optional[Dict],
                    fleet_jsonl: str,
                    initial_replicas: int = 1) -> Dict:
    """Invariant #7 (``autoscale`` episodes): the replica-count
    trajectory is explained by the ledger — every ``spawn``/``retire``
    fleet record pairs with a ``scale-up``/``scale-down`` autoscale
    decision record, the final active count equals
    ``initial + spawns - retires``, the flash crowd provoked at least
    one scale-up, and ticket conservation held through the scale
    events. Vacuously green when the episode did not run the drill."""
    if fleet_summary is None:
        return _inv(False, error="autoscale drill crashed (no summary)")
    spawns = retires = ups = downs = 0
    if os.path.exists(fleet_jsonl):
        with open(fleet_jsonl, encoding="utf-8") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                ev = rec.get("event")
                if ev == "fleet":
                    if rec.get("kind") == "spawn":
                        spawns += 1
                    elif rec.get("kind") == "retire":
                        retires += 1
                elif ev == "autoscale":
                    if rec.get("action") == "scale-up":
                        ups += 1
                    elif rec.get("action") == "scale-down":
                        downs += 1
    detail = dict(spawns=spawns, retires=retires,
                  decisions_up=ups, decisions_down=downs,
                  n_spawned=fleet_summary.get("n_spawned"),
                  n_retired=fleet_summary.get("n_retired"),
                  replicas_active=fleet_summary.get("replicas_active"),
                  conserved=fleet_summary.get("conserved"),
                  drained=fleet_summary.get("drained"),
                  n_submitted=fleet_summary.get("n_submitted"),
                  n_served=fleet_summary.get("n_served"),
                  n_shed=fleet_summary.get("n_shed"))
    errors = []
    if spawns != fleet_summary.get("n_spawned"):
        errors.append(f"ledger spawns {spawns} != summary "
                      f"{fleet_summary.get('n_spawned')}")
    if retires != fleet_summary.get("n_retired"):
        errors.append(f"ledger retires {retires} != summary "
                      f"{fleet_summary.get('n_retired')}")
    if ups != spawns:
        errors.append(f"scale-up decisions {ups} != spawns {spawns}")
    if downs != retires:
        errors.append(f"scale-down decisions {downs} != retires "
                      f"{retires}")
    want = initial_replicas + spawns - retires
    if fleet_summary.get("replicas_active") != want:
        errors.append(f"replicas_active "
                      f"{fleet_summary.get('replicas_active')} != "
                      f"{initial_replicas} + {spawns} - {retires}")
    if spawns < 1:
        errors.append("flash crowd provoked no scale-up")
    if not (fleet_summary.get("conserved") is True
            and fleet_summary.get("drained") is True
            and fleet_summary.get("n_submitted")
            == fleet_summary.get("n_served", 0)
            + fleet_summary.get("n_shed", 0)):
        errors.append("tickets not conserved through scale events")
    return _inv(not errors, **detail,
                **({"error": "; ".join(errors)} if errors else {}))


def check_integrity(metric_files: Sequence[str],
                    schedule: Sequence[str],
                    cadence: int) -> Dict:
    """Invariant #8 (``integrity`` episodes): every scheduled bitflip
    actually fired (an episode that completes to n_epochs must have
    crossed the injection epoch in some generation), and every
    ``fault kind=injected reason=bitflip:<class>`` record has a
    matching detection — an ``integrity`` mismatch record or an
    ``sdc`` fault record naming the SAME target class — within
    ``cadence`` epochs of the injection. Vacuously green when the
    schedule holds no bitflips."""
    scheduled = [e for e in schedule if e.startswith("bitflip@")]
    if not scheduled:
        return _inv(True, skipped=True)
    injected: List[Tuple[int, str]] = []
    detected: List[Tuple[int, str]] = []
    for path in metric_files:
        if not os.path.exists(path):
            continue
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                ev = rec.get("event")
                if (ev == "fault" and rec.get("kind") == "injected"
                        and str(rec.get("reason", ""))
                        .startswith("bitflip:")):
                    injected.append((int(rec.get("epoch", -1)),
                                     str(rec["reason"]).split(":", 1)[1]))
                elif ev == "integrity" and rec.get("outcome") == "mismatch":
                    detected.append((int(rec.get("epoch", -1)),
                                     str(rec.get("target") or "")))
                elif ev == "fault" and rec.get("kind") == "sdc":
                    detected.append((int(rec.get("epoch", -1)),
                                     str(rec.get("target") or "")))
    errors = []
    fired_classes = {cls for _, cls in injected}
    for entry in scheduled:
        cls = entry.rsplit(":", 1)[-1]
        if cls not in fired_classes:
            errors.append(f"scheduled {entry} never injected")
    for e, cls in injected:
        hit = any(dcls == cls and e <= de <= e + max(cadence, 1)
                  for de, dcls in detected)
        if not hit:
            errors.append(f"bitflip:{cls}@{e} undetected within "
                          f"cadence {cadence}")
    return _inv(not errors, scheduled=list(scheduled),
                injected=sorted(set(injected)),
                detected=sorted(set(detected))[:8],
                **({"error": "; ".join(errors)} if errors else {}))


def check_journal(resume_metrics: str, n_batches: int) -> Dict:
    """Invariant #9 (journaled streaming): the clean resume's post-run
    rebuild audit — the ``journal`` op="verify" record in the resume
    metrics stream — reports the trainer's topo_generation at the
    NOMINAL delta count (every scheduled delta applied exactly once,
    whether by WAL replay, plan re-derivation after a torn tail, or
    live delivery) and ``tables_match`` true: the patched device
    tables are bitwise-identical to a from-scratch rebuild."""
    if not os.path.exists(resume_metrics):
        return _inv(False, error="no resume metrics stream")
    verify = None
    replayed = truncated = 0
    with open(resume_metrics, encoding="utf-8") as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("event") != "journal":
                continue
            op = rec.get("op")
            if op == "verify":
                verify = rec
            elif op == "replay":
                replayed += (int(rec.get("n_records", 0))
                             + int(rec.get("rederived", 0)))
            elif op == "truncate":
                truncated += int(rec.get("n_records", 0))
    if verify is None:
        return _inv(False,
                    error="no journal verify record in the resume "
                          "stream (journaled resume did not run)")
    errors = []
    if verify.get("tables_match") is not True:
        errors.append(f"device tables diverge from rebuild: "
                      f"{verify.get('mismatch')}")
    if int(verify.get("topo_generation", -1)) != n_batches:
        errors.append(f"topo_generation "
                      f"{verify.get('topo_generation')} != nominal "
                      f"{n_batches}")
    return _inv(not errors,
                topo_generation=verify.get("topo_generation"),
                tables_match=verify.get("tables_match"),
                replayed=replayed, truncated=truncated,
                **({"error": "; ".join(errors)} if errors else {}))


# ---------------------------------------------------------------------
# episode driver
# ---------------------------------------------------------------------


def _episode_env() -> Dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env["PYTHONUNBUFFERED"] = "1"
    return env


def _train_argv(cfg: SoakConfig, ep_dir: str, delta_path: str,
                stream_epoch: int) -> List[str]:
    argv = [
        "--dataset", cfg.dataset,
        "--n-partitions", str(cfg.n_parts),
        "--parts-per-node", str(cfg.n_parts),  # one member: streaming
        #                                        is single-process
        "--n-epochs", str(cfg.n_epochs),
        "--n-hidden", "8", "--dropout", "0.0",
        "--log-every", "1000", "--no-eval",
        "--fix-seed", "--seed", "7",
        "--local-reorder", "none",
        "--partition-dir", os.path.join(ep_dir, "parts"),
        "--checkpoint-dir", os.path.join(ep_dir, "ck"),
        "--checkpoint-every", str(cfg.checkpoint_every),
        "--checkpoint-keep", "0",  # keep every generation: the
        #                            invariants audit the full history
        "--stream-plan", f"{delta_path}@{stream_epoch}",
        "--metrics-out", os.path.join(ep_dir, "metrics.jsonl"),
    ]
    if cfg.integrity:
        # pipeline on so the carry/halo target classes are injectable
        argv += ["--enable-pipeline",
                 "--integrity-check-every", str(cfg.integrity_every)]
    return argv


def _write_delta_file(cfg: SoakConfig, episode: int, path: str) -> None:
    """One small CRC-guarded delta batch, deterministic per episode.
    The base graph comes from the same dataset string the episode
    trains on (synthetic loads are seed-stable), so the batch is valid
    against every generation's rebuild of the graph."""
    from ..graph import load_data
    from ..graph.synthetic import synthetic_delta_schedule
    from ..stream.deltas import save_deltas

    g = load_data(cfg.dataset)
    batches = synthetic_delta_schedule(
        g, n_batches=1, edges_per_batch=4, dels_per_batch=2,
        nodes_per_batch=1, seed=episode_seed(cfg, episode))
    save_deltas(path, batches)


def _run_fleet_drill(cfg: SoakConfig, episode: int, ep_dir: str,
                     log: Callable[[str], None]) -> Optional[Dict]:
    """Short serving-fleet load drill; returns the driver's summary
    dict (None on a crash, which check_tickets turns red)."""
    rng = random.Random(episode_seed(cfg, episode) ^ 0x5EA5)
    cmd = [
        sys.executable, "-m", "pipegcn_tpu.cli.fleet",
        "--dataset", cfg.dataset, "--n-partitions", str(cfg.n_parts),
        "--n-hidden", "8", "--fix-seed",
        "--partition-dir", os.path.join(ep_dir, "parts-serve"),
        "--serve-build", "--replicas", "2", "--fleet-policy", "hash",
        "--serve-duration", "6", "--serve-qps", "40",
        "--serve-report-every", "0.5",
        "--metrics-out", os.path.join(ep_dir, "fleet.jsonl"),
    ]
    if rng.random() < 0.5:
        cmd += ["--fault-plan", "replica-kill@2:m1",
                "--fleet-retry-timeout", "15"]
    env = _episode_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PIPEGCN_PLATFORM"] = "cpu"
    try:
        proc = subprocess.run(cmd, env=env, cwd=_REPO,
                              timeout=cfg.episode_timeout_s,
                              capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        log("  fleet drill timed out")
        return None
    tails = [ln for ln in proc.stdout.splitlines()
             if '"fleet": true' in ln]
    if proc.returncode != 0 or not tails:
        log(f"  fleet drill rc={proc.returncode}, no summary")
        return None
    return json.loads(tails[-1])


def _run_autoscale_drill(cfg: SoakConfig, episode: int, ep_dir: str,
                         log: Callable[[str], None]) -> Optional[Dict]:
    """Closed-loop autoscale drill: a 1-replica fleet under a
    flash-crowd arrival schedule with --autoscale, plus one
    replica-kill (the lone replica, pre-crowd — queue pressure during
    the relaunch is what provokes the scale-up) and one mid-crowd
    net-partition. Windows are 0.5 s wide; the kill/partition windows
    are drawn deterministically from the episode seed. Returns the
    driver's summary dict (None on a crash, which check_autoscale
    turns red)."""
    rng = random.Random(episode_seed(cfg, episode) ^ 0xA5CA)
    kill_w = rng.choice((2, 3))        # t ~ 1.0-2.0 s, before the crowd
    part_w = rng.choice((7, 8))        # t ~ 3.5-4.5 s, mid-crowd
    faults = (f"replica-kill@{kill_w}:m0,"
              f"net-partition@{part_w}:m0:1")
    cmd = [
        sys.executable, "-m", "pipegcn_tpu.cli.fleet",
        "--dataset", cfg.dataset, "--n-partitions", str(cfg.n_parts),
        "--n-hidden", "8", "--fix-seed",
        "--partition-dir", os.path.join(ep_dir, "parts-serve"),
        "--serve-build", "--replicas", "1",
        "--autoscale", "--autoscale-max", "3",
        "--autoscale-cooldown", "1.5",
        "--traffic", "flash-crowd:4:0.25:0.625",
        "--serve-duration", "8", "--serve-qps", "30",
        "--serve-max-batch", "32", "--serve-max-queue", "96",
        "--serve-report-every", "0.5",
        "--fault-plan", faults,
        "--fleet-retry-timeout", "20",
        "--metrics-out", os.path.join(ep_dir, "autoscale.jsonl"),
    ]
    log(f"  autoscale drill: kill@{kill_w} partition@{part_w}")
    env = _episode_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PIPEGCN_PLATFORM"] = "cpu"
    try:
        proc = subprocess.run(cmd, env=env, cwd=_REPO,
                              timeout=cfg.episode_timeout_s,
                              capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        log("  autoscale drill timed out")
        return None
    tails = [ln for ln in proc.stdout.splitlines()
             if '"fleet": true' in ln]
    if proc.returncode != 0 or not tails:
        log(f"  autoscale drill rc={proc.returncode}, no summary")
        log(f"  tail:\n{(proc.stdout + proc.stderr)[-1500:]}")
        return None
    return json.loads(tails[-1])


def run_episode(cfg: SoakConfig, episode: int,
                log: Callable[[str], None] = print) -> Dict:
    """Run one episode end-to-end and return its soak record body."""
    schedule, stream_epoch = compose_schedule(cfg, episode)
    ep_dir = os.path.abspath(os.path.join(
        cfg.out_dir, f"ep{cfg.seed:04d}-{episode:03d}"))
    shutil.rmtree(ep_dir, ignore_errors=True)
    os.makedirs(ep_dir)
    delta_path = os.path.join(ep_dir, "deltas.jsonl")
    _write_delta_file(cfg, episode, delta_path)
    argv = _train_argv(cfg, ep_dir, delta_path, stream_epoch)
    log(f"episode {episode}: faults={schedule} "
        f"stream@{stream_epoch}")

    env = _episode_env()
    sup_cmd = [
        sys.executable, "-m", "pipegcn_tpu.cli.elastic",
        "--max-restarts", str(cfg.max_restarts),
        "--backoff-base", "0.1",
        "--metrics-out", os.path.join(ep_dir, "sup.jsonl"),
        "--", *argv,
    ]
    if schedule:
        sup_cmd += ["--fault-plan", ",".join(schedule)]
    try:
        sup = subprocess.run(sup_cmd, env=env, cwd=_REPO,
                             timeout=cfg.episode_timeout_s,
                             capture_output=True, text=True)
        sup_rc: Optional[int] = sup.returncode
        sup_tail = (sup.stdout + sup.stderr)[-2000:]
    except subprocess.TimeoutExpired as exc:
        sup_rc, sup_tail = None, f"TIMEOUT: {exc}"
    log(f"  supervised phase rc={sup_rc}")

    # final clean resume: no fault plan, fresh metrics file
    resume_argv = [a for a in argv]
    mi = resume_argv.index("--metrics-out")
    resume_metrics = os.path.join(ep_dir, "metrics-resume.jsonl")
    resume_argv[mi + 1] = resume_metrics
    res_cmd = [sys.executable, "-m", "pipegcn_tpu.cli.main",
               *resume_argv, "--resume"]
    try:
        res = subprocess.run(res_cmd, env=env, cwd=_REPO,
                             timeout=cfg.episode_timeout_s,
                             capture_output=True, text=True)
        res_rc: Optional[int] = res.returncode
        res_tail = (res.stdout + res.stderr)[-2000:]
    except subprocess.TimeoutExpired as exc:
        res_rc, res_tail = None, f"TIMEOUT: {exc}"
    log(f"  clean resume rc={res_rc}")

    fleet_summary = (_run_fleet_drill(cfg, episode, ep_dir, log)
                     if cfg.serve else None)
    autoscale_summary = (_run_autoscale_drill(cfg, episode, ep_dir, log)
                         if cfg.autoscale else None)

    ck_dir = os.path.join(ep_dir, "ck")
    coord_dir = os.path.join(ep_dir, "parts", "coord-elastic")
    metric_files = sorted(glob.glob(
        os.path.join(ep_dir, "metrics*.jsonl")))
    invariants = {
        "checkpoint": check_checkpoint(ck_dir, want_epoch=cfg.n_epochs),
        "ledger": check_ledger(coord_dir),
        "metrics": check_metrics(metric_files, cfg.n_epochs),
        "tickets": (check_tickets(fleet_summary) if cfg.serve
                    else _inv(True, skipped=True)),
        "autoscale": (check_autoscale(
            autoscale_summary, os.path.join(ep_dir, "autoscale.jsonl"))
            if cfg.autoscale else _inv(True, skipped=True)),
        # invariant #8: every injected bitflip detected within cadence,
        # attributed to the right target class
        "integrity": (check_integrity(metric_files, schedule,
                                      cfg.integrity_every)
                      if cfg.integrity else _inv(True, skipped=True)),
        # invariant #9: post-resume topo_generation at nominal, device
        # tables digest-match a from-scratch rebuild (one delta batch
        # per episode, see _write_delta_file)
        "journal": check_journal(resume_metrics, n_batches=1),
        "resume": _inv(res_rc == 0,
                       rc=res_rc,
                       **({} if res_rc == 0
                          else {"tail": res_tail[-500:]})),
    }
    # invariant #6 rides on the other five's verdict (green episodes
    # must diagnose clean-exit, red ones a schedule-consistent class)
    # and must run BEFORE the green-episode dir cleanup below
    pre_verdict = ("green" if all(v["ok"] for v in invariants.values())
                   else "red")
    invariants["diagnosis"] = check_diagnosis(ep_dir, pre_verdict,
                                              schedule)
    verdict = ("green" if all(v["ok"] for v in invariants.values())
               else "red")
    for name, v in invariants.items():
        log(f"  invariant {name}: {'ok' if v['ok'] else 'RED ' + str(v)}")
    if verdict == "red":
        log(f"  supervised tail:\n{sup_tail}")
    elif not cfg.keep_dirs:
        shutil.rmtree(ep_dir, ignore_errors=True)
    return {
        "episode": episode,
        "seed": episode_seed(cfg, episode),
        "schedule": list(schedule),
        "stream_epoch": stream_epoch,
        "supervised_rc": sup_rc,
        "invariants": invariants,
        "verdict": verdict,
    }


def run_soak(cfg: SoakConfig,
             log: Callable[[str], None] = print) -> Dict:
    """Run every episode, write the soak JSONL + summary JSON, return
    the summary (verdict 'green' iff every episode is green)."""
    from ..obs.metrics import MetricsLogger

    os.makedirs(cfg.out_dir, exist_ok=True)
    records = []
    soak_jsonl = os.path.join(cfg.out_dir,
                              f"soak-seed{cfg.seed}.jsonl")
    m = MetricsLogger(soak_jsonl)
    try:
        for i in range(cfg.episodes):
            rec = run_episode(cfg, i, log=log)
            records.append(rec)
            m.soak(episode=rec["episode"], seed=rec["seed"],
                   schedule=rec["schedule"],
                   invariants=rec["invariants"],
                   verdict=rec["verdict"],
                   supervised_rc=rec["supervised_rc"])
    finally:
        m.close()
    verdict = ("green" if records and
               all(r["verdict"] == "green" for r in records)
               else "red")
    # fraction of episodes whose automated postmortem matched the
    # expected class (invariant #6) — the headline forensics number
    diag_ok = [bool(r["invariants"].get("diagnosis", {}).get("ok"))
               for r in records]
    summary = {"seed": cfg.seed, "episodes": records,
               "n_episodes": len(records), "verdict": verdict,
               "diagnosis_accuracy": (round(sum(diag_ok)
                                            / len(diag_ok), 4)
                                      if diag_ok else None)}
    out = os.path.join(cfg.out_dir, f"soak-seed{cfg.seed}.json")
    from .storage import write_text_atomic

    write_text_atomic(out, json.dumps(summary, indent=1), fsync=False)
    log(f"soak seed {cfg.seed}: {len(records)} episode(s), "
        f"verdict {verdict}, diagnosis accuracy "
        f"{summary['diagnosis_accuracy']} -> {out}")
    return summary
