"""Cross-rank fault coordination for multi-process (multi-host) runs.

PR 2's recovery machinery (docs/RESILIENCE.md) is *unilateral*: the
sentinel rolls back, the preemption handler checkpoints and exits, the
carry flushes — all decisions one process takes alone. In a
``jax.distributed`` run every rank blocks in a collective every layer
of every epoch, so a unilateral decision desynchronizes the SPMD
program and the next ``halo_exchange`` deadlocks (the exact reference
failure mode, SURVEY.md §5 — gloo collectives hang when any rank dies).
This module makes every recovery decision *agreed* across ranks, makes
dead peers *detected* instead of waited on, and catches silent
cross-rank state divergence:

  FaultConsensus    OR-reduces a small host-side fault word (sentinel
                    trip + reason code, preemption request, desync bit)
                    with one tiny jitted psum over the training mesh at
                    each dispatch boundary; any rank raising a bit makes
                    ALL ranks execute the matching recovery in lockstep
  HeartbeatWatchdog each rank touches ``heartbeat-r<k>`` on the shared
                    partition filesystem (the same out-of-band channel
                    the partition-artifact wait uses) and watches peer
                    mtimes; a silent peer raises :class:`PeerLost` at
                    the next dispatch boundary — and a daemon-thread
                    hard deadline converts "blocked forever inside a
                    collective" into snapshot checkpoint + exit 75
  desync detector   per-leaf CRC32 digests of the replicated params
                    (utils/checkpoint.py's digest code) broadcast from
                    rank 0 and compared on every rank at a configured
                    cadence; mismatch is agreed through the consensus
                    word and either resynced from rank 0's state or
                    aborted resumably

A single-process Coordinator is *inactive*: every method degenerates to
a local no-op (no collectives, no watchdog), so ``fit()`` keeps one
code path whether or not the run is distributed. "rank" throughout
means ``jax.process_index()`` — the unit that can die independently.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .preemption import EXIT_PREEMPTED

# ---------------- fault word ------------------------------------------
# One int32 vector per rank, summed across ranks by a single psum.
# Bit slots sum to the number of raisers; rank slots carry (rank + 1)
# so the source rank is recoverable when exactly one rank raised.
WORD_LEN = 11
IDX_TRIP, IDX_TRIP_CODE, IDX_TRIP_RANK = 0, 1, 2
IDX_PREEMPT, IDX_PREEMPT_RANK = 3, 4
IDX_DESYNC, IDX_DESYNC_RANK = 5, 6
IDX_SDC, IDX_SDC_CODE, IDX_SDC_RANK = 7, 8, 9
IDX_COUNT = 10

# sentinel trip reasons compressed into a code (free text cannot ride a
# psum); decoded best-effort on the receiving ranks
TRIP_CODES = {
    1: "non-finite loss",
    2: "non-finite grad norm",
    3: "grad-norm cap exceeded",
    4: "loss explosion vs healthy median",
    5: "divergence (unclassified)",
}


def trip_code_of(reason: Optional[str]) -> int:
    """Compress a DivergenceSentinel trip reason into a wire code."""
    if not reason:
        return 0
    if "non-finite loss" in reason:
        return 1
    if "non-finite grad" in reason:
        return 2
    if "cap" in reason or "grad norm" in reason:
        return 3
    if "healthy median" in reason:
        return 4
    return 5


class PeerLost(RuntimeError):
    """A peer rank stopped heartbeating: the pod cannot complete its
    collectives. Raised at a dispatch boundary (or synthesized from a
    failed collective); rides the trainer's crash-checkpoint path and
    maps to the resumable exit status 75 in the CLI."""

    def __init__(self, rank: int, silent_s: float):
        super().__init__(
            f"peer rank {rank} silent for {silent_s:.0f}s "
            f"(heartbeat watchdog)")
        self.rank = int(rank)
        self.silent_s = float(silent_s)


@dataclasses.dataclass
class Agreed:
    """Decoded OR-reduction of every rank's fault word. ``*_rank`` is
    the raising rank when exactly one rank raised, else -1."""

    trip: bool = False
    trip_code: int = 0
    trip_rank: int = -1
    preempt: bool = False
    preempt_rank: int = -1
    desync: bool = False
    desync_rank: int = -1
    sdc: bool = False
    sdc_code: int = 0      # integrity.SDC_CODES target class (0 = none)
    sdc_rank: int = -1
    n_ranks: int = 1

    def trip_reason(self) -> str:
        what = TRIP_CODES.get(self.trip_code, "divergence")
        who = (f"rank {self.trip_rank}" if self.trip_rank >= 0
               else "multiple ranks")
        return f"consensus: {who} tripped ({what})"


def digest_leaves(tree: Any) -> np.ndarray:
    """Per-leaf CRC32 digest vector (uint32, path-sorted) of a host
    pytree — the same digest checkpoint verification uses, so a desync
    report and a checkpoint manifest disagree on nothing."""
    import jax

    from ..utils.checkpoint import _crc, _path_str

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    leaves = sorted(leaves, key=lambda kv: _path_str(kv[0]))
    return np.asarray([_crc(np.asarray(v)) for _, v in leaves], np.uint32)


def digest_leaf_names(tree: Any) -> list:
    """Leaf paths in the exact order :func:`digest_leaves` digests them
    — index i of the digest vector is leaf ``names[i]``, so a digest
    mismatch can be attributed to a NAMED tensor."""
    import jax

    from ..utils.checkpoint import _path_str

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return sorted(_path_str(p) for p, _ in leaves)


class FaultConsensus:
    """One tiny jitted psum over the training mesh.

    Each process contributes its word on its FIRST local device (zeros
    on the rest), so the psum's result is the exact per-rank sum — no
    normalization by local device count. ``broadcast0`` instead places
    the vector on every local device and masks to mesh device 0, which
    belongs to process 0, so every rank receives rank 0's vector."""

    def __init__(self, mesh):
        import jax

        self._mesh = mesh
        self._axis = mesh.axis_names[0]
        self._pid = jax.process_index()  # fixed for the process's life
        self._fns: Dict[str, Any] = {}

    def _fn(self, mode: str):
        if mode in self._fns:
            return self._fns[mode]
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec

        axis = self._axis
        if mode == "sum":
            def body(w):
                return jax.lax.psum(w, axis)
        else:  # "bcast0": rank 0's row to everyone
            def body(w):
                idx = jax.lax.axis_index(axis)
                return jax.lax.psum(
                    jnp.where(idx == 0, w, jnp.zeros_like(w)), axis)
        fn = jax.jit(jax.shard_map(
            body, mesh=self._mesh,
            in_specs=PartitionSpec(self._axis),
            out_specs=PartitionSpec()))
        self._fns[mode] = fn
        return fn

    def _scatter(self, vec: np.ndarray, every_device: bool):
        """Build the [n_devices, len(vec)] global array whose local
        shards carry this process's vector."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        devs = list(self._mesh.devices.flat)
        sharding = NamedSharding(self._mesh, PartitionSpec(self._axis))
        pid = self._pid
        shards = []
        first = True
        zero = np.zeros_like(vec)
        for d in devs:
            if d.process_index != pid:
                continue
            row = vec if (every_device or first) else zero
            first = False
            shards.append(jax.device_put(row[None, :], d))
        return jax.make_array_from_single_device_arrays(
            (len(devs),) + vec.shape, sharding, shards)

    def reduce(self, word: np.ndarray) -> np.ndarray:
        """Element-wise sum of every rank's word (the OR-reduce: bit
        slots become raiser counts)."""
        import jax

        word = np.asarray(word)
        out = self._fn("sum")(self._scatter(word, every_device=False))
        return np.asarray(jax.device_get(out))[0]

    def broadcast0(self, vec: np.ndarray) -> np.ndarray:
        """Rank 0's vector, delivered to every rank."""
        import jax

        vec = np.asarray(vec)
        out = self._fn("bcast0")(self._scatter(vec, every_device=True))
        return np.asarray(jax.device_get(out))[0]


# tests monkeypatch this to observe the hard-deadline path without
# killing the test process
_hard_exit: Callable[[int], None] = os._exit


class HeartbeatWatchdog:
    """Heartbeat files + peer staleness detection on a shared dir.

    A monitor thread touches ``heartbeat-r<rank>`` every ``interval_s``
    and stats the peers'. A peer whose file is older than ``timeout_s``
    (measured from this watchdog's start, so stale files from a
    previous run never false-trip) marks it lost; ``check()`` then
    raises :class:`PeerLost` from the main thread. If the main thread
    never gets there — blocked inside a collective that can no longer
    complete — the monitor thread itself fires ``on_deadline`` after a
    further ``grace_s`` (the hard deadline)."""

    def __init__(self, directory: str, rank: int, n_ranks: int,
                 timeout_s: float, interval_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 on_deadline: Optional[Callable[[int, float], None]] = None,
                 log: Callable[[str], None] = print,
                 generation: int = -1):
        self.dir = directory
        self.rank = int(rank)
        self.n_ranks = int(n_ranks)
        # elastic membership generation: -1 (unsupervised) keeps the
        # legacy heartbeat-r<k> names; >= 0 keys the filenames on the
        # generation so a relaunched fleet can never be poisoned by
        # ghosts of a previous incarnation's files (the supervisor
        # also unlinks heartbeat-* at launch — belt and braces)
        self.generation = int(generation)
        self.timeout = float(timeout_s)
        self.interval = (float(interval_s) if interval_s
                         else max(self.timeout / 4.0, 0.2))
        self.grace = (float(grace_s) if grace_s is not None
                      else max(self.timeout / 2.0, 2.0))
        self.on_deadline = on_deadline
        self.log = log
        self._lost: Optional[Tuple[int, float]] = None
        self._deadline: Optional[float] = None
        self._handled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._suspended = False
        self._start_time = 0.0

    def path_for(self, rank: int) -> str:
        if self.generation >= 0:
            return os.path.join(
                self.dir, f"heartbeat-g{self.generation}-r{rank}")
        return os.path.join(self.dir, f"heartbeat-r{rank}")

    @property
    def lost(self) -> Optional[Tuple[int, float]]:
        return self._lost

    def start(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        self._start_time = time.time()
        self.beat()
        self._thread = threading.Thread(
            target=self._loop, name=f"pipegcn-watchdog-r{self.rank}",
            daemon=True)
        self._thread.start()

    def beat(self) -> None:
        p = self.path_for(self.rank)
        try:
            with open(p, "a"):
                os.utime(p, None)
        except OSError:
            # genuinely-optional (storage-fault audit): a missed beat
            # is survivable, a raise from the watchdog thread is not —
            # and sustained beat failure already HAS a degradation
            # policy upstream: the peers' watchdogs cull this rank and
            # the elastic supervisor redistributes its shards. The
            # heartbeat channel is deliberately NOT routed through the
            # FaultyIO shim: it must keep beating while the shim
            # simulates a full data disk, exactly like a real host
            # whose scratch volume fills while /dev/shm stays fine.
            pass

    def suspend(self) -> None:
        """Stop beating (the ``hang`` chaos fault: simulate a frozen
        process so the PEERS' watchdogs trip)."""
        self._suspended = True

    def disarm(self) -> None:
        """Main thread took responsibility for a detected loss: cancel
        the hard deadline so the emergency exit never races a clean
        PeerLost checkpoint."""
        self._handled = True
        self._deadline = None

    def check(self) -> None:
        """Raise PeerLost if a peer is flagged (dispatch-boundary call;
        must happen BEFORE entering any collective — a dead peer can
        never complete one)."""
        if self._lost is not None:
            self.disarm()
            raise PeerLost(*self._lost)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 4)
        try:
            os.remove(self.path_for(self.rank))
        except OSError:
            # genuinely-optional (storage-fault audit): the next
            # generation's supervisor clears stale heartbeats anyway
            # (elastic._clear_stale_heartbeats) and filenames are
            # generation-keyed
            pass

    # ---------------- monitor thread ----------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if not self._suspended:
                self.beat()
            now = time.time()
            self._scan(now)
            if (self._lost is not None and not self._handled
                    and self._deadline is not None
                    and now > self._deadline
                    and self.on_deadline is not None):
                self._handled = True
                self.on_deadline(*self._lost)

    def _scan(self, now: float) -> None:
        if self._lost is not None:
            return
        for k in range(self.n_ranks):
            if k == self.rank:
                continue
            try:
                m = os.path.getmtime(self.path_for(k))
            except OSError:
                m = 0.0  # never seen: age runs from watchdog start
            age = now - max(m, self._start_time)
            if age > self.timeout:
                self._lost = (k, age)
                self._deadline = now + self.grace
                self.log(
                    f"heartbeat watchdog: peer rank {k} silent for "
                    f"{age:.0f}s (> {self.timeout:.0f}s); raising "
                    f"PeerLost at the next dispatch boundary (hard "
                    f"exit {EXIT_PREEMPTED} in {self.grace:.0f}s if "
                    f"blocked in a collective)")
                return


@dataclasses.dataclass
class CoordConfig:
    # shared-filesystem channel: heartbeat files + desync resync states
    # (the CLI defaults it under the partition dir — the one directory
    # multi-host runs already share)
    dir: str = ""
    # a peer silent this long is lost; 0 disables the watchdog
    watchdog_timeout: float = 0.0
    # epochs between cross-rank param-digest agreement checks;
    # 0 disables
    desync_every: int = 0
    # on agreed desync: resync every rank from rank 0's state instead
    # of aborting resumably
    desync_resync: bool = False
    # elastic membership generation (resilience/elastic.py): keys the
    # heartbeat filenames so files from a previous incarnation are
    # invisible; -1 = unsupervised (legacy names). The CLI reads it
    # from the PIPEGCN_MEMBERSHIP_GEN env the supervisor sets.
    generation: int = -1


class Coordinator:
    """The per-rank handle fit() drives: consensus + watchdog + desync.

    Inactive (single-process) coordinators are pure no-ops — no
    collectives, no watchdog — so the trainer keeps one code path.
    ``force_active=True`` lets single-process tests exercise the
    consensus machinery (the psum degenerates to identity)."""

    def __init__(self, mesh=None, cfg: Optional[CoordConfig] = None,
                 rank: Optional[int] = None,
                 n_ranks: Optional[int] = None,
                 metrics=None, log: Callable[[str], None] = print,
                 force_active: bool = False):
        import jax

        self.rank = jax.process_index() if rank is None else int(rank)
        self.n_ranks = jax.process_count() if n_ranks is None \
            else int(n_ranks)
        self.cfg = cfg or CoordConfig()
        self.active = force_active or self.n_ranks > 1
        # mesh=None defers the consensus channel (attach_mesh) so the
        # CLI can start HEARTBEATS before the slow partition build /
        # trainer compile — a rank silently partitioning for minutes
        # must not look dead to its already-training-blocked peers
        self._consensus = FaultConsensus(mesh) \
            if (self.active and mesh is not None) else None
        self.watchdog: Optional[HeartbeatWatchdog] = None
        self.metrics = metrics
        self.log = log
        self.last_desync_mismatch = 0
        # names of the mismatching leaves (bounded), so the fault
        # record can distinguish one-tensor corruption from full
        # divergence
        self.last_desync_leaves: list = []
        self._started = False
        # emergency context for the hard-deadline path: the freshest
        # host-side snapshot (device state may be unreachable while the
        # main thread is blocked inside a dead collective)
        self._snapshot: Optional[Tuple[int, Any]] = None
        self._ckpt_dir: Optional[str] = None
        self._ckpt_keep = 3
        self._progress_epoch = 0

    # ---------------- lifecycle ---------------------------------------

    def attach_mesh(self, mesh) -> None:
        """Late-bind the consensus channel to the training mesh (the
        heartbeat watchdog needs no mesh and may already be running)."""
        if self.active and self._consensus is None:
            self._consensus = FaultConsensus(mesh)

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        if (self.active and self.n_ranks > 1 and self.cfg.dir
                and self.cfg.watchdog_timeout > 0):
            self.watchdog = HeartbeatWatchdog(
                self.cfg.dir, self.rank, self.n_ranks,
                self.cfg.watchdog_timeout,
                on_deadline=self._on_hard_deadline, log=self.log,
                generation=self.cfg.generation)
            self.watchdog.start()

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        self._started = False

    # ---------------- fit() context -----------------------------------

    def note_snapshot(self, epoch: int, host_state: Any) -> None:
        """Freshest host-side last-good snapshot (the sentinel's
        rollback target doubles as the emergency checkpoint source)."""
        self._snapshot = (int(epoch), host_state)

    def note_progress(self, epoch: int) -> None:
        self._progress_epoch = int(epoch)

    def set_checkpoint(self, directory: Optional[str], keep: int) -> None:
        self._ckpt_dir = directory or None
        self._ckpt_keep = int(keep)

    def suspend_heartbeat(self) -> None:
        if self.watchdog is not None:
            self.watchdog.suspend()

    # ---------------- peer liveness -----------------------------------

    def check_peers(self) -> None:
        """Dispatch-boundary liveness gate; raises PeerLost BEFORE any
        collective a dead peer could never complete."""
        if self.watchdog is not None:
            self.watchdog.check()

    def await_peer_verdict(self) -> Optional[Tuple[int, float]]:
        """After a failed collective: block (up to the watchdog horizon)
        for the watchdog's verdict. Returns (peer, silent_s) when a
        peer died — the caller converts the failure into PeerLost — or
        None when every peer kept beating (a real local crash)."""
        if self.watchdog is None:
            return None
        deadline = time.time() + self.cfg.watchdog_timeout \
            + self.watchdog.grace + 5.0
        while time.time() < deadline:
            lost = self.watchdog.lost
            if lost is not None:
                self.watchdog.disarm()
                return lost
            time.sleep(0.2)
        return None

    def _on_hard_deadline(self, peer: int, age: float) -> None:
        """Monitor-thread emergency: the main thread is blocked inside
        a collective that can never complete. Record the fault, save
        the freshest HOST-side snapshot (touching the device could
        block forever), exit with the resumable status."""
        try:
            self.log(
                f"watchdog hard deadline: peer rank {peer} dead and the "
                f"main thread is blocked; emergency checkpoint + exit "
                f"{EXIT_PREEMPTED}")
            if self.metrics is not None:
                try:
                    self.metrics.fault(
                        kind="peer-lost", epoch=self._progress_epoch,
                        peer_rank=int(peer), silent_s=float(age),
                        hard_deadline=True)
                except Exception:  # noqa: BLE001 — exit anyway
                    pass
            if self._ckpt_dir and self._snapshot is not None:
                from ..utils.checkpoint import save_checkpoint

                ep, host = self._snapshot
                try:
                    save_checkpoint(self._ckpt_dir, host, ep,
                                    keep=self._ckpt_keep)
                    self.log(f"emergency checkpoint saved to "
                             f"{self._ckpt_dir} (epoch {ep})")
                except Exception as exc:  # noqa: BLE001
                    self.log(f"emergency checkpoint failed: {exc!r}")
            # black-box dump with all-thread stacks BEFORE _hard_exit:
            # the main thread is wedged in a dead collective right now,
            # so this capture is exactly the forensics the postmortem
            # engine (obs/postmortem.py) needs to name the wedged
            # phase/epoch. Runs on the monitor thread — faulthandler
            # is C-level and needs no cooperation from the wedged one.
            try:
                from ..obs import flight as _flight

                rec = _flight.get_recorder()
                rec.crumb("watchdog-trip", peer_rank=int(peer),
                          silent_s=float(age),
                          epoch=self._progress_epoch)
                _flight.dump_blackbox(
                    "watchdog", directory=(rec.dump_dir or self.cfg.dir),
                    with_stacks=True, peer_rank=int(peer),
                    silent_s=float(age), epoch=self._progress_epoch)
            except Exception:  # noqa: BLE001 — exit anyway
                pass
        finally:
            # _hard_exit skips atexit AND io teardown: fsync every
            # buffered metrics record (the fault record above explains
            # this death — it must survive it)
            if self.metrics is not None:
                try:
                    self.metrics.hard_flush()
                except Exception:  # noqa: BLE001 — exit anyway
                    pass
            _hard_exit(EXIT_PREEMPTED)

    # ---------------- consensus ---------------------------------------

    def _exchange(self, trip_code: int = 0, preempt: bool = False,
                  desync: bool = False, sdc_code: int = 0) -> Agreed:
        word = np.zeros(WORD_LEN, np.int32)
        if trip_code:
            word[IDX_TRIP] = 1
            word[IDX_TRIP_CODE] = trip_code
            word[IDX_TRIP_RANK] = self.rank + 1
        if preempt:
            word[IDX_PREEMPT] = 1
            word[IDX_PREEMPT_RANK] = self.rank + 1
        if desync:
            word[IDX_DESYNC] = 1
            word[IDX_DESYNC_RANK] = self.rank + 1
        if sdc_code:
            word[IDX_SDC] = 1
            word[IDX_SDC_CODE] = sdc_code
            word[IDX_SDC_RANK] = self.rank + 1
        word[IDX_COUNT] = 1
        # no consensus channel yet (mesh not attached): decode locally
        if self.active and self._consensus is not None:
            word = self._consensus.reduce(word)

        def _decode(bit_idx, code_idx, rank_idx):
            n = int(word[bit_idx])
            if n == 0:
                return False, 0, -1
            code = int(word[code_idx]) if (code_idx is not None
                                           and n == 1) else 0
            rank = int(word[rank_idx]) - 1 if n == 1 else -1
            return True, code, rank

        trip, tcode, trank = _decode(IDX_TRIP, IDX_TRIP_CODE,
                                     IDX_TRIP_RANK)
        pre, _, prank = _decode(IDX_PREEMPT, None, IDX_PREEMPT_RANK)
        des, _, drank = _decode(IDX_DESYNC, None, IDX_DESYNC_RANK)
        sdc, scode, srank = _decode(IDX_SDC, IDX_SDC_CODE, IDX_SDC_RANK)
        return Agreed(trip=trip, trip_code=tcode, trip_rank=trank,
                      preempt=pre, preempt_rank=prank,
                      desync=des, desync_rank=drank,
                      sdc=sdc, sdc_code=scode, sdc_rank=srank,
                      n_ranks=int(word[IDX_COUNT]))

    def agree_boundary(self, preempt: bool = False,
                       sdc_code: int = 0) -> Agreed:
        """Epoch-boundary (pre-dispatch) consensus: preemption requests
        and local SDC verdicts (the integrity plane's checks run at the
        boundary, before the state they indict gets dispatched again).
        Every rank calls this at the same program point."""
        return self._exchange(preempt=preempt, sdc_code=sdc_code)

    def agree_step(self, trip_reason: Optional[str] = None,
                   desync: bool = False) -> Agreed:
        """Post-dispatch consensus: sentinel trips + desync verdicts."""
        return self._exchange(trip_code=trip_code_of(trip_reason),
                              desync=desync)

    def barrier(self) -> None:
        if self.active and self._consensus is not None:
            self._consensus.reduce(np.zeros(WORD_LEN, np.int32))

    # ---------------- desync detection / repair -----------------------

    def desync_due(self, epoch: int) -> bool:
        return (self.active and self._consensus is not None
                and self.cfg.desync_every > 0 and epoch > 0
                and epoch % self.cfg.desync_every == 0)

    def desync_check(self, params_host: Any) -> bool:
        """Compare this rank's per-leaf param digests against rank 0's
        (broadcast through the consensus channel). Returns True on
        local mismatch; the caller feeds it into agree_step so the
        VERDICT — like every recovery decision — is agreed."""
        digs = digest_leaves(params_host)
        ref = self._consensus.broadcast0(digs)
        bad = np.nonzero(digs != ref)[0]
        mism = int(bad.size)
        self.last_desync_mismatch = mism
        names = digest_leaf_names(params_host)
        self.last_desync_leaves = [
            names[i] for i in bad[:8] if i < len(names)]
        return mism > 0

    def resync(self, trainer, epoch: int) -> None:
        """Adopt rank 0's full state everywhere: rank 0 writes a
        digest-verified state to the shared coordination dir, a psum
        barrier publishes it, and EVERY rank loads + restores it.
        (Collectives can't repair a desync — XLA already believes the
        replicated arrays are identical — so repair goes out-of-band
        like the partition artifact does. Every step below is either
        executed on all ranks or collective-free, so the ranks'
        collective streams stay aligned: host_state's allgather and
        restore_state's device_put broadcasts are lockstep, the save is
        host-side, and loading on rank 0 too guarantees every rank
        holds the byte-identical on-disk state.)"""
        from ..utils.checkpoint import load_checkpoint, save_checkpoint

        d = os.path.join(self.cfg.dir or ".", "resync")
        host = trainer.host_state()  # collective: ALL ranks
        if self.rank == 0:
            save_checkpoint(d, host, epoch, keep=1)
        self.barrier()  # peers must not read before rank 0 finished
        state, _ = load_checkpoint(d, host)
        trainer.restore_state(state)  # device_put broadcasts: ALL ranks
        self.barrier()  # nobody races ahead of slow loaders
