"""Storage-fault injection + the atomic-write seam for durable writers.

Every CRC/digest mechanism in this repo (checkpoint generations,
membership ledger, stream delta files, tuning sidecar) verifies READS;
this module is where WRITE failure becomes injectable and survivable.
A process-wide :class:`FaultyIO` shim sits at the open/write/fsync/
rename seams the durable writers share; the fault-plan grammar
(resilience/faults.py) arms it with four storage-fault kinds:

  enospc      writes raise OSError(ENOSPC) — the disk is full
  torn-write  the temp file is truncated to half and EIO raised
              BEFORE the rename, so the destination is untouched: a
              torn artifact is indistinguishable from an absent one
              (the property temp+rename exists to guarantee)
  ro-dir      opens-for-write raise OSError(EROFS) — the artifact
              directory went read-only (remount, quota, NFS hiccup)
  slow-fs     every seam op sleeps a configured number of
              milliseconds — a degraded shared filesystem

When nothing is armed every seam is a no-op (one falsy dict check), so
production writers pay nothing. Degradation policies live with the
writers: checkpoint saves retry at the next boundary (+ optional
fallback dir, parallel/trainer.py), the metrics sink ring-buffers and
re-drains (obs/metrics.py), the membership ledger queues payloads and
keeps the last durable generation authoritative
(resilience/elastic.py). All of them emit loud ``io-degraded``
fault/recovery records (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import errno
import os
import time
from typing import Dict, Optional, Tuple

# fault kinds this shim understands; resilience/faults.py registers
# them in the fault-plan grammar and the trainer arms/disarms the
# process-wide shim at epoch boundaries
IO_KINDS = ("enospc", "torn-write", "ro-dir", "slow-fs")

# the fault/recovery record kind every storage degradation policy emits
IO_DEGRADED = "io-degraded"


class FaultyIO:
    """Process-wide armable IO-fault state + the seam checks.

    Writers never branch on fault kinds themselves — they call
    :meth:`gate` at each seam (open / write / fsync / rename) and
    :meth:`maybe_tear` on their finished temp file just before the
    rename. Unarmed, both are single-dict-lookup no-ops.
    """

    def __init__(self):
        self._armed: Dict[str, Dict[str, int]] = {}

    # -- arming -----------------------------------------------------------

    def arm(self, kind: str, *, ms: int = 0) -> None:
        if kind not in IO_KINDS:
            raise ValueError(
                f"unknown IO fault kind {kind!r}; known: "
                f"{', '.join(IO_KINDS)}")
        self._armed[kind] = {"ms": int(ms)}

    def disarm(self, kind: str) -> bool:
        """True when `kind` was armed (and is now disarmed)."""
        return self._armed.pop(kind, None) is not None

    def disarm_all(self) -> Tuple[str, ...]:
        kinds = tuple(sorted(self._armed))
        self._armed.clear()
        return kinds

    def active(self, kind: str) -> bool:
        return kind in self._armed

    def armed_kinds(self) -> Tuple[str, ...]:
        return tuple(sorted(self._armed))

    # -- seams ------------------------------------------------------------

    def gate(self, path: str, op: str) -> None:
        """Apply armed faults at one seam. `op` is one of 'open',
        'write', 'fsync', 'rename'. ro-dir fires at open-for-write,
        enospc at write/fsync (a full disk lets you open but not
        flush), slow-fs sleeps at every seam."""
        if not self._armed:
            return
        slow = self._armed.get("slow-fs")
        if slow is not None and slow["ms"] > 0:
            time.sleep(slow["ms"] / 1000.0)
        if op == "open" and "ro-dir" in self._armed:
            raise OSError(errno.EROFS,
                          "read-only file system (injected ro-dir)", path)
        if op in ("write", "fsync") and "enospc" in self._armed:
            raise OSError(errno.ENOSPC,
                          "no space left on device (injected enospc)",
                          path)

    def maybe_tear(self, tmp_path: str) -> None:
        """torn-write seam: called on a fully-written TEMP file just
        before its rename. Truncates the temp to half its bytes and
        raises EIO — the destination is never touched, so recovery sees
        the previous good artifact (or nothing), never half of one."""
        if "torn-write" not in self._armed:
            return
        try:
            size = os.path.getsize(tmp_path)
            with open(tmp_path, "r+b") as f:
                f.truncate(size // 2)
        except OSError:
            pass  # the raise below is the injection either way
        raise OSError(errno.EIO,
                      "interrupted write (injected torn-write)", tmp_path)


# THE process-wide shim every durable writer routes through. Tests and
# the trainer's boundary arming mutate this instance; anything not
# armed here behaves exactly as before this module existed.
FAULTY_IO = FaultyIO()


def write_text_atomic(path: str, text: str, *, fsync: bool = True,
                      io: Optional[FaultyIO] = None) -> None:
    """The one temp+rename text writer (membership ledger, rejoin
    requests, tuning sidecar, readiness files, stream delta JSONL):
    write to a pid-suffixed temp, optionally fsync, rename into place.
    An interrupted (or injected-torn) write leaves the destination
    untouched. Raises OSError on any failure — degradation policy is
    the CALLER's job."""
    io = io if io is not None else FAULTY_IO
    io.gate(path, "open")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            io.gate(path, "write")
            f.write(text)
            f.flush()
            if fsync:
                io.gate(path, "fsync")
                os.fsync(f.fileno())
        io.maybe_tear(tmp)
        io.gate(path, "rename")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass  # orphaned temp: cosmetic, never load-bearing
