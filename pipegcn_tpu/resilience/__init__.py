"""Fault tolerance for long-running training jobs.

The paper's convergence guarantee (PAPER.md) holds only while training
stays in a healthy regime; on real pods, bf16 + staleness-1 can produce
non-finite losses, machines get preempted, and checkpoints rot on
shared filesystems. This package makes the trainer detect and survive
all three (docs/RESILIENCE.md):

  sentinel.py    DivergenceSentinel — trips on non-finite / exploding
                 loss or grad-norm (the telemetry the jitted step
                 already harvests) and drives rollback + backoff
  preemption.py  SIGTERM/SIGINT → checkpoint at the next epoch boundary
                 and exit with a distinct resumable status code
  faults.py      deterministic fault-injection plans
                 ("nan-loss@5:r1,sigterm@8,corrupt-ckpt@10") for chaos
                 testing the recovery paths; :rN targets one rank
  numerics.py    numerical robustness — in-graph non-finite tripwire
                 (NaN provenance by phase), dynamic loss scaling with
                 overflow-skip, and the kernel fallback ladder
                 (block -> bucket -> sorted-XLA on backend crashes)
  coord.py       cross-rank coordination for jax.distributed runs —
                 fault consensus (one tiny psum per dispatch boundary
                 makes every recovery action lockstep across ranks),
                 heartbeat watchdog (dead peers become PeerLost →
                 resumable exit 75 instead of an infinite collective
                 hang), and the param-digest desync detector
  elastic.py     elastic membership — the cli.elastic supervisor that
                 turns "survives preemption" into "trains through
                 preemption": on rank death it re-plans the
                 partition→rank assignment over the survivors
                 (ceil(P/R') shards each), records the generation in a
                 CRC-guarded membership ledger, and relaunches from
                 the last good checkpoint; exponential backoff,
                 --max-restarts and a restart-storm circuit breaker
                 bound crash loops
  storage.py     process-wide storage-fault shim (enospc / torn-write
                 / ro-dir / slow-fs at the open/write/fsync/rename
                 seams every durable writer shares) and the one
                 temp+rename atomic text writer; each writer's
                 io-degraded policy lives with the writer
  soak.py        seeded full-stack chaos soak — per-episode fault
                 schedules composed from ALL kinds above over an
                 elastic-supervised streaming run, five structural
                 invariants over the artifacts (scripts/soak.py)

Checkpoint hardening (per-leaf digests, keep-last-N generations,
corrupt-generation fallback) lives in utils/checkpoint.py; the fault /
recovery telemetry records it emits are contracted in obs/schema.py.

No reference counterpart: the reference's gloo collectives simply hang
when any rank dies (SURVEY.md §5).
"""

from .coord import (
    Agreed,
    CoordConfig,
    Coordinator,
    FaultConsensus,
    HeartbeatWatchdog,
    PeerLost,
    digest_leaves,
)
from .elastic import (
    Assignment,
    ElasticConfig,
    ElasticSupervisor,
    LedgerCorrupt,
    MembershipLedger,
    RestartPolicy,
    plan_assignment,
)
from .faults import FaultPlan, corrupt_latest_checkpoint
from .storage import (
    FAULTY_IO,
    IO_DEGRADED,
    IO_KINDS,
    FaultyIO,
    write_text_atomic,
)
from .numerics import (
    PHASES,
    KernelFallbackError,
    LossScaleConfig,
    LossScaler,
    fallback_ladder,
    first_nonfinite_phase,
    is_kernel_error,
)
from .preemption import (EXIT_PREEMPTED, Preempted, PreemptionHandler,
                         classify_exit)
from .sentinel import DivergenceError, DivergenceSentinel, SentinelConfig

__all__ = [
    "DivergenceError",
    "DivergenceSentinel",
    "SentinelConfig",
    "PHASES",
    "KernelFallbackError",
    "LossScaleConfig",
    "LossScaler",
    "fallback_ladder",
    "first_nonfinite_phase",
    "is_kernel_error",
    "EXIT_PREEMPTED",
    "Preempted",
    "PreemptionHandler",
    "classify_exit",
    "Assignment",
    "ElasticConfig",
    "ElasticSupervisor",
    "LedgerCorrupt",
    "MembershipLedger",
    "RestartPolicy",
    "plan_assignment",
    "FaultPlan",
    "corrupt_latest_checkpoint",
    "FAULTY_IO",
    "FaultyIO",
    "IO_DEGRADED",
    "IO_KINDS",
    "write_text_atomic",
    "Agreed",
    "CoordConfig",
    "Coordinator",
    "FaultConsensus",
    "HeartbeatWatchdog",
    "PeerLost",
    "digest_leaves",
]
