"""Numerical robustness: NaN provenance, loss scaling, kernel fallback.

Three guardrails that make low-precision training a supervised
subsystem instead of a post-hoc NaN in a results file
(docs/RESILIENCE.md "Numerics"):

1. **In-graph non-finite tripwire.** The jitted step counts non-finite
   elements per pipeline phase (post-halo-concat, post-SpMM,
   post-dense, logits, loss, grads) — a handful of `isfinite`
   reductions riding the existing metrics harvest, so when the
   divergence sentinel trips on a NaN the `fault` record names the
   phase where the NaN was BORN (`first_nonfinite_phase`), not just
   "loss is nan". The probe hook lives in `models.sage.forward`
   (`probe=` callback); this module owns the phase vocabulary and the
   host-side interpretation.

2. **Dynamic loss scaling** (`LossScaler`) for the bf16 / fp8-remainder
   path, ZeRO/Megatron style: the step's loss is multiplied by a scale
   before the backward, the reduced gradients are divided by it, and a
   non-finite gradient ANYWHERE skips the parameter update in-graph
   (`jnp.where` select — fused multi-epoch dispatches stay one
   program). The host state machine halves the scale on overflow
   (`backoff`), regrows it after `growth_interval` clean epochs, and
   counts skips — every transition lands in the metrics JSONL as a
   contracted `numerics` record.

3. **Kernel fallback ladder** (`fallback_ladder` + trainer wiring): a
   TPU-backend compile-or-first-dispatch crash downgrades the
   aggregation kernel block -> bucket -> sorted-XLA automatically, with
   a contracted `fallback` record, instead of killing the run — the
   Dorylus-style graceful degradation the block-kernel products-shape
   crash (VERDICT r5 "What's weak" 3) demanded. The ladder is the
   safety net UNDER the measured auto-tuner dispatch (ops/tuner.py):
   the tuner picks the fastest measured kernel, the ladder guarantees
   a crashing pick degrades instead of killing the run.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# ---------------- tripwire phases -------------------------------------

# Phase vocabulary, in DATAFLOW ORDER — the first phase with a nonzero
# non-finite count is where the NaN was born (everything downstream is
# contamination, not cause). `input` covers the features entering the
# step; `loss`/`grads` are probed by the trainer around the model.
PHASES = ("input", "halo_concat", "spmm", "dense", "norm", "logits",
          "loss", "grads")


def first_nonfinite_phase(counts: Dict[str, Any]) -> Optional[str]:
    """Earliest phase (dataflow order) with a nonzero non-finite count,
    or None when every probed tensor was finite. `counts` maps phase ->
    scalar/array count (a fused block's [k] arrays count as nonzero
    when any epoch in the block tripped)."""
    for ph in PHASES:
        v = counts.get(ph)
        if v is None:
            continue
        if float(np.sum(np.asarray(v, np.float64))) > 0:
            return ph
    return None


def epoch_nonfinite_counts(counts: Dict[str, Any], j: int
                           ) -> Dict[str, int]:
    """Per-phase counts for epoch j of a fused block ([k]-array values;
    scalars broadcast). Only nonzero phases are returned — the record
    extra stays small."""
    out = {}
    for ph, v in counts.items():
        a = np.atleast_1d(np.asarray(v))
        c = int(a[j] if a.size > 1 else a[0])
        if c:
            out[ph] = c
    return out


# ---------------- loss scaling ----------------------------------------


@dataclasses.dataclass
class LossScaleConfig:
    """`--loss-scale auto|<N>|off` parsed into a state-machine config.

    mode 'auto': dynamic — start at `init_scale`, multiply by `backoff`
    on every overflow epoch (the skipped step), regrow by
    `growth_factor` after `growth_interval` consecutive clean epochs.
    mode 'static': fixed scale N; overflow still skips the step (the
    guardrail half of scaling) but the scale never moves.
    mode 'off': scale 1, no overflow-skip select traced into the step.
    """
    mode: str = "off"                 # off | auto | static
    init_scale: float = 2.0 ** 15
    backoff: float = 0.5
    growth_factor: float = 2.0
    growth_interval: int = 200
    max_scale: float = 2.0 ** 24
    min_scale: float = 1.0

    @classmethod
    def parse(cls, spec: str) -> "LossScaleConfig":
        """CLI surface: 'off' | 'auto' | a positive number (static)."""
        s = (spec or "off").strip().lower()
        if s in ("off", "none", "", "1", "1.0"):
            return cls(mode="off")
        if s == "auto":
            return cls(mode="auto")
        try:
            v = float(s)
        except ValueError:
            raise ValueError(
                f"bad --loss-scale {spec!r}: expected 'auto', 'off' or "
                f"a positive number") from None
        if not (v > 0 and np.isfinite(v)):
            raise ValueError(
                f"bad --loss-scale {spec!r}: scale must be a positive "
                f"finite number")
        return cls(mode="static", init_scale=v)

    @property
    def enabled(self) -> bool:
        return self.mode != "off"


class LossScaler:
    """Host-side loss-scale state machine; one instance per run.

    The trainer passes `scale` into each dispatch as a traced scalar
    (no recompile on change) and harvests a per-epoch overflow flag
    (non-finite reduced gradient -> the in-graph select already skipped
    the update). `update()` consumes the flags and returns the event
    list (for `numerics` records); `scale` is what the NEXT dispatch
    should use."""

    def __init__(self, cfg: Optional[LossScaleConfig] = None):
        self.cfg = cfg or LossScaleConfig()
        self.scale = self.cfg.init_scale if self.cfg.enabled else 1.0
        self.n_skipped = 0     # epochs whose update was skipped
        self.n_backoffs = 0    # scale halvings (auto mode)
        self.n_growths = 0
        self._clean_streak = 0

    def update(self, first_epoch: int,
               overflow_flags: Sequence[float]) -> List[Dict[str, Any]]:
        """Consume one dispatched block's per-epoch overflow flags
        (truthy = that epoch's update was skipped in-graph). Returns
        the state-machine events as record-ready dicts."""
        cfg = self.cfg
        events: List[Dict[str, Any]] = []
        if not cfg.enabled:
            return events
        flags = np.atleast_1d(np.asarray(overflow_flags))
        for j, f in enumerate(flags.tolist()):
            epoch = first_epoch + j
            if f:
                self.n_skipped += 1
                self._clean_streak = 0
                ev = {"kind": "overflow", "epoch": epoch,
                      "scale": self.scale, "skipped": True}
                if cfg.mode == "auto" and \
                        self.scale * cfg.backoff >= cfg.min_scale:
                    self.scale *= cfg.backoff
                    self.n_backoffs += 1
                    ev["new_scale"] = self.scale
                events.append(ev)
            else:
                self._clean_streak += 1
                if cfg.mode == "auto" and \
                        self._clean_streak >= cfg.growth_interval and \
                        self.scale * cfg.growth_factor <= cfg.max_scale:
                    self.scale *= cfg.growth_factor
                    self.n_growths += 1
                    self._clean_streak = 0
                    events.append({"kind": "growth", "epoch": epoch,
                                   "scale": self.scale})
        return events


def sanitize_for_sentinel(losses, grad_norms, overflow_flags):
    """Mask overflow-skipped epochs out of the sentinel's view: a
    loss-scale overflow is a HANDLED event (step skipped, scale backed
    off), not a divergence — its non-finite grad norm must not trigger
    a rollback. Flagged epochs are replaced with the nearest preceding
    clean value in the block (or the nearest following one when the
    block starts flagged); a fully-flagged block returns (None, None)
    meaning "nothing for the sentinel to check"."""
    losses = np.array(np.atleast_1d(losses), np.float64)
    gn = np.array(np.atleast_1d(grad_norms), np.float64)
    flags = np.atleast_1d(np.asarray(overflow_flags)).astype(bool)
    if flags.size == 1 and losses.size > 1:
        flags = np.repeat(flags, losses.size)
    clean = np.flatnonzero(~flags[:losses.size])
    if clean.size == 0:
        return None, None
    for j in np.flatnonzero(flags[:losses.size]):
        prev = clean[clean < j]
        src = int(prev[-1]) if prev.size else int(clean[0])
        losses[j] = losses[src]
        if j < gn.size and src < gn.size:
            gn[j] = gn[src]
    return losses, gn


# ---------------- kernel fallback ladder ------------------------------


class KernelFallbackError(RuntimeError):
    """Every rung of the kernel fallback ladder failed."""


# Downgrade order: each impl's next-most-robust formulation. The ladder
# ends at the raw sorted-XLA gather+segment-sum path ('xla') — the
# least performant but most battle-tested formulation; if THAT crashes
# the failure is not the kernel's.
_LADDER = {
    "block": "bucket",
    "bucket": "xla",
    "auto": None,    # resolved by the trainer to what auto picked
    "xla": None,
}


def fallback_ladder(impl: str) -> List[str]:
    """Remaining rungs below `impl` ([] when already at the bottom)."""
    out: List[str] = []
    cur = _LADDER.get(impl, "xla" if impl != "xla" else None)
    while cur is not None:
        out.append(cur)
        cur = _LADDER.get(cur)
    return out


# Error-message fragments that identify a kernel/backend dispatch or
# compile failure (vs. an ordinary Python error the ladder must NOT
# swallow). Matched case-insensitively against repr(exc).
_KERNEL_ERROR_MARKERS = (
    "tpu backend",            # INTERNAL: TPU backend error (VERDICT r5)
    "xlaruntimeerror",
    "jaxruntimeerror",
    "internal: ",
    "resource exhausted",
    "mosaic",                 # Pallas-TPU lowering failures
    "pallas",
    "vmem",                   # VMEM OOM / spill failures
    "fault-injected kernel",  # resilience.faults kernel-crash kind
)


def is_kernel_error(exc: BaseException) -> bool:
    """Heuristic: does this exception look like a kernel/backend
    compile-or-dispatch failure the fallback ladder should absorb?
    KeyboardInterrupt & friends are never absorbed."""
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return False
    txt = repr(exc).lower()
    return any(m in txt for m in _KERNEL_ERROR_MARKERS)


def summarize_numerics(records: Sequence[Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """Collapse a run's numerics/fallback telemetry for the report CLI:
    first-NaN phase (from tripwire `numerics` records or the `phase`
    extra on divergence faults), loss-scale skip/backoff/growth counts
    and last scale, and the kernel fallbacks taken. Empty dict when
    the run produced none of it."""
    out: Dict[str, Any] = {}
    numerics = [r for r in records if r.get("event") == "numerics"]
    skips = [r for r in numerics if r.get("kind") == "overflow"]
    if skips:
        out["loss_scale_skips"] = len(skips)
        out["loss_scale_backoffs"] = sum(
            1 for r in skips if r.get("new_scale") is not None)
        last = skips[-1]
        out["loss_scale_last"] = last.get("new_scale", last.get("scale"))
    growths = [r for r in numerics if r.get("kind") == "growth"]
    if growths:
        out["loss_scale_growths"] = len(growths)
        out["loss_scale_last"] = growths[-1].get("scale")
    trip = next((r for r in numerics if r.get("kind") == "tripwire"
                 and r.get("phase")), None)
    if trip is None:
        trip = next((r for r in records if r.get("event") == "fault"
                     and r.get("phase")), None)
    if trip is not None:
        out["first_nan_phase"] = trip["phase"]
        if isinstance(trip.get("epoch"), int):
            out["first_nan_epoch"] = trip["epoch"]
    falls = [r for r in records if r.get("event") == "fallback"]
    if falls:
        out["kernel_fallbacks"] = [
            f"{r.get('from_impl')}->{r.get('to_impl')}" for r in falls]
    return out
