"""Elastic membership: train THROUGH rank loss, not just survive it.

PRs 2-3 made every rank exit 75 with a digest-verified checkpoint when
a peer dies; a human still had to notice and relaunch with the SAME
world size. This module closes the loop with a supervisor
(``python -m pipegcn_tpu.cli.elastic -- <train flags>``) that

  1. launches the rank processes of a multi-host run,
  2. watches for death (SIGKILL/OOM/crash), resumable exits (75) and
     completion (0),
  3. on a membership change computes a new partition->rank assignment
     (P partitions over the R' survivors, each process owning
     ceil(P/R') shards through the existing multi-shard SPMD
     machinery — a node's mesh slice is just "more local devices"),
  4. relaunches the survivors from the last good checkpoint
     generation. The dead rank's comm carry needs NO explicit remap:
     checkpoints always hold the FULL [P, ...] carry (host_state's
     allgather), and ``Trainer.restore_state`` re-device_puts it under
     the NEW mesh's shardings, so partition i's rows land on whoever
     owns partition i now.

Membership is durable: a CRC-guarded ``membership-<gen>.json`` ledger
in the coord dir records every generation (members, assignment,
trigger, restart latency). The generation counter is monotonic across
supervisor restarts — a new supervisor resumes at latest+1 with the
last recorded membership. Rejoin is ledger-driven too: a returning
rank drops a ``rejoin-r<k>.json`` request (or the fault plan schedules
``rejoin@G``) and the supervisor folds it into the next generation's
assignment, rebalancing shards back.

Crash-looping fleets degrade gracefully instead of thrashing:
exponential backoff between relaunches, a hard ``--max-restarts`` cap,
and a restart-storm circuit breaker (too many restarts inside a
sliding window). Both stop paths leave the last resumable checkpoint
untouched and exit 75 so an outer scheduler can still resume later.

Every member death is additionally DIAGNOSED (obs/postmortem.py runs
over the coord dir's black-box dumps, rank logs and metrics streams);
the verdict rides the next ledger generation and the membership
metrics record. Deterministic verdict classes — corrupt-artifact,
config-error, fallback-exhausted, failures a relaunch reproduces —
get ONE gated retry and then stop the supervisor hard (rc 1, not 75)
instead of burning ``--max-restarts`` (docs/RESILIENCE.md "Fail fast
vs restart").
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import signal
import subprocess
import sys
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .preemption import EXIT_PREEMPTED, classify_exit
from .storage import IO_DEGRADED, write_text_atomic

# env vars the supervisor sets on every child; cli/main.py reads the
# generation into CoordConfig so heartbeat files are generation-keyed
# (stale-heartbeat poisoning fix) and MEMBER tells a relaunched process
# which ledger identity it carries (node ranks are re-dealt per gen)
GENERATION_ENV = "PIPEGCN_MEMBERSHIP_GEN"
MEMBER_ENV = "PIPEGCN_ELASTIC_MEMBER"

LEDGER_PREFIX = "membership-"
REJOIN_PREFIX = "rejoin-r"


# ---------------------------------------------------------------------------
# assignment math
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Assignment:
    """Partition->member mapping for one membership generation.

    ``members`` is the sorted member-id list; the first ``n_nodes`` of
    them get node ranks 0..n_nodes-1 (the contiguous-block ownership
    the mesh construction implies: node i owns partitions
    [i*parts_per_node, min((i+1)*parts_per_node, n_parts))). Members
    beyond ``n_nodes`` are idle spares this generation — they exist
    when ceil-division needs fewer nodes than there are members.
    """

    n_parts: int
    members: Tuple[int, ...]
    parts_per_node: int
    n_nodes: int

    def node_rank_of(self, member: int) -> Optional[int]:
        """Node rank this member runs at, None when idle this gen."""
        i = self.members.index(member)
        return i if i < self.n_nodes else None

    def parts_of_node(self, node_rank: int) -> Tuple[int, ...]:
        lo = node_rank * self.parts_per_node
        hi = min(lo + self.parts_per_node, self.n_parts)
        return tuple(range(lo, hi))

    def active_members(self) -> Tuple[int, ...]:
        return self.members[: self.n_nodes]

    def as_json(self) -> Dict[str, object]:
        """JSON shape recorded in the ledger and the `membership`
        metrics record (docs/OBSERVABILITY.md schema v6)."""
        return {
            "n_parts": self.n_parts,
            "parts_per_node": self.parts_per_node,
            "n_nodes": self.n_nodes,
            "members": list(self.members),
            "parts": {str(m): list(self.parts_of_node(i))
                      for i, m in enumerate(self.active_members())},
            "idle": list(self.members[self.n_nodes:]),
        }


def plan_assignment(n_parts: int, members: Sequence[int]) -> Assignment:
    """P partitions over the surviving members: each active node owns
    ceil(P/R') contiguous partitions. Contiguity is load-bearing, not
    cosmetic — ``make_mesh`` assigns the first P devices in
    process-major order, so node i's local devices ARE partitions
    [i*k, (i+1)*k) and the v3 mmap artifact's per-rank edge files can
    be opened without any shuffle."""
    ms = sorted(set(int(m) for m in members))
    if not ms:
        raise ValueError("cannot plan an assignment with zero members")
    if n_parts < 1:
        raise ValueError(f"n_parts must be >= 1, got {n_parts}")
    k = math.ceil(n_parts / len(ms))
    n_nodes = math.ceil(n_parts / k)
    return Assignment(n_parts=int(n_parts), members=tuple(ms),
                      parts_per_node=k, n_nodes=n_nodes)


# ---------------------------------------------------------------------------
# durable membership ledger
# ---------------------------------------------------------------------------

class LedgerCorrupt(RuntimeError):
    """A membership record failed its CRC or JSON parse."""


def _crc_of(payload: Dict) -> int:
    blob = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return zlib.crc32(blob) & 0xFFFFFFFF


class MembershipLedger:
    """CRC-guarded ``membership-<gen>.json`` records in the coord dir.

    One file per generation, written atomically (tmp + rename) as
    ``{"crc32": ..., "payload": {...}}`` where the CRC covers the
    canonical-JSON payload bytes. Generations are monotonic: a write
    must strictly exceed the latest on-disk generation, ACROSS
    supervisor restarts — the counter lives in the filenames, not in
    any process.

    Rejoin requests ride the same directory: ``rejoin-r<k>.json``,
    dropped by a returning rank (or the fault plan's ``rejoin@G``
    schedule) and consumed by the supervisor at the next membership
    event.
    """

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def path_for(self, generation: int) -> str:
        return os.path.join(self.dir, f"{LEDGER_PREFIX}{generation:06d}.json")

    def generations(self) -> List[int]:
        out = []
        for p in glob.glob(os.path.join(self.dir, LEDGER_PREFIX + "*.json")):
            stem = os.path.basename(p)[len(LEDGER_PREFIX):-len(".json")]
            try:
                out.append(int(stem))
            except ValueError:
                continue
        return sorted(out)

    def latest_generation(self) -> int:
        gens = self.generations()
        return gens[-1] if gens else -1

    def read(self, generation: int) -> Dict:
        path = self.path_for(generation)
        try:
            with open(path, "r", encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError) as exc:
            raise LedgerCorrupt(
                f"membership record {path} unreadable: {exc}") from exc
        payload = rec.get("payload")
        if not isinstance(payload, dict) or "crc32" not in rec:
            raise LedgerCorrupt(f"membership record {path} malformed")
        if int(rec["crc32"]) != _crc_of(payload):
            raise LedgerCorrupt(
                f"membership record {path} failed CRC "
                f"(stored {rec['crc32']}, computed {_crc_of(payload)})")
        return payload

    def latest(self) -> Optional[Dict]:
        """Newest record that passes its CRC, walking backwards past
        corrupt generations (same fallback discipline as checkpoint
        loading)."""
        for gen in reversed(self.generations()):
            try:
                return self.read(gen)
            except LedgerCorrupt:
                continue
        return None

    def append(self, *, generation: int, members: Sequence[int],
               assignment: Assignment, trigger: str,
               restart_latency_s: Optional[float] = None,
               diagnosis: Optional[Dict] = None) -> Dict:
        latest = self.latest_generation()
        if generation <= latest:
            raise ValueError(
                f"membership generation must be monotonic: {generation} "
                f"<= latest on-disk generation {latest}")
        payload = {
            "generation": int(generation),
            "members": sorted(int(m) for m in members),
            "assignment": assignment.as_json(),
            "trigger": str(trigger),
            "time_unix": time.time(),
        }
        if restart_latency_s is not None:
            payload["restart_latency_s"] = float(restart_latency_s)
        if diagnosis is not None:
            # the postmortem verdict that explains why this generation
            # exists (obs/postmortem.py) — slim form, evidence lives in
            # the metrics stream's diagnosis record
            payload["diagnosis"] = dict(diagnosis)
        rec = {"crc32": _crc_of(payload), "payload": payload}
        path = self.path_for(generation)
        # temp+rename through the storage-fault seams: a torn or failed
        # append leaves no membership-<gen>.json at all, so latest()
        # keeps answering with the previous durable generation
        write_text_atomic(path, json.dumps(rec, sort_keys=True))
        return payload

    # -- rejoin requests ---------------------------------------------------

    def rejoin_path(self, member: int) -> str:
        return os.path.join(self.dir, f"{REJOIN_PREFIX}{int(member)}.json")

    def request_rejoin(self, member: int) -> str:
        """Register a returning rank; the supervisor folds it into the
        next generation's assignment."""
        path = self.rejoin_path(member)
        write_text_atomic(
            path,
            json.dumps({"member": int(member), "time_unix": time.time()}),
            fsync=False)
        return path

    def pending_rejoins(self) -> List[int]:
        out = []
        for p in glob.glob(os.path.join(self.dir, REJOIN_PREFIX + "*.json")):
            stem = os.path.basename(p)[len(REJOIN_PREFIX):-len(".json")]
            try:
                out.append(int(stem))
            except ValueError:
                continue
        return sorted(out)

    def clear_rejoin(self, member: int) -> None:
        try:
            os.unlink(self.rejoin_path(member))
        except FileNotFoundError:
            pass


# ---------------------------------------------------------------------------
# restart policy: backoff + cap + storm circuit breaker
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RestartDecision:
    action: str            # "restart" | "stop"
    delay_s: float = 0.0   # backoff before the relaunch
    reason: str = ""       # "max-restarts" | "restart-storm" on stop


class RestartPolicy:
    """Decides whether (and after how long) a membership event may
    relaunch the fleet. Three independent brakes:

      * exponential backoff: base * 2^(consecutive-1), capped; the
        consecutive counter resets once a generation survives
        ``stable_s`` (note_stable), so one long-lived fleet doesn't
        pay for last week's crash loop
      * hard cap: more than ``max_restarts`` total restarts -> stop
      * storm breaker: ``storm_threshold`` restarts inside a sliding
        ``storm_window_s`` -> stop, even below the hard cap — the
        signature of a config that kills every generation instantly

    Both stop paths are RESUMABLE stops: the supervisor exits 75 with
    the last good checkpoint intact.
    """

    def __init__(self, max_restarts: int = 8, backoff_base_s: float = 1.0,
                 backoff_max_s: float = 30.0, storm_window_s: float = 120.0,
                 storm_threshold: int = 5, stable_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.storm_window_s = float(storm_window_s)
        self.storm_threshold = int(storm_threshold)
        self.stable_s = float(stable_s)
        self._clock = clock
        self.total = 0
        self.consecutive = 0
        self._recent: List[float] = []

    def note_stable(self, ran_s: float) -> None:
        """The last generation ran `ran_s` before its membership event;
        a long-enough run resets the backoff exponent (not the total
        cap — max_restarts bounds lifetime restarts)."""
        if ran_s >= self.stable_s:
            self.consecutive = 0

    def decide(self) -> RestartDecision:
        now = self._clock()
        self.total += 1
        self.consecutive += 1
        self._recent = [t for t in self._recent
                        if now - t <= self.storm_window_s]
        self._recent.append(now)
        if self.total > self.max_restarts:
            return RestartDecision("stop", reason="max-restarts")
        if len(self._recent) >= self.storm_threshold:
            return RestartDecision("stop", reason="restart-storm")
        delay = min(self.backoff_base_s * (2 ** (self.consecutive - 1)),
                    self.backoff_max_s)
        return RestartDecision("restart", delay_s=delay)


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ElasticConfig:
    max_restarts: int = 8
    backoff_base_s: float = 1.0
    backoff_max_s: float = 30.0
    storm_window_s: float = 120.0
    storm_threshold: int = 5
    stable_s: float = 60.0
    poll_s: float = 0.25
    # extra seconds past the watchdog horizon to wait for survivors to
    # notice a dead peer and exit 75 on their own before being culled
    grace_extra_s: float = 60.0
    metrics_out: str = ""  # default: <coord_dir>/membership.jsonl


def _strip_flag(argv: List[str], flag: str, has_value: bool = True) -> List[str]:
    out, i = [], 0
    while i < len(argv):
        a = argv[i]
        if a == flag:
            i += 2 if has_value else 1
            continue
        if has_value and a.startswith(flag + "="):
            i += 1
            continue
        out.append(a)
        i += 1
    return out


def _flag_value(argv: List[str], flag: str) -> Optional[str]:
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def _member_metrics_path(base: str, generation: int, member: int) -> str:
    """Per-(generation, member) metrics file: a relaunch must never
    clobber a previous generation's epoch records — the drill's
    epoch-continuity check merges across all of them."""
    stem, ext = os.path.splitext(base)
    return f"{stem}.g{generation}.m{member}{ext or '.jsonl'}"


def _cpu_device_flags(env: Dict[str, str], parts_per_node: int) -> None:
    """On the CPU backend a 'node' gets its devices from
    --xla_force_host_platform_device_count; keep it in sync with the
    generation's parts_per_node (this IS the redistribution mechanism
    on the test mesh: fewer processes, more virtual devices each)."""
    plat = env.get("PIPEGCN_PLATFORM") or env.get("JAX_PLATFORMS", "")
    if "cpu" not in plat:
        return
    kept = [t for t in env.get("XLA_FLAGS", "").split()
            if not t.startswith("--xla_force_host_platform_device_count")]
    kept.append(f"--xla_force_host_platform_device_count={parts_per_node}")
    env["XLA_FLAGS"] = " ".join(kept)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Child:
    """One launched rank process plus its ledger identity."""

    def __init__(self, member: int, node_rank: int, handle, log_path: str):
        self.member = member
        self.node_rank = node_rank
        self.handle = handle
        self.log_path = log_path
        self.outcome: Optional[str] = None  # completed|resumable|dead|culled

    def poll(self) -> Optional[int]:
        return self.handle.poll()


def _default_popen(cmd: List[str], env: Dict[str, str], log_path: str):
    # children inherit nothing interactive; stdout/stderr land in a
    # per-(gen, member) file so a post-mortem never depends on the
    # supervisor having drained pipes
    logf = open(log_path, "ab")
    try:
        return subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf,
                                start_new_session=True)
    finally:
        logf.close()


class ElasticSupervisor:
    """Launch, watch, redistribute, relaunch — the membership loop.

    ``train_argv`` is everything after the CLI's ``--`` separator: a
    verbatim ``cli.main`` flag list. The supervisor owns and overrides
    ``--node-rank``, ``--parts-per-node``, ``--port``,
    ``--watchdog-dir`` and ``--metrics-out`` per child; every other
    flag passes through untouched.
    """

    def __init__(self, train_argv: Sequence[str],
                 cfg: Optional[ElasticConfig] = None,
                 popen: Callable = _default_popen,
                 log: Callable[[str], None] = None):
        from ..cli.parser import create_parser

        self.cfg = cfg or ElasticConfig()
        self.train_argv = list(train_argv)
        self.popen = popen
        self._log = log or (lambda s: print(f"[elastic] {s}", flush=True))
        args = create_parser().parse_args(self.train_argv)
        if not args.checkpoint_dir:
            raise ValueError(
                "elastic supervision requires --checkpoint-dir in the "
                "train flags: redistribution resumes survivors from the "
                "last good checkpoint generation")
        self.args = args
        self.n_parts = int(args.n_partitions)
        # the ledger home must be STABLE across generations while the
        # coordination port changes per relaunch, so never leave the
        # coord dir keyed on the port: pin one and pass it down
        self.coord_dir = args.watchdog_dir or os.path.join(
            args.partition_dir, "coord-elastic")
        self.ledger = MembershipLedger(self.coord_dir)
        self.policy = RestartPolicy(
            max_restarts=self.cfg.max_restarts,
            backoff_base_s=self.cfg.backoff_base_s,
            backoff_max_s=self.cfg.backoff_max_s,
            storm_window_s=self.cfg.storm_window_s,
            storm_threshold=self.cfg.storm_threshold,
            stable_s=self.cfg.stable_s)
        self._metrics = None
        self._children: List[_Child] = []
        self._shutdown: Optional[int] = None
        self._stopping = False
        # generations whose ledger append failed (disk full / read-only
        # coord dir), queued for in-order retry: the last DURABLE
        # generation stays authoritative — a supervisor restart resumes
        # from ledger.latest(), never from progress that was only
        # acked in memory
        self._ledger_pending: List[Dict] = []
        # postmortem fail-fast state: per deterministic verdict class,
        # how many member deaths diagnosed as it. One gated retry is
        # allowed (the diagnosis could be wrong); a recurrence stops
        # the supervisor instead of burning --max-restarts on a
        # failure that reproduces every launch (docs/RESILIENCE.md
        # "Fail fast vs restart")
        self._det_seen: Dict[str, int] = {}
        self._pending_diag: Optional[Dict] = None
        # rejoin@G entries in the fault plan are the supervisor's to
        # honor (inert in the trainer): member rank rejoins at gen G
        self._rejoin_schedule: List[Tuple[int, Optional[int]]] = []
        if args.fault_plan:
            from .faults import FaultPlan

            self._rejoin_schedule = list(
                FaultPlan.parse(args.fault_plan).schedule("rejoin"))

    # -- helpers -----------------------------------------------------------

    def _metrics_logger(self):
        if self._metrics is None:
            from ..obs.metrics import MetricsLogger

            path = self.cfg.metrics_out or os.path.join(
                self.coord_dir, "membership.jsonl")
            self._metrics = MetricsLogger(path)
        return self._metrics

    def _clear_stale_heartbeats(self) -> None:
        # stale-heartbeat poisoning fix, half 2 (half 1 is the
        # generation-keyed filenames in coord.py): a relaunched fleet
        # must never see ghosts of the previous incarnation
        for p in glob.glob(os.path.join(self.coord_dir, "heartbeat-*")):
            try:
                os.unlink(p)
            except OSError:
                # genuinely-optional (storage-fault audit): heartbeat
                # filenames are generation-keyed, so a ghost that
                # refuses to unlink can never be mistaken for a live
                # peer of the NEXT generation anyway
                pass

    def _watchdog_horizon_s(self) -> float:
        wd = float(getattr(self.args, "watchdog_timeout", 0) or 0)
        # mirrors the hard-deadline factor in coord.py: survivors get
        # the full watchdog escalation path before the supervisor culls
        return (wd * 5 if wd > 0 else 120.0) + self.cfg.grace_extra_s

    def _child_argv(self, assignment: Assignment, node_rank: int,
                    member: int, generation: int, port: int,
                    resume: bool) -> List[str]:
        argv = list(self.train_argv)
        for flag in ("--node-rank", "--parts-per-node", "--port",
                     "--watchdog-dir"):
            argv = _strip_flag(argv, flag)
        metrics_base = _flag_value(argv, "--metrics-out")
        if metrics_base:
            argv = _strip_flag(argv, "--metrics-out")
            argv += ["--metrics-out",
                     _member_metrics_path(metrics_base, generation, member)]
        argv += ["--node-rank", str(node_rank),
                 "--parts-per-node", str(assignment.parts_per_node),
                 "--port", str(port),
                 "--watchdog-dir", self.coord_dir]
        if resume and "--resume" not in argv:
            argv.append("--resume")
        return argv

    def _launch_generation(self, generation: int,
                           assignment: Assignment) -> None:
        from ..utils.checkpoint import latest_checkpoint_path

        self._clear_stale_heartbeats()
        port = _free_port()
        resume = (latest_checkpoint_path(self.args.checkpoint_dir)
                  is not None)
        self._children = []
        for node_rank, member in enumerate(assignment.active_members()):
            argv = self._child_argv(assignment, node_rank, member,
                                    generation, port, resume)
            env = dict(os.environ)
            env[GENERATION_ENV] = str(generation)
            env[MEMBER_ENV] = str(member)
            _cpu_device_flags(env, assignment.parts_per_node)
            cmd = [sys.executable, "-m", "pipegcn_tpu.cli.main"] + argv
            log_path = os.path.join(
                self.coord_dir, f"rank-g{generation}-m{member}.log")
            handle = self.popen(cmd, env, log_path)
            self._children.append(_Child(member, node_rank, handle, log_path))
            self._log(f"gen {generation}: launched member {member} as "
                      f"node-rank {node_rank}/{assignment.n_nodes} "
                      f"(parts {list(assignment.parts_of_node(node_rank))}, "
                      f"port {port}, resume={resume})")

    def _signal_children(self, sig: int) -> None:
        for c in self._children:
            if c.poll() is None:
                try:
                    c.handle.send_signal(sig)
                except (OSError, ProcessLookupError):
                    pass

    def _watch_generation(self) -> Tuple[Optional[int], float]:
        """Block until every child of the current generation exits,
        classifying each. Returns (victim_member, death_time): the
        FIRST child to die abnormally (None when the generation ended
        without a death — all completed/resumable). Once a death is
        seen, survivors get the watchdog horizon to notice and exit 75
        themselves before being culled (SIGTERM -> SIGKILL) — a peer
        wedged in a dead collective would otherwise stall the
        relaunch forever."""
        victim: Optional[int] = None
        death_t = 0.0
        deadline: Optional[float] = None
        while True:
            alive = 0
            for c in self._children:
                rc = c.poll()
                if rc is None:
                    alive += 1
                    continue
                if c.outcome is None:
                    c.outcome = classify_exit(rc)
                    self._log(f"member {c.member} exited rc={rc} "
                              f"({c.outcome})")
                    if c.outcome == "dead" and victim is None:
                        victim = c.member
                        death_t = time.monotonic()
                        deadline = death_t + self._watchdog_horizon_s()
            if alive == 0:
                return victim, death_t
            if self._shutdown is not None and not self._stopping:
                # forward once, then keep waiting for the children's
                # own preemption checkpoints to land
                self._stopping = True
                self._signal_children(signal.SIGTERM)
            if deadline is not None and time.monotonic() > deadline:
                self._log("culling survivors stuck past the watchdog "
                          "horizon")
                self._signal_children(signal.SIGTERM)
                t0 = time.monotonic()
                while (any(c.poll() is None for c in self._children)
                       and time.monotonic() - t0 < 10):
                    time.sleep(self.cfg.poll_s)
                self._signal_children(signal.SIGKILL)
                for c in self._children:
                    if c.outcome is None and c.poll() is not None:
                        rc = c.handle.returncode
                        # a culled survivor was alive, just wedged: it
                        # stays a member (resumable), it is not the
                        # victim
                        c.outcome = ("resumable"
                                     if classify_exit(rc) != "dead"
                                     else "culled")
                deadline = None
                continue
            time.sleep(self.cfg.poll_s)

    def _next_members(self, members: List[int], victim: Optional[int],
                      generation: int) -> Tuple[List[int], str]:
        """Survivor set for the next generation plus its trigger tag.
        Exactly one victim per membership event (the first death); a
        total wipe-out keeps the full membership — a full-fleet
        restart beats training on nothing."""
        outcomes = {c.member: c.outcome for c in self._children}
        survivors = [m for m in members
                     if outcomes.get(m) not in ("dead",) and m != victim]
        if victim is not None and not survivors:
            self._log(f"every member died with member {victim}; retrying "
                      f"the full membership")
            return list(members), "restart-all"
        if victim is not None:
            trigger = "rank-death"
            members = survivors
        else:
            trigger = "preempt-resume"
        rejoining = set(self.ledger.pending_rejoins())
        due = [(g, m) for (g, m) in self._rejoin_schedule
               if g <= generation + 1]
        for g, m in due:
            self._rejoin_schedule.remove((g, m))
            if m is not None:
                rejoining.add(m)
        for m in sorted(rejoining):
            self.ledger.clear_rejoin(m)
        if rejoining:
            members = sorted(set(members) | rejoining)
            trigger = "rejoin" if victim is None else trigger
            self._log(f"rejoin: members {sorted(rejoining)} fold back in "
                      f"at generation {generation + 1}")
        members, stripped = self._strip_quarantined(members, rejoining)
        if stripped:
            trigger = "quarantine"
        return members, trigger

    def _strip_quarantined(self, members: List[int],
                           rejoining=frozenset()
                           ) -> Tuple[List[int], bool]:
        """Drop quarantined members (resilience/integrity.py markers —
        recurring silent data corruption on that rank) from the
        candidate set at every replan. A pending explicit rejoin
        request is the operator's release valve: it clears the marker
        and the member stays in. Quarantining EVERY member keeps the
        full set with a loud log — a fleet of zero trains nothing."""
        from .integrity import clear_quarantine, read_quarantines

        q = read_quarantines(self.coord_dir)
        if not q:
            return members, False
        for m in sorted(set(rejoining) & set(q)):
            clear_quarantine(self.coord_dir, m)
            q.pop(m, None)
            self._log(f"member {m} released from quarantine by "
                      f"explicit rejoin request")
        banned = [m for m in members if m in q]
        if not banned:
            return members, False
        keep = [m for m in members if m not in q]
        if not keep:
            self._log(f"every member ({banned}) is quarantined; "
                      f"keeping the full membership — an operator must "
                      f"clear the markers to make progress")
            return members, False
        reasons = ", ".join(
            f"m{m}: {q[m].get('reason', '?')}" for m in banned)
        self._log(f"quarantine: excluding members {banned} from the "
                  f"next generation ({reasons})")
        return keep, True

    def _flush_ledger_pending(self) -> bool:
        """Retry queued ledger appends in generation order, stopping at
        the first failure — appending a LATER generation while an
        earlier one is still pending would make the earlier one
        permanently unappendable (the ledger enforces monotonicity).
        True when the queue fully drained."""
        drained = 0
        while self._ledger_pending:
            kw = self._ledger_pending[0]
            try:
                self.ledger.append(**kw)
            except OSError as exc:
                self._log(f"ledger append for generation "
                          f"{kw['generation']} still failing ({exc}); "
                          f"{len(self._ledger_pending)} generations "
                          f"pending")
                return False
            self._ledger_pending.pop(0)
            drained += 1
        if drained:
            self._metrics_logger().recovery(
                IO_DEGRADED, -1, redrained=drained,
                component="membership-ledger")
            self._log(f"ledger recovered: {drained} pending "
                      f"generations appended")
        return True

    def _diagnose_death(self, generation: int,
                        victim: int) -> Optional[Dict]:
        """Run the postmortem rule engine over the coordination dir
        (black-box dumps, rank logs, the membership metrics stream all
        live there) after a member death. Returns the verdict dict, or
        None when diagnosis itself failed — forensics must never take
        the supervisor down."""
        try:
            from ..obs.postmortem import diagnose_run

            v = diagnose_run(self.coord_dir)
        except Exception as exc:  # noqa: BLE001
            self._log(f"postmortem for member {victim} failed: {exc!r}")
            return None
        self._log(f"postmortem for member {victim}: {v['verdict']} "
                  f"(confidence {v['confidence']:.2f}"
                  + (", deterministic" if v["deterministic"] else "")
                  + ")")
        try:
            self._metrics_logger().diagnosis(
                verdict=v["verdict"], confidence=v["confidence"],
                evidence=list(v["evidence"])[:6],
                remediation=v["remediation"],
                deterministic=v["deterministic"],
                generation=generation, victim=victim)
        except OSError:
            pass  # a degraded metrics sink must not block the verdict
        return v

    @staticmethod
    def _diag_slim(v: Dict) -> Dict:
        return {"verdict": v["verdict"],
                "confidence": v["confidence"],
                "deterministic": v["deterministic"]}

    def _record(self, generation: int, members: List[int],
                assignment: Assignment, trigger: str,
                latency: Optional[float],
                diagnosis: Optional[Dict] = None) -> None:
        kw = dict(generation=generation, members=list(members),
                  assignment=assignment, trigger=trigger,
                  restart_latency_s=latency,
                  diagnosis=(self._diag_slim(diagnosis)
                             if diagnosis else None))
        appended = False
        if self._flush_ledger_pending():
            try:
                self.ledger.append(**kw)
                appended = True
            except OSError as exc:
                self._log(f"LEDGER WRITE FAILED for generation "
                          f"{generation} ({exc}); the last durable "
                          f"generation {self.ledger.latest_generation()} "
                          f"stays authoritative — queuing for retry at "
                          f"the next membership event")
                self._metrics_logger().fault(
                    IO_DEGRADED, -1, reason=repr(exc),
                    generation=generation,
                    component="membership-ledger")
        if not appended:
            self._ledger_pending.append(kw)
        extra = ({"diagnosis": diagnosis["verdict"]}
                 if diagnosis else {})
        # surface the durable stream watermark with every membership
        # generation: the checkpoint's (__stream_seq__,
        # __topo_generation__) pair tells the reader exactly which
        # topology the relaunched fleet will replay to before training
        from ..utils.checkpoint import peek_watermark

        try:
            wm_seq, wm_gen = peek_watermark(self.args.checkpoint_dir)
        except Exception:  # noqa: BLE001 — observability must not kill
            wm_seq, wm_gen = -1, 0
        if wm_seq >= 0 or wm_gen > 0:
            extra["stream_seq"] = int(wm_seq)
            extra["topo_generation"] = int(wm_gen)
        self._metrics_logger().membership(
            generation=generation, assignment=assignment.as_json(),
            trigger=trigger, restart_latency_s=latency,
            n_members=len(members), **extra)

    # -- main loop ---------------------------------------------------------

    def run(self) -> int:
        def _on_term(signum, frame):  # noqa: ARG001
            self._shutdown = signum

        try:
            signal.signal(signal.SIGTERM, _on_term)
            signal.signal(signal.SIGINT, _on_term)
        except ValueError:
            pass  # not the main thread (unit tests)

        generation = self.ledger.latest_generation() + 1
        prev = self.ledger.latest()
        if prev is not None:
            members = list(prev["members"])
            trigger = "supervisor-resume"
            self._log(f"resuming ledger at generation {generation} "
                      f"with members {members}")
        else:
            n_nodes0 = math.ceil(
                self.n_parts / max(int(self.args.parts_per_node), 1))
            members = list(range(max(n_nodes0, 1)))
            trigger = "start"
        # quarantine markers survive a supervisor restart: excluded
        # members stay out until the operator clears them
        members, stripped = self._strip_quarantined(members)
        if stripped:
            trigger = "quarantine"
        latency: Optional[float] = None

        while True:
            assignment = plan_assignment(self.n_parts, members)
            self._record(generation, members, assignment, trigger, latency,
                         diagnosis=self._pending_diag)
            self._pending_diag = None
            t_launch = time.monotonic()
            self._launch_generation(generation, assignment)
            victim, death_t = self._watch_generation()
            ran_s = time.monotonic() - t_launch
            event_t = death_t if victim is not None else time.monotonic()
            outcomes = [c.outcome for c in self._children]
            if victim is None and all(o == "completed" for o in outcomes):
                self._log(f"generation {generation} completed; "
                          f"{self.policy.total} restarts total")
                return 0
            if self._stopping:
                self._log("supervisor shutdown requested; children "
                          "checkpointed — exiting resumable")
                return EXIT_PREEMPTED
            members, trigger = self._next_members(members, victim,
                                                  generation)
            if victim is not None:
                self._pending_diag = self._diagnose_death(generation,
                                                          victim)
            diag = self._pending_diag
            if diag is not None and diag.get("deterministic"):
                v = diag["verdict"]
                self._det_seen[v] = self._det_seen.get(v, 0) + 1
                if self._det_seen[v] >= 2:
                    # the gated retry died the same way: relaunching
                    # reproduces this — stop HARD (rc 1, not 75; a
                    # blind outer-scheduler resume would loop too)
                    self._log(
                        f"stopping: deterministic failure "
                        f"'{v}' recurred after its one gated retry — "
                        f"{diag['remediation']}")
                    try:
                        self.ledger.append(
                            generation=generation + 1,
                            members=list(members),
                            assignment=assignment,
                            trigger=f"deterministic:{v}",
                            diagnosis=self._diag_slim(diag))
                    except (OSError, ValueError) as exc:
                        self._log(f"final ledger append failed: {exc}")
                    self._metrics_logger().membership(
                        generation=generation + 1,
                        assignment=assignment.as_json(),
                        trigger=f"deterministic:{v}",
                        restart_latency_s=None,
                        n_members=len(members), diagnosis=v)
                    return 1
                self._log(f"postmortem verdict '{v}' is deterministic: "
                          f"allowing ONE gated retry, then failing "
                          f"fast")
            self.policy.note_stable(ran_s)
            decision = self.policy.decide()
            if decision.action == "stop":
                self._log(f"stopping: {decision.reason} after "
                          f"{self.policy.total - 1} restarts; last "
                          f"resumable checkpoint left in "
                          f"{self.args.checkpoint_dir}")
                self._metrics_logger().membership(
                    generation=generation, assignment=assignment.as_json(),
                    trigger=decision.reason, restart_latency_s=None,
                    n_members=len(members))
                return EXIT_PREEMPTED
            self._log(f"membership event ({trigger}); backing off "
                      f"{decision.delay_s:.1f}s before generation "
                      f"{generation + 1}")
            time.sleep(decision.delay_s)
            # death-detect -> next-generation-launch wall time: the
            # headline the acceptance criteria bound (watchdog horizon
            # + one backoff interval)
            latency = time.monotonic() - event_t
            generation += 1
