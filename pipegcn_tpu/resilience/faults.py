"""Deterministic fault injection for chaos-testing the recovery paths.

A fault plan is a comma-separated list of ``kind@epoch`` entries, e.g.
``--fault-plan nan-loss@5,sigterm@8,corrupt-ckpt@10``. Kinds:

  nan-loss      the harvested loss of that epoch reads NaN (what a
                diverged bf16 step reports) — exercises the sentinel's
                rollback/backoff/retry loop
  nan-grad      same, for the harvested grad norm
  sigterm       a shutdown request at that epoch boundary, exactly as
                if SIGTERM had been delivered — exercises the
                preemption checkpoint + resumable exit path
  crash         an uncaught exception at that epoch boundary —
                exercises the crash-checkpoint handler
  corrupt-ckpt  after the first checkpoint save at-or-after that
                epoch, the newest generation's bytes are scribbled —
                exercises digest verification + generation fallback

Every entry fires AT MOST ONCE (otherwise a recovered retry of the same
epoch would re-trip forever), and :meth:`skip_before` retires entries a
resumed run has already lived through, so the same ``--fault-plan`` can
be passed verbatim to the resume invocation. Epoch semantics: boundary
kinds (sigterm/crash) fire at the START of epoch E, so the resumable
checkpoint they produce says E completed and ``skip_before(E)`` retires
them; injection kinds poison epoch E itself and survive a resume that
starts at E (the epoch is re-run).

Injection is host-side only — device programs are never altered, so a
fault-injected run compiles byte-identical XLA to a production run.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import List, Optional

KINDS = ("nan-loss", "nan-grad", "sigterm", "crash", "corrupt-ckpt")
# kinds that fire at the start of an epoch boundary: a resume whose
# start_epoch equals the scheduled epoch has already seen them fire
_BOUNDARY_KINDS = ("sigterm", "crash")

_ENTRY_RE = re.compile(r"^([a-z-]+)@(\d+)$")


@dataclasses.dataclass
class _Entry:
    kind: str
    epoch: int
    consumed: bool = False


class FaultPlan:
    """Parsed, single-shot fault schedule."""

    def __init__(self, entries: List[_Entry]):
        self._entries = sorted(entries, key=lambda e: e.epoch)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse ``kind@epoch[,kind@epoch...]``; raises ValueError with
        the grammar on any malformed entry or unknown kind."""
        entries = []
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            m = _ENTRY_RE.match(raw)
            if not m:
                raise ValueError(
                    f"bad fault-plan entry {raw!r}: expected kind@epoch "
                    f"(e.g. nan-loss@5,sigterm@8,corrupt-ckpt@10)")
            kind, epoch = m.group(1), int(m.group(2))
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: "
                    f"{', '.join(KINDS)}")
            entries.append(_Entry(kind, epoch))
        return cls(entries)

    def __len__(self) -> int:
        return len(self._entries)

    def __bool__(self) -> bool:
        return bool(self._entries)

    def remaining(self) -> List[str]:
        return [f"{e.kind}@{e.epoch}" for e in self._entries
                if not e.consumed]

    def skip_before(self, start_epoch: int) -> None:
        """Retire entries a resume starting at `start_epoch` has already
        lived through (see module docstring for the boundary-kind
        off-by-one)."""
        for e in self._entries:
            if e.epoch < start_epoch or (
                    e.kind in _BOUNDARY_KINDS and e.epoch <= start_epoch
                    and start_epoch > 0):
                e.consumed = True

    def due(self, kind: str, epoch: int) -> bool:
        """True (and consumes the entry) when a `kind` fault is
        scheduled at-or-before `epoch`. The <= comparison keeps faults
        from being silently skipped when the loop only visits block
        boundaries (fused_epochs > 1)."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch <= epoch:
                e.consumed = True
                return True
        return False

    def due_in(self, kind: str, lo: int, hi: int) -> Optional[int]:
        """Epoch (clamped into [lo, hi)) of a `kind` fault scheduled
        before `hi`, consuming it; None otherwise. For injection into a
        fused block's harvested [k]-metrics."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch < hi:
                e.consumed = True
                return min(max(e.epoch, lo), hi - 1)
        return None


def corrupt_latest_checkpoint(directory: str) -> str:
    """Scribble over the middle of the newest checkpoint generation
    (the file the `latest` pointer names), returning its path. The
    damage lands inside the zip payload, so digest verification — not
    just the zip CRC — is what the loader must survive by."""
    from ..utils.checkpoint import latest_checkpoint_path

    path = latest_checkpoint_path(directory)
    if path is None:
        raise FileNotFoundError(f"no checkpoint generation in {directory}")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(max(0, size // 2 - 32))
        f.write(b"\xde\xad\xbe\xef" * 16)
    return path
