"""Deterministic fault injection for chaos-testing the recovery paths.

A fault plan is a comma-separated list of ``kind@epoch[:rN]`` entries,
e.g. ``--fault-plan nan-loss@5:r1,sigterm@8,corrupt-ckpt@10``. Kinds:

  nan-loss      the harvested loss of that epoch reads NaN (what a
                diverged bf16 step reports) — exercises the sentinel's
                rollback/backoff/retry loop
  nan-grad      same, for the harvested grad norm
  sigterm       a shutdown request at that epoch boundary, exactly as
                if SIGTERM had been delivered — exercises the
                preemption checkpoint + resumable exit path
  crash         an uncaught exception at that epoch boundary —
                exercises the crash-checkpoint handler
  corrupt-ckpt  after the first checkpoint save at-or-after that
                epoch, the newest generation's bytes are scribbled —
                exercises digest verification + generation fallback
  desync        this rank's replicated params are silently perturbed at
                that epoch boundary — exercises the cross-rank desync
                detector (docs/RESILIENCE.md multi-host section)
  hang          the rank freezes at that epoch boundary (heartbeats
                stop too, like a truly wedged process) — exercises the
                PEERS' heartbeat watchdog / PeerLost path.
                ``hang@E[:rN]:<ms>`` instead stalls the rank for <ms>
                milliseconds and RESUMES (heartbeats keep flowing): a
                sub-watchdog stall that exercises the flight
                recorder's stall detector (obs/flight.py) without
                tripping PeerLost
  overflow      that epoch's harvested loss-scale overflow flag reads 1
                (what a saturated-activation backward reports) —
                exercises the loss-scale backoff / step-skip accounting
                / regrowth state machine (needs --loss-scale; inert
                when scaling is off, like every injection host-side)
  kernel-crash  the dispatch at the start of that epoch raises a
                simulated TPU-backend error — exercises the kernel
                fallback ladder (block -> bucket -> sorted-XLA) and its
                contracted `fallback` record
  kill          hard SIGKILL(self) at that epoch boundary — no
                handlers, no atexit, no checkpoint: the process
                vanishes like an OOM-killed or preempted-VM rank, so
                the PEERS' watchdog (not the graceful SIGTERM path)
                and the elastic supervisor's redistribution
                (resilience/elastic.py) must do ALL the recovery.
                ``kill@E:rN`` targets the generation's node rank N —
                node ranks are re-dealt per membership generation
  rejoin        ``rejoin@G``: the targeted member re-registers at
                membership generation G. Inert inside the trainer —
                the elastic SUPERVISOR reads it (via :meth:`schedule`)
                and folds the member back into generation G's
                assignment, rebalancing shards
  graph-delta   ``graph-delta@E[:rN]``: apply an unscheduled synthetic
                graph delta batch (stream/patch.py) to the live
                training graph at that epoch boundary — edges appear
                and vanish, a node arrives — exercising the incremental
                patch, the carry flush, and the forced drift probe
  journal-torn  ``journal-torn@E``: truncate the delta journal's newest
                segment to half its bytes at that boundary (an
                interrupted append / disk corruption); the next resume
                must tolerate the torn tail, replay the surviving
                prefix, and re-derive the lost records from the stream
                plan (stream/journal.py). Skipped when no journal is
                attached
                mid-run without a prepared delta file. Requires
                streaming to be enabled (warn + skip otherwise)
  replica-kill  ``replica-kill@W[:mK]``: SIGKILL serving replica K at
                serving report window W (default replica 0). Inert in
                the trainer — the serving FLEET driver reads it (via
                :meth:`due_member`) and hard-kills the replica process
                so the router's failover path is drillable from the
                standard chaos harness. Boundary-retired like kill@E.
  enospc        ``enospc@E[:rN]``: from the start of epoch E until the
                next checkpoint boundary, every durable write/fsync on
                this rank raises ENOSPC (resilience/storage.py shim) —
                exercises the checkpoint retry-next-boundary policy,
                the metrics ring buffer, and the ledger pending queue
  torn-write    ``torn-write@E``: durable writes over the same window
                are truncated mid-flight and fail with EIO before
                their rename — exercises the temp+rename guarantee
                that a torn artifact is indistinguishable from absent
  ro-dir        ``ro-dir@E``: opens-for-write raise EROFS over the
                window — the artifact directory went read-only
  slow-rank     ``slow-rank@E[:rN]:<ms>``: a host-side sleep of <ms>
                milliseconds at rank rN's dispatch boundary — a
                deterministic straggler (one rank arrives late at the
                epoch's collectives while the others wait inside the
                compiled step). Exercises the training-span straggler
                attribution + the straggler-skew alert rule
                (obs/trainspan.py, docs/OBSERVABILITY.md "Training
                traces"); available to scripts/soak.py episodes
  slow-fs       ``slow-fs@E:<ms>``: every durable-write seam op sleeps
                <ms> milliseconds over the window — a degraded shared
                filesystem; nothing fails, progress just crawls
  net-delay     ``net-delay@W[:mK]:<ms>``: from serving report window W
                every RPC the driver sends replica K (default 0) is
                delayed <ms> milliseconds at the TcpReplicaClient seam
                for one report window — a slow peer the router must
                absorb via its retry budget, not mark dead. Inert in
                the trainer; the fleet driver reads it via
                :meth:`due_member_arg`
  net-drop      ``net-drop@W[:mK]``: the NEXT RPC to replica K raises a
                connection error (one-shot) — a dropped packet/reset
                the router's retry-with-backoff path must ride out
  net-partition ``net-partition@W:<s>``: replica K (default 0) becomes
                unreachable — every RPC errors — for <s> SECONDS, then
                heals; the process stays alive and heartbeating the
                whole time. Exercises router mark-down + the fleet
                poll's health-probe reconciliation that routes the
                healthy-again peer back in (no relaunch involved)
  bitflip       ``bitflip@E[:rN]:<params|carry|tables|halo>``: one real
                bit is flipped in the named target class at that epoch
                boundary — replicated params, the pipelined non-halo
                carry, a static device kernel table, or a stored halo
                feature block — exercising the integrity plane's
                detect/attribute/recover path (resilience/integrity.py,
                docs/RESILIENCE.md "Silent data corruption"). The
                class argument is REQUIRED

The optional ``:rN`` qualifier targets one rank (``jax.process_index``)
so multi-process chaos drills can kill, desynchronize, or hang a single
rank: ``nan-loss@5:r1`` trips ONLY rank 1's sentinel and the fault
consensus must propagate the rollback to the rest of the pod.
Unqualified entries fire on every rank (lockstep, the single-process
behavior). Entries qualified for another rank are inert on this one.

Every entry fires AT MOST ONCE (otherwise a recovered retry of the same
epoch would re-trip forever), and :meth:`skip_before` retires entries a
resumed run has already lived through, so the same ``--fault-plan`` can
be passed verbatim to the resume invocation. Epoch semantics: boundary
kinds (sigterm/crash/desync/hang) fire at the START of epoch E, so the
resumable checkpoint they produce says E completed and
``skip_before(E)`` retires them; injection kinds poison epoch E itself
and survive a resume that starts at E (the epoch is re-run).

Injection is host-side only — device programs are never altered, so a
fault-injected run compiles byte-identical XLA to a production run.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import List, Optional

from .storage import IO_KINDS

KINDS = ("nan-loss", "nan-grad", "sigterm", "crash", "corrupt-ckpt",
         "desync", "hang", "slow-rank", "overflow", "kernel-crash",
         "kill", "rejoin", "replica-kill", "graph-delta",
         "journal-torn", "net-delay", "net-drop", "net-partition",
         "bitflip") + IO_KINDS
# kinds that fire at the start of an epoch boundary: a resume whose
# start_epoch equals the scheduled epoch has already seen them fire.
# IO kinds arm at the boundary and disarm by the next checkpoint
# boundary, so a resume past the arming epoch has outlived them too.
_BOUNDARY_KINDS = ("sigterm", "crash", "desync", "hang", "slow-rank",
                   "kernel-crash", "kill", "replica-kill",
                   "graph-delta", "journal-torn", "net-delay",
                   "net-drop", "net-partition", "bitflip") + IO_KINDS

# the optional third group is 'r<N>' (rank), 'm<K>' (member), or a bare
# number — the per-kind argument (slow-fs / hang: milliseconds). A
# rank/member qualifier may additionally be FOLLOWED by a bare arg
# (``hang@6:r1:250``) or a word argument (``bitflip@6:r0:tables``),
# the fourth group.
_ENTRY_RE = re.compile(
    r"^([a-z-]+)@(\d+)(?::([rm]?)(\d+))?(?::([a-z0-9]+))?$")

# kinds whose entries may carry a bare numeric argument
# (slow-fs / hang / slow-rank / net-delay: milliseconds;
# net-partition: seconds)
_ARG_KINDS = ("slow-fs", "hang", "slow-rank", "net-delay",
              "net-partition")

# kinds whose entries carry a REQUIRED word argument (the SDC target
# class); the legal classes live next to the detectors
_STR_ARG_KINDS = ("bitflip",)
_BITFLIP_CLASSES = ("params", "carry", "tables", "halo")


@dataclasses.dataclass
class _Entry:
    kind: str
    epoch: int
    rank: Optional[int] = None    # None = every rank (``:rN``)
    member: Optional[int] = None  # serving replica target (``:mK``)
    arg: Optional[int] = None     # per-kind argument (slow-fs ms)
    sarg: Optional[str] = None    # per-kind word argument (bitflip class)
    consumed: bool = False


class FaultPlan:
    """Parsed, single-shot fault schedule (for one rank's process)."""

    def __init__(self, entries: List[_Entry], rank: int = 0):
        self._entries = sorted(entries, key=lambda e: e.epoch)
        self._rank = int(rank)

    @classmethod
    def parse(cls, spec: str, rank: int = 0) -> "FaultPlan":
        """Parse ``kind@epoch[:rN][,kind@epoch[:rN]...]``; raises
        ValueError with the grammar on any malformed entry or unknown
        kind. ``rank`` is THIS process's rank — entries qualified for
        another rank parse but never fire here."""
        entries = []
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            m = _ENTRY_RE.match(raw)
            if not m:
                raise ValueError(
                    f"bad fault-plan entry {raw!r}: expected "
                    f"kind@epoch[:rN] or kind@window[:mK] (e.g. "
                    f"nan-loss@5:r1,sigterm@8,replica-kill@2:m1)")
            kind, epoch = m.group(1), int(m.group(2))
            erank = emember = earg = esarg = None
            if m.group(3) == "r":
                erank = int(m.group(4))
            elif m.group(3) == "m":
                emember = int(m.group(4))
            elif m.group(3) == "" and m.group(4) is not None:
                earg = int(m.group(4))
            if m.group(5) is not None:
                if earg is not None:
                    raise ValueError(
                        f"bad fault-plan entry {raw!r}: at most one "
                        f"bare numeric argument (kind@E[:rN]:<N>)")
                if m.group(5).isdigit():
                    earg = int(m.group(5))
                else:
                    esarg = m.group(5)
            if earg is not None and kind not in _ARG_KINDS:
                raise ValueError(
                    f"bad fault-plan entry {raw!r}: a bare numeric "
                    f"argument (kind@E[:rN]:<N>) is only valid for "
                    f"{' / '.join(_ARG_KINDS)} (milliseconds)")
            if esarg is not None and kind not in _STR_ARG_KINDS:
                raise ValueError(
                    f"bad fault-plan entry {raw!r}: expected "
                    f"kind@epoch[:rN] — a word argument "
                    f"(kind@E[:rN]:<word>) is only valid for "
                    f"{' / '.join(_STR_ARG_KINDS)}")
            if kind in _STR_ARG_KINDS:
                if esarg not in _BITFLIP_CLASSES:
                    raise ValueError(
                        f"bad fault-plan entry {raw!r}: {kind} needs a "
                        f"target class, one of "
                        f"{' / '.join(_BITFLIP_CLASSES)} "
                        f"(e.g. bitflip@6:r0:tables)")
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: "
                    f"{', '.join(KINDS)}")
            entries.append(_Entry(kind, epoch, erank, emember, earg,
                                  esarg))
        return cls(entries, rank=rank)

    def _mine(self, e: _Entry) -> bool:
        return e.rank is None or e.rank == self._rank

    def __len__(self) -> int:
        return len(self._entries)

    def __bool__(self) -> bool:
        return bool(self._entries)

    def remaining(self) -> List[str]:
        return [f"{e.kind}@{e.epoch}"
                + (f":r{e.rank}" if e.rank is not None else "")
                + (f":m{e.member}" if e.member is not None else "")
                + (f":{e.arg}" if e.arg is not None else "")
                + (f":{e.sarg}" if e.sarg is not None else "")
                for e in self._entries if not e.consumed]

    def skip_before(self, start_epoch: int) -> None:
        """Retire entries a resume starting at `start_epoch` has already
        lived through (see module docstring for the boundary-kind
        off-by-one)."""
        for e in self._entries:
            if e.epoch < start_epoch or (
                    e.kind in _BOUNDARY_KINDS and e.epoch <= start_epoch
                    and start_epoch > 0):
                e.consumed = True

    def due(self, kind: str, epoch: int) -> bool:
        """True (and consumes the entry) when a `kind` fault targeting
        this rank is scheduled at-or-before `epoch`. The <= comparison
        keeps faults from being silently skipped when the loop only
        visits block boundaries (fused_epochs > 1)."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch <= epoch \
                    and self._mine(e):
                e.consumed = True
                return True
        return False

    def peek(self, kind: str, epoch: int) -> bool:
        """Non-consuming `due`: would a `kind` fault targeting this
        rank fire at-or-before `epoch`? Lets the trainer settle
        in-flight work (e.g. harvest a pending async eval) before the
        consuming `due` call actually mutates anything."""
        return any(not e.consumed and e.kind == kind
                   and e.epoch <= epoch and self._mine(e)
                   for e in self._entries)

    def schedule(self, kind: str) -> List[tuple]:
        """Non-consuming (epoch-or-generation, rank) view of every
        unconsumed entry of `kind`, REGARDLESS of rank targeting — the
        elastic supervisor reads the ``rejoin`` schedule for ALL
        members, not just the rank this plan was parsed for."""
        return [(e.epoch, e.rank) for e in self._entries
                if e.kind == kind and not e.consumed]

    def due_member(self, kind: str, window: int) -> Optional[int]:
        """Member (serving replica) id of a `kind` fault scheduled
        at-or-before `window`, consuming the entry; None when nothing
        is due. An entry without an ``:mK`` qualifier targets member 0
        — the fleet driver calls this at serving-window boundaries
        (``replica-kill@W:mK``)."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch <= window:
                e.consumed = True
                return e.member if e.member is not None else 0
        return None

    def due_member_arg(self, kind: str, window: int):
        """Like :meth:`due_member`, but returns ``(member, arg)`` —
        both defaulting to 0 — for the net-fault kinds that target a
        replica AND carry a numeric argument (``net-delay@W[:mK]:<ms>``,
        ``net-partition@W:<s>``). Consuming; None when nothing is
        due."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch <= window:
                e.consumed = True
                return (e.member if e.member is not None else 0,
                        e.arg if e.arg is not None else 0)
        return None

    def due_arg(self, kind: str, epoch: int) -> Optional[int]:
        """Like :meth:`due`, but returns the entry's per-kind argument
        (0 when none was given) instead of True — for kinds that carry
        one (``slow-fs@E:<ms>``, ``hang@E[:rN]:<ms>``). None when
        nothing is due."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch <= epoch \
                    and self._mine(e):
                e.consumed = True
                return e.arg if e.arg is not None else 0
        return None

    def due_str_arg(self, kind: str, epoch: int) -> Optional[str]:
        """Like :meth:`due`, but returns the entry's word argument —
        for kinds that carry one (``bitflip@E[:rN]:<class>``). None
        when nothing is due."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch <= epoch \
                    and self._mine(e):
                e.consumed = True
                return e.sarg
        return None

    def due_in(self, kind: str, lo: int, hi: int) -> Optional[int]:
        """Epoch (clamped into [lo, hi)) of a `kind` fault targeting
        this rank scheduled before `hi`, consuming it; None otherwise.
        For injection into a fused block's harvested [k]-metrics."""
        for e in self._entries:
            if not e.consumed and e.kind == kind and e.epoch < hi \
                    and self._mine(e):
                e.consumed = True
                return min(max(e.epoch, lo), hi - 1)
        return None


def corrupt_latest_checkpoint(directory: str) -> str:
    """Scribble over the middle of the newest checkpoint generation
    (the file the `latest` pointer names), returning its path. The
    damage lands inside the zip payload, so digest verification — not
    just the zip CRC — is what the loader must survive by."""
    from ..utils.checkpoint import latest_checkpoint_path

    path = latest_checkpoint_path(directory)
    if path is None:
        raise FileNotFoundError(f"no checkpoint generation in {directory}")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(max(0, size // 2 - 32))
        f.write(b"\xde\xad\xbe\xef" * 16)
    return path
