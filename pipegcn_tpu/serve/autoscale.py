"""Closed-loop autoscaling policy for the serving fleet.

This is the piece that turns the PR-15 telemetry plane from a read-only
dashboard into a control loop (docs/SERVING.md "Autoscaling &
overload"): the fleet driver feeds each report window's telemetry —
queue depth, shed rate, p99, staleness, and any AlertEngine fire edges
— into :class:`AutoscalePolicy`, which answers with a
:class:`ScaleDecision`. ``scale-up`` / ``scale-down`` decisions are
executed by ``FleetManager.spawn_replica`` / ``retire_replica`` (serve/
fleet.py) and remap the router's consistent-hash ring; every non-hold
decision lands as a contracted schema-v12 ``autoscale`` record carrying
the triggering evidence, so the soak harness can replay the replica-
count trajectory from the ledger alone.

Anti-flap brakes mirror the PR-11 ``RestartPolicy`` shape (cooldowns +
a sliding-window storm breaker) rather than reusing the class: the
restart policy answers "should this DEAD thing come back", while the
scale policy answers "should a HEALTHY fleet change size" — but the
refusal reasons (``cooldown`` / ``storm-brake``) are deliberately the
same vocabulary so operators read one brake language across both.

Everything is host-side, dependency-free, and takes an injectable
clock, so the whole policy is drivable by fake-clock unit tests
(tests/test_autoscale.py).

The module also hosts :class:`NetFaultInjector`, the network-fault
chaos seam: armed from fault-plan entries (``net-delay`` / ``net-drop``
/ ``net-partition``, resilience/faults.py), its :meth:`~
NetFaultInjector.gate` is installed as ``TcpReplicaClient.fault_gate``
and consulted before every RPC — delaying, dropping, or erroring the
call without touching the replica process, so the router's retry/
timeout/backoff path is exercised against slow and partitioned peers,
not just dead ones.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One autoscaler answer. action: ``scale-up`` | ``scale-down`` |
    ``refuse`` | ``hold``; target is the proposed fleet size (equal to
    the current size on refuse/hold); evidence is the telemetry
    snapshot that justified it (logged verbatim into the `autoscale`
    record)."""

    action: str
    target: int
    reason: str = ""
    evidence: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def wants_scale(self) -> bool:
        return self.action in ("scale-up", "scale-down")


# alert rules whose FIRE edge is overload evidence the policy treats as
# an immediate scale-up trigger (no sustain wait — the AlertEngine's
# own hysteresis already debounced it)
_SCALE_UP_RULES = ("shed-rate", "staleness-age")


class AutoscalePolicy:
    """Threshold-with-hysteresis scale policy under anti-flap brakes.

    Scale-up triggers (any, evaluated per report window):
      - queue pressure: queue_depth > ``queue_high`` for
        ``sustain_ticks`` consecutive windows (one hot window is a
        blip; a sustained queue is demand outrunning capacity)
      - shed rate: shed_rate > ``shed_high`` (already dropping work —
        no sustain wait)
      - p99 SLO: p99_ms > ``p99_slo_ms`` for ``sustain_ticks`` windows
      - alert edge: a fire edge from one of the overload rules
        (shed-rate / staleness-age) arrives from the AlertEngine

    Scale-down trigger: ``idle_ticks`` consecutive windows with
    queue_depth < ``queue_low`` AND zero shed — capacity is provably
    idle, retire one replica.

    Brakes (checked AFTER a trigger, so refusals carry the trigger's
    evidence): ``cooldown_s`` since the last executed scale action, and
    a storm breaker refusing when >= ``storm_threshold`` scale actions
    landed inside ``storm_window_s``. Bounds clamp to
    [min_replicas, max_replicas] with reasons ``min-replicas`` /
    ``max-replicas``.

    One step per call: decisions move the fleet by ONE replica — the
    loop re-evaluates next window, so convergence is rate-limited by
    design (the cooldown IS the ramp rate)."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 queue_high: int = 64, queue_low: int = 8,
                 shed_high: float = 0.01,
                 p99_slo_ms: Optional[float] = None,
                 sustain_ticks: int = 2, idle_ticks: int = 4,
                 cooldown_s: float = 10.0,
                 storm_window_s: float = 60.0, storm_threshold: int = 4,
                 clock: Callable[[], float] = time.monotonic):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"bad replica bounds [{min_replicas}, {max_replicas}]")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        self.shed_high = float(shed_high)
        self.p99_slo_ms = None if p99_slo_ms is None else float(p99_slo_ms)
        self.sustain_ticks = max(1, int(sustain_ticks))
        self.idle_ticks = max(1, int(idle_ticks))
        self.cooldown_s = float(cooldown_s)
        self.storm_window_s = float(storm_window_s)
        self.storm_threshold = int(storm_threshold)
        self._clock = clock
        # trigger hysteresis state
        self._hot_ticks = 0    # consecutive queue-pressure windows
        self._slo_ticks = 0    # consecutive p99-over-SLO windows
        self._idle_ticks = 0   # consecutive provably-idle windows
        # brake state
        self._last_scale_t: Optional[float] = None
        self._recent_scales: list = []  # timestamps, storm window
        # observability
        self.n_up = 0
        self.n_down = 0
        self.n_refused = 0

    # ---------------- policy ------------------------------------------

    def _brake(self, now: float) -> Optional[str]:
        """Refusal reason when the anti-flap brakes veto a scale."""
        if self._last_scale_t is not None \
                and now - self._last_scale_t < self.cooldown_s:
            return "cooldown"
        self._recent_scales = [t for t in self._recent_scales
                               if now - t >= 0 and now - t
                               < self.storm_window_s]
        if len(self._recent_scales) >= self.storm_threshold:
            return "storm-brake"
        return None

    def _note_scaled(self, now: float) -> None:
        self._last_scale_t = now
        self._recent_scales.append(now)
        self._hot_ticks = self._slo_ticks = self._idle_ticks = 0

    def observe(self, window: int, queue_depth: int, shed_rate: float,
                p99_ms: Optional[float], n_replicas: int,
                alerts: Sequence[str] = ()) -> ScaleDecision:
        """Fold one report window's telemetry; returns the decision.
        `alerts` is the list of rule names whose FIRE edge landed this
        window (AlertEngine.evaluate output). `shed_rate` is shed rows
        / submitted rows over the window (0 when nothing arrived)."""
        now = self._clock()
        n = int(n_replicas)
        ev: Dict[str, Any] = {
            "window": int(window),
            "queue_depth": int(queue_depth),
            "shed_rate": float(shed_rate),
            "p99_ms": None if p99_ms is None else float(p99_ms),
            "alerts": list(alerts),
        }

        # --- trigger detection -----------------------------------------
        self._hot_ticks = (self._hot_ticks + 1
                           if queue_depth > self.queue_high else 0)
        over_slo = (self.p99_slo_ms is not None and p99_ms is not None
                    and p99_ms > self.p99_slo_ms)
        self._slo_ticks = self._slo_ticks + 1 if over_slo else 0
        idle = queue_depth < self.queue_low and shed_rate <= 0.0
        self._idle_ticks = self._idle_ticks + 1 if idle else 0

        up_reason = None
        if shed_rate > self.shed_high:
            up_reason = "shed-rate"
        elif self._hot_ticks >= self.sustain_ticks:
            up_reason = "queue-pressure"
        elif self._slo_ticks >= self.sustain_ticks:
            up_reason = "p99-slo"
        else:
            fired = [a for a in alerts if a in _SCALE_UP_RULES]
            if fired:
                up_reason = f"alert:{fired[0]}"
        ev["sustain_ticks"] = int(self._hot_ticks)
        ev["idle_ticks"] = int(self._idle_ticks)

        # --- up path ---------------------------------------------------
        if up_reason is not None:
            if n >= self.max_replicas:
                self.n_refused += 1
                return ScaleDecision("refuse", n, "max-replicas",
                                     {**ev, "trigger": up_reason})
            brake = self._brake(now)
            if brake is not None:
                self.n_refused += 1
                return ScaleDecision("refuse", n, brake,
                                     {**ev, "trigger": up_reason})
            self._note_scaled(now)
            self.n_up += 1
            return ScaleDecision("scale-up", n + 1, up_reason, ev)

        # --- down path -------------------------------------------------
        if self._idle_ticks >= self.idle_ticks:
            if n <= self.min_replicas:
                # floor is normal operation, not a refusal worth a
                # ledger record every idle window — hold silently
                return ScaleDecision("hold", n, "min-replicas", ev)
            brake = self._brake(now)
            if brake is not None:
                self.n_refused += 1
                return ScaleDecision("refuse", n, brake,
                                     {**ev, "trigger": "idle"})
            self._note_scaled(now)
            self.n_down += 1
            return ScaleDecision("scale-down", n - 1, "idle", ev)

        return ScaleDecision("hold", n, "steady", ev)


class NetFaultInjector:
    """Deterministic network-fault chaos at the RPC seam.

    ``gate(rid, op)`` is installed as ``TcpReplicaClient.fault_gate``
    (serve/fleet.py) and runs at the top of every ``_rpc``:

      - :meth:`delay`: every RPC to the replica sleeps ``ms`` until the
        arming expires (``until`` on the injected clock) — a slow peer
      - :meth:`drop`: the next ``n`` RPCs raise — packet loss / resets
      - :meth:`partition`: every RPC raises until the arming expires —
        an unreachable-but-alive peer that later heals

    Raises plain ``ConnectionError`` (TcpReplicaClient wraps transport
    errors into ReplicaError for the router) so the injector stays
    import-safe from tests that never touch the fleet. Thread-safe:
    worker threads gate concurrently. ``clock`` / ``sleep`` are
    injectable for fake-clock tests."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._delay: Dict[int, Tuple[float, float]] = {}  # rid: (ms, until)
        self._drop: Dict[int, int] = {}                   # rid: n left
        self._partition: Dict[int, float] = {}            # rid: until
        self.n_gated = 0

    # ---------------- arming ------------------------------------------

    def delay(self, rid: int, ms: float, duration_s: float) -> None:
        with self._lock:
            self._delay[int(rid)] = (float(ms),
                                     self._clock() + float(duration_s))

    def drop(self, rid: int, n: int = 1) -> None:
        with self._lock:
            self._drop[int(rid)] = self._drop.get(int(rid), 0) + int(n)

    def partition(self, rid: int, duration_s: float) -> None:
        with self._lock:
            self._partition[int(rid)] = self._clock() + float(duration_s)

    def partitioned(self, rid: int) -> bool:
        """Non-consuming: is the replica inside a live partition
        window? (The fleet poll's health-probe reconciliation asks
        before trusting an in-process health RPC.)"""
        with self._lock:
            until = self._partition.get(int(rid))
            return until is not None and self._clock() < until

    # ---------------- the seam ----------------------------------------

    def gate(self, rid: int, op: str) -> None:
        """Called before every RPC to replica `rid`; sleeps or raises
        per the armed faults. Expired arms are pruned lazily."""
        rid = int(rid)
        now = self._clock()
        delay_ms = None
        with self._lock:
            until = self._partition.get(rid)
            if until is not None:
                if now < until:
                    self.n_gated += 1
                    raise ConnectionError(
                        f"injected net-partition: replica {rid} "
                        f"unreachable ({op})")
                del self._partition[rid]
            n = self._drop.get(rid, 0)
            if n > 0:
                self._drop[rid] = n - 1
                self.n_gated += 1
                raise ConnectionError(
                    f"injected net-drop: replica {rid} ({op})")
            arm = self._delay.get(rid)
            if arm is not None:
                ms, d_until = arm
                if now < d_until:
                    delay_ms = ms
                else:
                    del self._delay[rid]
        if delay_ms is not None:
            self.n_gated += 1
            self._sleep(delay_ms / 1000.0)
