"""Replicated multi-mesh serving fleet (docs/SERVING.md "Fleet").

N serving replicas, each a FULL sharded-graph mesh in its own OS
process (the same virtual-device trick the trainer uses makes a
CPU-mesh replica real enough to SIGKILL in tests), fronted by the
jax-free :class:`~.router.Router`. The pieces, by where they run:

  replica process (cli/fleet.py --replica-id K)
    ReplicaServer — wraps a ServingEngine behind a tiny
    length-prefixed-JSON TCP protocol (query/health/stop), binds port
    0 and publishes the real port through an atomic readiness file,
    beats a generation-keyed heartbeat file (the PR-11 machinery:
    HeartbeatWatchdog with n_ranks=1, generation=incarnation — a
    relaunched replica's beats can never be mistaken for its previous
    life's), and runs the zero-downtime checkpoint watcher: poll for a
    new CRC-verified generation, `load_from_checkpoint` under the
    engine lock (queries drain / briefly block — the measured
    `param_swap_ms` blip), never retracing (same shapes, same
    compiled programs).

  driver process (cli/fleet.py / bench.py --serve --replicas N)
    FleetManager — launches and supervises the replica subprocesses
    (RestartPolicy's backoff/cap/storm brakes, reused from the elastic
    supervisor), detects death by subprocess exit AND heartbeat
    staleness, relaunches with a bumped incarnation, and folds the
    rejoined replica back into the router.
    run_fleet_loop — the open-loop load loop: a driver-side
    MicroBatcher accumulates tickets (bounded queue + deadline load
    shedding), worker threads dispatch taken batches through the
    router (so N replicas serve concurrently — aggregate QPS scales
    near-linearly), failed batches retry against survivors, and a
    batch the whole fleet cannot answer is shed EXPLICITLY — the
    conservation invariant submitted == served + shed + queued holds
    at every instant, so "zero accepted tickets lost" is checkable
    from outside.

Transport is stdlib-only: '>I' length prefix + JSON, logits as base64
float32. One persistent connection per replica, one in-flight request
per connection (guarded by the client's lock).
"""

from __future__ import annotations

import base64
import json
import os
import signal
import socket
import socketserver
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .batcher import MicroBatcher, ServingStats
from .loadgen import OpenLoopGenerator
from .router import FleetUnavailable, Router
from .tracing import SpanWriter, TraceSampler

# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

_LEN = struct.Struct(">I")
_MAX_MSG = 64 << 20  # 64 MiB: a torn/hostile length prefix must not OOM us


def _send_msg(sock: socket.socket, obj: dict) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> dict:
    """Read one length-prefixed JSON message. Every way a torn,
    corrupt, or hostile byte stream can present — an absurd length
    (a desynced/garbage prefix decodes as a huge uint32), a zero
    length, payload that is not valid JSON, or JSON that is not an
    object — raises ConnectionError, which the per-connection handler
    treats as a clean close of THAT connection; the server and its
    other connections are unaffected."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n == 0 or n > _MAX_MSG:
        raise ConnectionError(
            f"message length {n} outside (0, {_MAX_MSG}]: "
            f"torn or hostile prefix")
    payload = _recv_exact(sock, n)
    try:
        msg = json.loads(payload)
    except (json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise ConnectionError(f"malformed message payload: {exc}") \
            from exc
    if not isinstance(msg, dict):
        raise ConnectionError(
            f"message is {type(msg).__name__}, expected object")
    return msg


def _encode_f32(arr: np.ndarray) -> dict:
    arr = np.ascontiguousarray(arr, np.float32)
    return {"shape": list(arr.shape),
            "b64": base64.b64encode(arr.tobytes()).decode()}


def _decode_f32(d: dict) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["b64"]),
                         np.float32).reshape(d["shape"]).copy()


# ---------------------------------------------------------------------------
# readiness files
# ---------------------------------------------------------------------------

def _ready_path(fleet_dir: str, replica: int) -> str:
    return os.path.join(fleet_dir, f"replica-m{replica}.json")


def _write_ready(fleet_dir: str, replica: int, incarnation: int,
                 port: int, topo_generation: int = 0) -> None:
    """Atomic publish: the manager must never read a torn port. Routed
    through the storage-fault seams (resilience/storage.py); a failed
    publish propagates and the replica dies unready — the manager's
    ready-timeout + relaunch policy IS the degradation path here.
    `topo_generation` is the graph-topology watermark the replica
    serves (stream/journal.py replay runs BEFORE this publish, so
    readiness implies caught-up)."""
    from ..resilience.storage import write_text_atomic

    write_text_atomic(
        _ready_path(fleet_dir, replica),
        json.dumps({"replica": int(replica),
                    "incarnation": int(incarnation),
                    "port": int(port), "pid": os.getpid(),
                    "topo_generation": int(topo_generation),
                    "t_ready": time.time()}),
        fsync=False)


def _read_ready(fleet_dir: str, replica: int) -> Optional[dict]:
    try:
        with open(_ready_path(fleet_dir, replica)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _heartbeat_path(fleet_dir: str, replica: int,
                    incarnation: int) -> str:
    # the HeartbeatWatchdog naming: heartbeat-g<generation>-r<rank>,
    # keyed on the replica's incarnation so a relaunch never reads its
    # previous life's beats as fresh
    return os.path.join(fleet_dir,
                        f"heartbeat-g{incarnation}-r{replica}")


# ---------------------------------------------------------------------------
# replica process
# ---------------------------------------------------------------------------

class ReplicaServer:
    """One serving replica: engine + TCP endpoint + heartbeats +
    checkpoint hot-swap watcher. Runs in its own process; everything
    that touches the engine holds `self._lock` (queries, swaps), so a
    hot-swap drains in-flight queries and in-flight queries never see
    half-swapped params."""

    def __init__(self, engine, fleet_dir: str, replica_id: int,
                 incarnation: int = 0, ml=None,
                 checkpoint_dir: Optional[str] = None,
                 swap_poll_s: float = 0.5,
                 heartbeat_interval_s: float = 0.2,
                 report_every_s: float = 2.0,
                 replay: Optional[Callable[[], int]] = None,
                 log: Callable[[str], None] = print):
        from ..resilience.coord import HeartbeatWatchdog

        self.engine = engine
        self.fleet_dir = fleet_dir
        self.replica_id = int(replica_id)
        self.incarnation = int(incarnation)
        self.ml = ml
        self.checkpoint_dir = checkpoint_dir
        # crash-consistent streaming: a restart/spawn must replay the
        # durable delta journal BEFORE declaring readiness, so the
        # fleet never routes to a replica serving a stale topology.
        # `replay()` returns the number of journal records applied.
        self._replay = replay
        self.swap_poll_s = float(swap_poll_s)
        self.report_every_s = float(report_every_s)
        self.log = log
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.stats = ServingStats()
        self.n_queries = 0
        os.makedirs(fleet_dir, exist_ok=True)
        # n_ranks=1: this watchdog only BEATS (no peers to watch) —
        # liveness judgment is the driver-side manager's job
        self._hb = HeartbeatWatchdog(
            fleet_dir, rank=self.replica_id, n_ranks=1,
            timeout_s=60.0, interval_s=heartbeat_interval_s,
            generation=self.incarnation, log=log)
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._threads: List[threading.Thread] = []

    # ---------------- request handling --------------------------------

    def _handle(self, msg: dict) -> dict:
        op = msg.get("op")
        if op == "query":
            ids = np.asarray(msg["ids"], np.int64)
            trace = msg.get("trace") or ()
            t_handle0 = time.time() if trace else 0.0
            with self._lock:
                t_eng0 = time.time() if trace else 0.0
                out = self.engine.query(ids, stats=self.stats)
                t_eng1 = time.time() if trace else 0.0
                meta = {
                    "hit": bool(self.engine.fully_fresh),
                    "staleness_age": int(self.engine.staleness_age),
                    "param_generation": int(self.engine.param_generation),
                    "param_staleness": int(self.engine.param_staleness),
                    "topo_generation": int(getattr(
                        self.engine, "topo_generation", 0)),
                    "incarnation": self.incarnation,
                }
            self.n_queries += int(ids.size)
            if trace:
                self._emit_spans(trace, ids.size, t_handle0,
                                 t_eng0, t_eng1)
            return {"ok": True, "logits": _encode_f32(out), "meta": meta}
        if op == "update":
            # mixed update/query workload (loadgen --update-fraction):
            # patch owned-node features + refresh the halo under the
            # engine lock, exactly like the single-process churn path
            ids = np.asarray(msg["ids"], np.int64)
            vals = _decode_f32(msg["vals"])
            with self._lock:
                self.engine.apply_updates(ids, vals)
                self.engine.refresh_boundary()
            return {"ok": True, "n": int(ids.size)}
        if op == "health":
            with self._lock:
                return {"ok": True, "replica": self.replica_id,
                        "incarnation": self.incarnation,
                        "pid": os.getpid(),
                        "param_generation":
                            int(self.engine.param_generation),
                        "param_staleness":
                            int(self.engine.param_staleness),
                        "topo_generation":
                            int(getattr(self.engine,
                                        "topo_generation", 0)),
                        "n_feat_raw": int(getattr(self.engine,
                                                  "n_feat_raw", 0)),
                        "n_queries": int(self.n_queries)}
        if op == "stop":
            self._stop.set()
            return {"ok": True, "stopping": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _emit_spans(self, trace, n_rows: int, t_handle0: float,
                    t_eng0: float, t_eng1: float) -> None:
        """Server-side spans for a traced batch: `replica` (request
        handling incl. lock wait) + `engine` (the compiled chunked
        execution alone), one pair per riding trace id, landed in this
        replica's own metrics stream. Timestamps are unix seconds so
        cli.timeline can stitch them to the driver's spans."""
        if self.ml is None:
            return
        from .tracing import SpanWriter

        if not hasattr(self, "_span_writer"):
            self._span_writer = SpanWriter(
                self.ml, clock=time.time,
                source=f"replica-m{self.replica_id}")
        t_now = time.time()
        for tid in trace:
            self._span_writer.emit(
                tid, "replica", t_handle0, t_now, "ok",
                replica=self.replica_id, rows=int(n_rows),
                incarnation=self.incarnation)
            self._span_writer.emit(
                tid, "engine", t_eng0, t_eng1, "ok",
                replica=self.replica_id, rows=int(n_rows))

    # ---------------- background threads ------------------------------

    def _swap_loop(self) -> None:
        while not self._stop.wait(self.swap_poll_s):
            self.poll_checkpoint()

    def poll_checkpoint(self) -> Optional[dict]:
        """One checkpoint-watcher step: hot-swap if a newer verified
        generation exists. Public so tests can drive it without the
        thread. Returns the swap report when a swap happened."""
        if not self.checkpoint_dir:
            return None
        with self._lock:
            rep = self.engine.load_from_checkpoint(
                self.checkpoint_dir, ml=self.ml)
        if rep.get("swapped"):
            self.stats.note_params(rep["param_generation"],
                                   rep.get("param_staleness", 0))
            if self.ml is not None:
                self.ml.fleet("hot-swap", self.replica_id,
                              param_generation=rep["param_generation"],
                              swap_ms=rep["swap_ms"],
                              incarnation=self.incarnation)
            self.log(f"replica {self.replica_id}: hot-swapped to "
                     f"generation {rep['param_generation']} in "
                     f"{rep['swap_ms']:.0f}ms")
            return rep
        if rep.get("reason") in ("all-corrupt",
                                 "newer-generation-corrupt") \
                and self.ml is not None:
            self.ml.fleet("swap-rejected", self.replica_id,
                          reason=rep["reason"],
                          incarnation=self.incarnation)
        return None

    def _report_loop(self) -> None:
        while not self._stop.wait(self.report_every_s):
            self._emit_window()

    def _emit_window(self, final: bool = False) -> None:
        if self.ml is None:
            return
        rec = self.stats.snapshot(queue_depth=0)
        extra = {"replica": self.replica_id,
                 "incarnation": self.incarnation}
        if final:
            extra["final"] = True
        self.ml.serving(**rec, **extra)

    # ---------------- lifecycle ---------------------------------------

    def serve_forever(self, host: str = "127.0.0.1") -> None:
        """Bind port 0, publish readiness, serve until a stop op or
        SIGTERM; drains in-flight requests, emits a final serving
        record, and returns."""
        handler_self = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):  # one persistent connection
                while True:
                    try:
                        msg = _recv_msg(self.request)
                    except (ConnectionError, OSError):
                        return
                    try:
                        resp = handler_self._handle(msg)
                    except Exception as exc:  # noqa: BLE001
                        resp = {"ok": False,
                                "error": f"{type(exc).__name__}: {exc}"}
                    try:
                        _send_msg(self.request, resp)
                    except OSError:
                        # genuinely-optional (storage-fault audit): the
                        # CLIENT hung up mid-error-reply; it will retry
                        # against a survivor via the router's failover
                        return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, 0), _Handler)
        port = self._server.server_address[1]
        self._hb.start()
        for target, name in ((self._swap_loop, "swap"),
                             (self._report_loop, "report")):
            t = threading.Thread(
                target=target, daemon=True,
                name=f"replica-{self.replica_id}-{name}")
            t.start()
            self._threads.append(t)
        srv = threading.Thread(target=self._server.serve_forever,
                               kwargs={"poll_interval": 0.05},
                               daemon=True,
                               name=f"replica-{self.replica_id}-srv")
        srv.start()
        # journal replay BEFORE readiness: a restarted replica catches
        # up to the fleet's topo_generation before any batch can route
        # here. The port is already bound (so the manager's connect
        # won't race), but the ready file is not yet published.
        if self._replay is not None:
            n = int(self._replay())
            gen = int(getattr(self.engine, "topo_generation", 0))
            self.log(f"replica {self.replica_id}: replayed {n} journal "
                     f"record(s); topo_generation={gen}")
            if self.ml is not None:
                self.ml.journal(
                    op="replay", seq=-1, topo_generation=gen,
                    n_records=n,
                    source=f"replica-m{self.replica_id}")
        _write_ready(self.fleet_dir, self.replica_id, self.incarnation,
                     port,
                     topo_generation=int(getattr(
                         self.engine, "topo_generation", 0)))
        self.log(f"replica {self.replica_id} (incarnation "
                 f"{self.incarnation}) serving on port {port}")
        try:
            while not self._stop.wait(0.1):
                pass
        finally:
            self.shutdown()

    def request_stop(self) -> None:
        self._stop.set()

    def shutdown(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._hb.suspend()
        self._emit_window(final=True)
        if self.ml is not None:
            self.ml.hard_flush()


# ---------------------------------------------------------------------------
# driver-side client
# ---------------------------------------------------------------------------

class ReplicaError(ConnectionError):
    """The replica did not answer (dead, closing, or protocol error)."""


class TcpReplicaClient:
    """One persistent connection to a replica; thread-safe (one
    request in flight per connection). `query` returns
    ``(logits, meta)`` — the router passes the result through
    opaquely."""

    def __init__(self, host: str, port: int, replica_id: int,
                 timeout_s: float = 10.0):
        self.host = host
        self.port = int(port)
        self.replica_id = int(replica_id)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        # network-fault chaos seam (serve/autoscale.NetFaultInjector):
        # when set, called as fault_gate(replica_id, op) before every
        # RPC — it may sleep (net-delay) or raise ConnectionError
        # (net-drop / net-partition). None in production.
        self.fault_gate: Optional[Callable[[int, str], None]] = None

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _rpc(self, msg: dict) -> dict:
        # flight-recorder span (obs/flight.py): an RPC against a dead
        # or wedged replica is exactly the kind of silent block the
        # black-box annotation must name (replica, op, endpoint)
        from ..obs import flight as _flight

        gate = self.fault_gate
        if gate is not None:
            try:
                gate(self.replica_id, str(msg.get("op", "?")))
            except ConnectionError as exc:
                raise ReplicaError(
                    f"replica {self.replica_id} at "
                    f"{self.host}:{self.port}: {exc}") from exc
        frec = _flight.get_recorder()
        frec.enter("rpc", replica=self.replica_id,
                   op=str(msg.get("op", "?")),
                   endpoint=f"{self.host}:{self.port}")
        with self._lock:
            try:
                s = self._ensure()
                _send_msg(s, msg)
                resp = _recv_msg(s)
            except (OSError, ValueError, ConnectionError) as exc:
                self._drop()
                frec.exit("rpc", replica=self.replica_id,
                          error=f"{type(exc).__name__}: {exc}"[:120])
                raise ReplicaError(
                    f"replica {self.replica_id} at "
                    f"{self.host}:{self.port}: {exc}") from exc
        frec.exit("rpc", replica=self.replica_id)
        if not resp.get("ok"):
            raise ReplicaError(
                f"replica {self.replica_id} error: "
                f"{resp.get('error', 'unknown')}")
        return resp

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                # genuinely-optional (storage-fault audit): closing an
                # already-dead socket; the fd is gone either way
                pass
            self._sock = None

    def query(self, ids: np.ndarray, trace=None):
        msg = {"op": "query",
               "ids": np.asarray(ids, np.int64).tolist()}
        if trace:
            # sampled trace ids riding this batch: the replica emits
            # its server-side spans for each (serve/tracing.py)
            msg["trace"] = list(trace)
        resp = self._rpc(msg)
        return _decode_f32(resp["logits"]), resp.get("meta", {})

    def update(self, ids: np.ndarray, vals: np.ndarray) -> int:
        """Broadcastable feature update (mixed workload): patch owned
        rows + refresh the halo replica-side. Returns rows applied."""
        resp = self._rpc({"op": "update",
                          "ids": np.asarray(ids, np.int64).tolist(),
                          "vals": _encode_f32(vals)})
        return int(resp.get("n", 0))

    def health(self) -> dict:
        return self._rpc({"op": "health"})

    def stop(self) -> None:
        try:
            self._rpc({"op": "stop"})
        except ReplicaError:
            pass

    def reconnect(self, port: int) -> None:
        """Point at a relaunched incarnation's new port."""
        with self._lock:
            self._drop()
            self.port = int(port)

    def close(self) -> None:
        with self._lock:
            self._drop()


# ---------------------------------------------------------------------------
# driver-side supervisor
# ---------------------------------------------------------------------------

def _popen_logged(cmd: List[str], env: Dict[str, str], log_path: str):
    logf = open(log_path, "ab")
    try:
        return subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf,
                                start_new_session=True)
    finally:
        logf.close()


class _Replica:
    """Manager-side view of one replica slot."""

    def __init__(self, rid: int):
        self.rid = rid
        self.incarnation = 0
        self.proc = None
        self.client: Optional[TcpReplicaClient] = None
        self.up = False
        self.relaunch_at: Optional[float] = None  # backoff deadline
        self.died_at: Optional[float] = None
        self.launched_at: Optional[float] = None
        self.gave_up = False
        # autoscale scale-down: set BEFORE the stop RPC so poll() never
        # reads the intentional exit as a death to relaunch
        self.retired = False


class FleetManager:
    """Launch, watch, and relaunch the replica subprocesses.

    Death is detected two ways — subprocess exit (fast) and heartbeat
    staleness (catches a wedged-but-alive process) — and each death
    runs through a per-replica :class:`RestartPolicy` (exponential
    backoff, lifetime cap, restart-storm brake: the elastic
    supervisor's brakes, reused). `poll(router)` is the one
    entrypoint the load loop calls; it marks the router down/up and
    emits the contracted `fleet` + fault/recovery records."""

    def __init__(self, fleet_dir: str, n_replicas: int,
                 child_args: List[str], *,
                 ml=None,
                 env: Optional[Dict[str, str]] = None,
                 heartbeat_timeout_s: float = 3.0,
                 ready_timeout_s: float = 120.0,
                 max_restarts: int = 4,
                 backoff_base_s: float = 0.5,
                 backoff_max_s: float = 5.0,
                 popen: Callable = _popen_logged,
                 log: Callable[[str], None] = print):
        from ..resilience.elastic import RestartPolicy

        self.fleet_dir = os.path.abspath(fleet_dir)
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.n_replicas = int(n_replicas)
        self.child_args = list(child_args)
        self.ml = ml
        self.env = dict(env if env is not None else os.environ)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.popen = popen
        self.log = log
        self.replicas = {rid: _Replica(rid)
                         for rid in range(self.n_replicas)}
        # kept so autoscale-spawned slots get the same brake policy
        self._policy_args = dict(max_restarts=max_restarts,
                                 backoff_base_s=backoff_base_s,
                                 backoff_max_s=backoff_max_s)
        self._policies = {rid: RestartPolicy(**self._policy_args)
                          for rid in range(self.n_replicas)}
        self.window = -1  # updated by the load loop for record context
        # net-fault chaos seam, installed on every client this manager
        # builds (serve/autoscale.NetFaultInjector.gate); None = off
        self.fault_gate = None
        self.n_spawned = 0
        self.n_retired = 0

    # ---------------- launch ------------------------------------------

    def _cmd(self, rep: _Replica) -> List[str]:
        # manager flags LAST so they win argparse's last-occurrence
        # rule over anything in the forwarded driver argv
        return [sys.executable, "-m", "pipegcn_tpu.cli.fleet"] \
            + self.child_args \
            + ["--replica-id", str(rep.rid),
               "--incarnation", str(rep.incarnation),
               "--fleet-dir", self.fleet_dir]

    def launch(self, rid: int) -> None:
        rep = self.replicas[rid]
        # retire the previous incarnation's readiness file so
        # wait_ready can never read a stale port
        try:
            os.remove(_ready_path(self.fleet_dir, rid))
        except OSError:
            # genuinely-optional (storage-fault audit): wait_ready
            # matches on the NEW incarnation number, so a stale file
            # that refuses to unlink is ignored, not trusted
            pass
        log_path = os.path.join(
            self.fleet_dir, f"replica-m{rid}-i{rep.incarnation}.log")
        rep.proc = self.popen(self._cmd(rep), self.env, log_path)
        rep.launched_at = time.monotonic()
        rep.relaunch_at = None
        self.log(f"fleet: launched replica {rid} incarnation "
                 f"{rep.incarnation} (pid {rep.proc.pid})")

    def wait_ready(self, rid: int,
                   timeout_s: Optional[float] = None) -> dict:
        """Block until replica rid's CURRENT incarnation publishes its
        readiness file; returns it. Raises TimeoutError (or
        RuntimeError if the child exited) on failure."""
        rep = self.replicas[rid]
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.ready_timeout_s)
        while time.monotonic() < deadline:
            info = _read_ready(self.fleet_dir, rid)
            if info and info.get("incarnation") == rep.incarnation:
                return info
            if rep.proc is not None and rep.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {rid} exited rc={rep.proc.returncode} "
                    f"before becoming ready (see its log in "
                    f"{self.fleet_dir})")
            time.sleep(0.05)
        raise TimeoutError(f"replica {rid} not ready within "
                           f"{timeout_s or self.ready_timeout_s}s")

    def launch_all(self) -> Dict[int, TcpReplicaClient]:
        """Launch every replica, wait for readiness, build clients.
        Returns {rid: client} for the Router."""
        for rid in self.replicas:
            self.launch(rid)
        clients = {}
        for rid, rep in self.replicas.items():
            info = self.wait_ready(rid)
            rep.client = TcpReplicaClient("127.0.0.1", info["port"], rid)
            rep.client.fault_gate = self.fault_gate
            rep.up = True
            clients[rid] = rep.client
        return clients

    def install_fault_gate(self, gate) -> None:
        """Arm the net-fault chaos seam on every existing client and
        every client this manager builds from now on."""
        self.fault_gate = gate
        for rep in self.replicas.values():
            if rep.client is not None:
                rep.client.fault_gate = gate

    def active_count(self) -> int:
        """Replica slots that are part of the intended fleet size:
        not retired, not given up. The autoscaler's notion of
        n_replicas — a slot mid-relaunch still counts (capacity is
        coming back; spawning MORE on top would double-correct)."""
        return sum(1 for r in self.replicas.values()
                   if not r.retired and not r.gave_up)

    # ---------------- autoscale actuation -----------------------------

    def spawn_replica(self, router: Optional[Router] = None) -> int:
        """Scale-up actuation: launch a NEW replica slot (next unused
        id) without blocking — poll() folds it into the router via the
        standard rejoin path once its readiness file appears, so the
        load loop never stalls waiting on an engine build. Returns the
        new replica id."""
        from ..resilience.elastic import RestartPolicy

        rid = max(self.replicas) + 1 if self.replicas else 0
        rep = _Replica(rid)
        self.replicas[rid] = rep
        self._policies[rid] = RestartPolicy(**self._policy_args)
        self.n_replicas = len(self.replicas)
        self.launch(rid)
        self.n_spawned += 1
        if self.ml is not None:
            self.ml.fleet("spawn", rid, window=self.window,
                          incarnation=rep.incarnation)
        return rid

    def retire_replica(self, rid: Optional[int] = None,
                       router: Optional[Router] = None) -> Optional[int]:
        """Scale-down actuation: pick a victim (highest-id live slot
        when `rid` is None), pull it out of routing FIRST (its ring
        arcs remap, in-flight batches finish), then stop the process.
        The slot is flagged `retired` before the stop RPC so poll()
        never reads the intentional exit as a death. Returns the
        retired id (None when nothing was retirable)."""
        if rid is None:
            live = [r for r in sorted(self.replicas)
                    if not self.replicas[r].retired
                    and not self.replicas[r].gave_up]
            if not live:
                return None
            rid = live[-1]
        rep = self.replicas[rid]
        rep.retired = True
        rep.up = False
        if router is not None:
            router.remove_replica(rid)
        if rep.client is not None:
            rep.client.stop()
        if rep.proc is not None and rep.proc.poll() is None:
            try:
                rep.proc.terminate()
            except OSError:
                # genuinely-optional (storage-fault audit): it already
                # exited on the stop op; poll-side reaping is enough
                pass
        if rep.client is not None:
            rep.client.close()
        self.n_retired += 1
        if self.ml is not None:
            self.ml.fleet("retire", rid, window=self.window,
                          incarnation=rep.incarnation)
        self.log(f"fleet: retired replica {rid} (scale-down); "
                 f"{self.active_count()} slots remain")
        return rid

    # ---------------- liveness ----------------------------------------

    def _heartbeat_stale(self, rep: _Replica) -> bool:
        path = _heartbeat_path(self.fleet_dir, rep.rid, rep.incarnation)
        try:
            age = time.time() - os.path.getmtime(path)
        except OSError:
            # no beat yet: judge from launch grace instead
            if rep.launched_at is None:
                return False
            return (time.monotonic() - rep.launched_at
                    > self.heartbeat_timeout_s + self.ready_timeout_s)
        return age > self.heartbeat_timeout_s

    def _on_death(self, rep: _Replica, reason: str,
                  router: Optional[Router]) -> None:
        rep.up = False
        rep.died_at = time.monotonic()
        if rep.proc is not None and rep.proc.poll() is None:
            # wedged-but-alive (heartbeat silence): cull it so the
            # relaunch never races a zombie still holding the port
            try:
                rep.proc.kill()
            except OSError:
                # genuinely-optional (storage-fault audit): the process
                # already exited between poll() and kill()
                pass
        if router is not None:
            # the router's on_fault hook (wired in cli/fleet.py) emits
            # the replica-dead + fault records exactly once per death
            # edge, whether the router's dispatch or this supervisor
            # noticed first
            router.mark_down(rep.rid, reason)
        elif self.ml is not None:
            # no router (standalone manager): emit the dual records —
            # the contracted fleet event AND a fault record with
            # kind="fleet" so existing fault rollups count it
            self.ml.fleet("replica-dead", rep.rid, window=self.window,
                          reason=reason, incarnation=rep.incarnation)
            self.ml.fault("fleet", epoch=self.window, rank=rep.rid,
                          reason=reason)
        pol = self._policies[rep.rid]
        if rep.launched_at is not None:
            pol.note_stable(time.monotonic() - rep.launched_at)
        dec = pol.decide()
        if dec.action != "restart":
            rep.gave_up = True
            self.log(f"fleet: replica {rep.rid} NOT relaunched "
                     f"({dec.reason}); degraded to "
                     f"{sum(r.up for r in self.replicas.values())} "
                     f"replicas")
            return
        rep.incarnation += 1
        rep.relaunch_at = time.monotonic() + dec.delay_s
        if self.ml is not None:
            self.ml.fleet("relaunch", rep.rid, window=self.window,
                          incarnation=rep.incarnation,
                          delay_s=dec.delay_s)
        self.log(f"fleet: replica {rep.rid} dead ({reason}); relaunch "
                 f"as incarnation {rep.incarnation} in "
                 f"{dec.delay_s:.1f}s")

    def note_topo(self, rid: int, gen,
                  router: Optional[Router]) -> Optional[bool]:
        """Fold a replica's reported topo_generation (query meta,
        health response, or readiness file) into the router's skew
        detector, emitting the fleet record on each edge: `topo-skew`
        when the replica falls behind the fleet maximum (it is routed
        around), `topo-caught-up` when journal replay brings it back.
        Returns the router edge (True down / False up / None)."""
        if router is None or gen is None:
            return None
        edge = router.note_topo_generation(rid, gen)
        if edge is None:
            return None
        if self.ml is not None:
            gens = router.topo_generations()
            fleet_gen = max(gens.values()) if gens else int(gen)
            if edge:
                self.ml.fleet("topo-skew", rid, window=self.window,
                              topo_generation=int(gen),
                              fleet_generation=int(fleet_gen))
            else:
                self.ml.fleet("topo-caught-up", rid,
                              window=self.window,
                              topo_generation=int(gen))
        if edge:
            self.log(f"fleet: replica {rid} topology STALE "
                     f"(generation {int(gen)}); routed around until "
                     f"journal replay catches it up")
        else:
            self.log(f"fleet: replica {rid} topology caught up "
                     f"(generation {int(gen)}); routed back in")
        return edge

    def poll(self, router: Optional[Router] = None) -> None:
        """One supervision step: detect deaths, run due relaunches,
        fold ready rejoins back into the router."""
        for rep in list(self.replicas.values()):
            if rep.gave_up or rep.retired:
                continue
            if rep.up:
                if rep.proc is not None and rep.proc.poll() is not None:
                    self._on_death(
                        rep, f"exit rc={rep.proc.returncode}", router)
                elif self._heartbeat_stale(rep):
                    self._on_death(rep, "heartbeat-stale", router)
                elif router is not None and rep.client is not None \
                        and router.has_replica(rep.rid) \
                        and not router.is_up(rep.rid):
                    # alive by process AND heartbeat, but routed out —
                    # a dispatch error marked it down (e.g. a transient
                    # net fault). Probe it directly; a healthy answer
                    # routes it back in WITHOUT a relaunch — the
                    # partition-heal path
                    try:
                        rep.client.health()
                    except ReplicaError:
                        pass  # still unreachable; keep it routed out
                    else:
                        if router.mark_up(rep.rid):
                            if self.ml is not None:
                                self.ml.fleet(
                                    "replica-reachable", rep.rid,
                                    window=self.window,
                                    incarnation=rep.incarnation)
                            self.log(f"fleet: replica {rep.rid} "
                                     f"reachable again; routed back in")
                continue
            # down: launch when the backoff expires...
            if rep.relaunch_at is not None \
                    and time.monotonic() >= rep.relaunch_at:
                self.launch(rep.rid)
            # ...and rejoin once the new incarnation publishes
            if rep.proc is not None and rep.relaunch_at is None:
                info = _read_ready(self.fleet_dir, rep.rid)
                if info and info.get("incarnation") == rep.incarnation:
                    if rep.client is None:
                        rep.client = TcpReplicaClient(
                            "127.0.0.1", info["port"], rep.rid)
                        rep.client.fault_gate = self.fault_gate
                    else:
                        rep.client.reconnect(info["port"])
                    rep.up = True
                    latency = (time.monotonic() - rep.died_at
                               if rep.died_at is not None else 0.0)
                    if router is not None:
                        if router.has_replica(rep.rid):
                            router.mark_up(rep.rid)
                        else:
                            # autoscale-spawned slot the router has
                            # never seen: fold it into the ring
                            router.add_replica(rep.rid, rep.client)
                    if self.ml is not None:
                        self.ml.fleet(
                            "replica-rejoin", rep.rid,
                            window=self.window,
                            incarnation=rep.incarnation,
                            rejoin_latency_s=latency)
                        self.ml.recovery("fleet", epoch=self.window,
                                         rank=rep.rid,
                                         incarnation=rep.incarnation)
                    self.log(f"fleet: replica {rep.rid} rejoined as "
                             f"incarnation {rep.incarnation} after "
                             f"{latency:.1f}s")
                    # the ready file carries the replica's post-replay
                    # topo_generation: a rejoin that somehow skipped
                    # replay is caught here and held out of routing
                    self.note_topo(rep.rid,
                                   info.get("topo_generation"), router)
                elif rep.proc.poll() is not None:
                    # relaunch died before readiness: another strike
                    self._on_death(
                        rep, f"exit rc={rep.proc.returncode} before "
                             f"ready", router)

    # ---------------- chaos / shutdown --------------------------------

    def kill_replica(self, rid: int) -> None:
        """SIGKILL, no warning — the replica-kill@W[:mK] chaos fault."""
        rep = self.replicas[rid]
        if rep.proc is not None and rep.proc.poll() is None:
            self.log(f"fleet: CHAOS SIGKILL replica {rid} "
                     f"(pid {rep.proc.pid})")
            try:
                os.kill(rep.proc.pid, signal.SIGKILL)
            except OSError:
                # genuinely-optional (storage-fault audit): the chaos
                # drill wanted it dead and it already is
                pass

    def stop_all(self, timeout_s: float = 10.0) -> None:
        """Graceful stop: protocol stop op, then SIGTERM, then
        SIGKILL."""
        for rep in self.replicas.values():
            if rep.client is not None:
                rep.client.stop()
        deadline = time.monotonic() + timeout_s
        for rep in self.replicas.values():
            if rep.proc is None:
                continue
            if rep.proc.poll() is None:
                try:
                    rep.proc.terminate()
                except OSError:
                    # genuinely-optional (storage-fault audit): races
                    # the replica's own exit; SIGKILL below is the
                    # backstop
                    pass
            while rep.proc.poll() is None \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            if rep.proc.poll() is None:
                try:
                    rep.proc.kill()
                except OSError:
                    # genuinely-optional (storage-fault audit): already
                    # dead; wait() below reaps either way
                    pass
                rep.proc.wait()
        for rep in self.replicas.values():
            if rep.client is not None:
                rep.client.close()
        if self.ml is not None:
            self.ml.fleet("fleet-stop", -1, window=self.window,
                          reason="shutdown")


# ---------------------------------------------------------------------------
# the fleet load loop
# ---------------------------------------------------------------------------

def run_fleet_loop(manager: FleetManager, router: Router, *,
                   num_nodes: int, duration_s: float, qps: float,
                   max_batch: int = 64, max_delay_ms: float = 5.0,
                   ladder_min: int = 8,
                   ids_per_query: int = 1,
                   report_every_s: float = 2.0,
                   max_queue: Optional[int] = None,
                   ticket_deadline_ms: Optional[float] = None,
                   seed: int = 0, ml=None,
                   fault_plan=None,
                   traffic: Optional[str] = None,
                   update_fraction: float = 0.0,
                   ladder=None,
                   autoscaler=None,
                   alerts_fn: Optional[Callable[[], List[str]]] = None,
                   net_faults=None,
                   trace_sample_rate: float = 0.0,
                   poll_every_s: float = 0.1,
                   stop: Optional[Callable[[], bool]] = None,
                   clock: Callable[[], float] = time.monotonic,
                   sleep: Callable[[float], None] = time.sleep) -> dict:
    """Open-loop load over the fleet; returns the aggregate summary.

    The driver-side MicroBatcher does the queueing (bounded queue +
    deadline shedding, optionally tightened by a graceful-degradation
    `ladder` — serve/batcher.AdmissionLadder); worker threads pull
    taken batches off an internal dispatch queue and push them through
    the router, so batches flow to every up replica concurrently. A
    serving window closes every `report_every_s`: an aggregated
    `serving` record is emitted, per-replica depth/shed counters are
    sampled, the supervision poll runs, any `replica-kill@W[:mK]` /
    net-fault entry due at that window boundary fires (windows are
    1-indexed: window 1 is the first report), and — when `autoscaler`
    (serve/autoscale.AutoscalePolicy) is set — the window's telemetry
    plus any `alerts_fn()` fire edges feed one policy decision, whose
    scale-up/scale-down the manager executes immediately (spawn is
    non-blocking; the new replica joins routing via the standard
    rejoin path when ready).

    `traffic` / `update_fraction` shape the arrival schedule
    (serve/loadgen.RateShape): update arrivals broadcast a seeded
    feature patch to every up replica (best-effort — a replica
    relaunched mid-run misses earlier updates) and never enter the
    query ticket ledger, so conservation stays a statement about
    queries alone."""
    import queue as _queue

    stats = ServingStats(clock)
    all_lat: List[float] = []
    fills: List[float] = []
    lat_lock = threading.Lock()

    def observer(bucket, n_valid, lats):
        with lat_lock:
            stats.note_batch(bucket, n_valid, lats)
            all_lat.extend(lats)
            fills.append(n_valid / bucket)

    # sampled per-query tracing (serve/tracing.py): the trace id is
    # minted at submit and rides the ticket through queue/dispatch
    # spans here, an `rpc` span around the router round-trip, and the
    # replica's own replica/engine spans on the far side of the wire
    sampler = TraceSampler(trace_sample_rate, seed=seed, tag="fleet")
    spans = SpanWriter(ml if trace_sample_rate > 0 else None,
                       clock=clock, source="driver")

    shed_cum: Dict[str, int] = {}  # cumulative, survives window resets

    def on_shed(t, reason):
        stats.note_shed(t, reason)
        shed_cum[reason] = shed_cum.get(reason, 0) + int(t.ids.size)

    batcher = MicroBatcher(
        run=lambda ids: (_ for _ in ()).throw(
            RuntimeError("fleet loop dispatches via the router")),
        max_batch=max_batch, max_delay_ms=max_delay_ms,
        ladder_min=ladder_min, clock=clock, observer=observer,
        max_queue=max_queue, ticket_deadline_ms=ticket_deadline_ms,
        on_shed=on_shed, on_span=spans.emit,
        admission_ladder=ladder)

    # network-fault chaos: arm an injector whenever a fault plan is in
    # play (inert until a net-* entry fires) and install its gate on
    # every client the manager owns or will build
    net = net_faults
    if net is None and fault_plan is not None:
        from .autoscale import NetFaultInjector
        net = NetFaultInjector(clock=clock, sleep=sleep)
    if net is not None:
        # getattr: manager fakes in tests may not model the seam
        install = getattr(manager, "install_fault_gate", None)
        if install is not None:
            install(net.gate)

    def active_count() -> int:
        f = getattr(manager, "active_count", None)
        return f() if f is not None else manager.n_replicas

    work: "_queue.Queue" = _queue.Queue()
    n_fleet_shed = 0
    n_update_rpcs = 0
    n_update_errors = 0
    window = [0]  # 1-indexed once the first report window closes

    def worker():
        nonlocal n_fleet_shed, n_update_rpcs, n_update_errors
        while True:
            item = work.get()
            if item is None:
                work.task_done()
                return
            if item[0] == "u":
                # feature-update broadcast: best-effort to every up
                # replica, outside the query ticket ledger
                _, u_ids, u_vals = item
                for u_rid in router.up_replicas():
                    u_rep = manager.replicas.get(u_rid)
                    if u_rep is None or u_rep.client is None:
                        continue
                    try:
                        u_rep.client.update(u_ids, u_vals)
                        n_update_rpcs += 1
                    except Exception:  # noqa: BLE001 — best-effort
                        n_update_errors += 1
                work.task_done()
                continue
            _, take, ids = item
            traced = [t.trace_id for t in take
                      if t.trace_id is not None]
            try:
                t_rpc0 = clock()
                res, rid = router.dispatch(ids, trace=traced or None)
                if traced:
                    t_rpc1 = clock()
                    for tid in traced:
                        spans.emit(tid, "rpc", t_rpc0, t_rpc1, "ok",
                                   replica=int(rid),
                                   rows=int(ids.size))
                out, meta = (res if isinstance(res, tuple)
                             else (res, {}))
                batcher.complete_batch(take, np.asarray(out))
                with lat_lock:
                    stats.note_serve(
                        int(ids.size), bool(meta.get("hit", False)),
                        int(meta.get("staleness_age", 0)))
                    stats.note_params(
                        int(meta.get("param_generation", -1)),
                        int(meta.get("param_staleness", 0)))
                # live cross-replica skew detection: every answer
                # carries the replica's topo_generation; one falling
                # behind the fleet maximum is routed around. The batch
                # is already completed — a bookkeeping failure here
                # must never re-shed it.
                try:
                    manager.note_topo(
                        rid, meta.get("topo_generation"), router)
                except AttributeError:
                    pass  # manager without skew tracking
            except FleetUnavailable:
                # the whole fleet is down / timed out: the batch is
                # answered 'shed', never silently lost (the shed count
                # lands in the serving records)
                batcher.shed_batch(take, "fleet-down")
                n_fleet_shed += int(ids.size)
            except Exception as exc:  # noqa: BLE001 — never lose a batch
                batcher.shed_batch(take, f"error:{type(exc).__name__}")
                manager.log(f"fleet: dispatch error: {exc}")
            finally:
                work.task_done()

    max_fleet = (autoscaler.max_replicas if autoscaler is not None
                 else manager.n_replicas)
    n_workers = max(2, 2 * max(manager.n_replicas, max_fleet))
    workers = [threading.Thread(target=worker, daemon=True,
                                name=f"fleet-worker-{i}")
               for i in range(n_workers)]
    for w in workers:
        w.start()

    gen = OpenLoopGenerator(num_nodes, qps, duration_s,
                            ids_per_query=ids_per_query, seed=seed,
                            traffic=traffic,
                            update_fraction=update_fraction)
    # mixed workload: update arrivals need the raw feature width to
    # synthesize patches; probe it once before load starts (a replica
    # under use_pp reports 0 — updates then count but don't broadcast)
    upd_rng = None
    feat_dim = 0
    if gen.update_fraction > 0:
        upd_rng = np.random.default_rng(seed + 7919)
        for rid in router.up_replicas():
            rep = manager.replicas.get(rid)
            if rep is None or rep.client is None:
                continue
            try:
                feat_dim = int(rep.client.health().get("n_feat_raw", 0))
                break
            except ReplicaError:
                continue
    t0 = clock()
    next_report = t0 + report_every_s
    next_poll = t0 + poll_every_s
    n_records = 0
    total_q = 0
    kills: List[dict] = []
    scale_events: List[dict] = []
    net_events: List[dict] = []
    rung_max = [0]
    per_replica_depth_max: Dict[int, int] = {
        rid: 0 for rid in manager.replicas}

    def emit(now, final=False):
        nonlocal n_records, total_q
        rec = stats.snapshot(
            queue_depth=batcher.queue_depth + work.qsize())
        total_q += rec["queries"]
        depths = router.queue_depths()
        for rid, d in depths.items():
            per_replica_depth_max[rid] = max(
                per_replica_depth_max.get(rid, 0), d)
        rung_max[0] = max(rung_max[0], batcher.rung)
        if ml is not None:
            # uncontracted extras: replicas_up / replica_queue_depth /
            # rung feed the exporter's fleet gauges (obs/health.py)
            extra = {"replicas_up": len(router.up_replicas()),
                     "window": window[0],
                     "replica_queue_depth": {
                         str(r): int(d) for r, d in depths.items()},
                     "rung": int(batcher.rung)}
            if final:
                extra["final"] = True
            ml.serving(**rec, **extra)
        n_records += 1
        return rec

    def autoscale_tick(now, rec):
        """One closed-loop step: window telemetry (+ alert fire edges)
        -> policy decision -> actuation + contracted record."""
        alerts = list(alerts_fn()) if alerts_fn is not None else []
        served, shed = rec["queries"], rec["shed"]
        shed_rate = shed / max(served + shed, 1)
        n_before = active_count()
        dec = autoscaler.observe(
            window[0], queue_depth=rec["queue_depth"],
            shed_rate=shed_rate, p99_ms=rec["p99_ms"],
            n_replicas=n_before, alerts=alerts)
        if dec.action == "hold":
            return
        acted = None
        if dec.action == "scale-up":
            acted = manager.spawn_replica(router)
        elif dec.action == "scale-down":
            acted = manager.retire_replica(router=router)
            if acted is None:
                return  # nothing retirable; no record for a no-op
        scale_events.append({"window": window[0],
                             "action": dec.action,
                             "reason": dec.reason,
                             "replica": acted})
        if ml is not None:
            ml.autoscale(dec.action, dec.reason, window[0],
                         n_before, dec.target, dec.evidence)

    def net_tick(now):
        """Arm any net-fault entries due at this window boundary."""
        for kind in ("net-delay", "net-drop", "net-partition"):
            hit = fault_plan.due_member_arg(kind, window[0])
            if hit is None:
                continue
            rid, arg = hit
            if kind == "net-delay":
                ms = float(arg) if arg > 0 else 50.0
                net.delay(rid, ms, report_every_s)
                detail = {"ms": ms}
            elif kind == "net-drop":
                net.drop(rid, 1)
                detail = {}
            else:
                secs = float(arg) if arg > 0 else report_every_s
                net.partition(rid, secs)
                detail = {"duration_s": secs}
            net_events.append({"window": window[0], "kind": kind,
                               "replica": rid, **detail})
            if ml is not None:
                ml.fleet(kind, rid, window=window[0], **detail)
            manager.log(f"fleet: CHAOS {kind} replica {rid} "
                        f"at window {window[0]} {detail}")

    def tick(now):
        nonlocal next_report, next_poll
        if now >= next_poll:
            manager.poll(router)
            next_poll = now + poll_every_s
        if now >= next_report:
            window[0] += 1
            manager.window = window[0]
            rec = emit(now)
            next_report = now + report_every_s
            if fault_plan is not None:
                rid = fault_plan.due_member("replica-kill", window[0])
                if rid is not None and rid in manager.replicas:
                    manager.kill_replica(rid)
                    kills.append({"window": window[0], "replica": rid})
                if net is not None:
                    net_tick(now)
            if autoscaler is not None:
                autoscale_tick(now, rec)

    def maybe_dispatch(now, force=False):
        while True:
            batch = batcher.take_batch(now, force=force)
            if batch is None:
                return
            take, ids = batch
            work.put(("q", take, ids))

    stopped = False
    n_update_arrivals = 0
    for i, (t_arr, q) in enumerate(zip(gen.arrivals, gen.queries)):
        if stop is not None and stop():
            stopped = True
            break
        target = t0 + t_arr
        while True:
            now = clock()
            if now >= target:
                break
            maybe_dispatch(now)
            tick(now)
            if stop is not None and stop():
                stopped = True
                break
            sleep(min(target - now, 0.0005))
        if stopped:
            break
        if gen.is_update[i]:
            # an update arrival, not a query: broadcast the seeded
            # feature patch off-thread (never blocks the open loop)
            n_update_arrivals += 1
            if feat_dim > 0:
                vals = upd_rng.standard_normal(
                    (q.size, feat_dim)).astype(np.float32)
                work.put(("u", np.asarray(q, np.int64), vals))
        else:
            batcher.submit(q, trace_id=sampler.sample())
        now = clock()
        maybe_dispatch(now)
        tick(now)

    # shutdown: every accepted ticket is dispatched (and served by a
    # survivor or EXPLICITLY shed), the workers drain, then the final
    # aggregated record lands hard-flushed
    maybe_dispatch(clock(), force=True)
    work.join()
    for _ in workers:
        work.put(None)
    work.join()
    for w in workers:
        w.join(timeout=5.0)
    manager.poll(router)
    emit(clock(), final=True)

    with lat_lock:
        lat = np.asarray(all_lat, np.float64) * 1000.0
        fill = float(np.mean(fills)) if fills else None
    dt = max(clock() - t0, 1e-9)
    conserved = (batcher.n_submitted_rows
                 == batcher.n_served_rows + batcher.n_shed_rows
                 + batcher.queue_depth)
    return {
        "qps": float(total_q / dt),
        "n_queries": int(total_q),
        "duration_s": float(dt),
        "traffic": gen.shape.kind,
        "p50_ms": float(np.percentile(lat, 50)) if lat.size else None,
        "p95_ms": float(np.percentile(lat, 95)) if lat.size else None,
        "p99_ms": float(np.percentile(lat, 99)) if lat.size else None,
        "batch_fill": fill,
        "n_records": int(n_records),
        "n_submitted": int(batcher.n_submitted_rows),
        "n_served": int(batcher.n_served_rows),
        "n_shed": int(batcher.n_shed_rows),
        "n_fleet_shed": int(n_fleet_shed),
        "shed_by_reason": dict(shed_cum),
        "rung_max": int(rung_max[0]),
        "n_update_arrivals": int(n_update_arrivals),
        "n_update_rpcs": int(n_update_rpcs),
        "n_update_errors": int(n_update_errors),
        "n_failovers": int(router.n_failovers),
        "n_retried_rows": int(router.n_retried_rows),
        "replicas_up": len(router.up_replicas()),
        "replicas_active": active_count(),
        "n_spawned": int(getattr(manager, "n_spawned", 0)),
        "n_retired": int(getattr(manager, "n_retired", 0)),
        "scale_events": scale_events,
        "net_events": net_events,
        "autoscale": (None if autoscaler is None else {
            "up": int(autoscaler.n_up),
            "down": int(autoscaler.n_down),
            "refused": int(autoscaler.n_refused)}),
        "per_replica_dispatched": {
            str(k): int(v) for k, v in router.n_dispatched.items()},
        "per_replica_queue_depth_max": {
            str(k): int(v) for k, v in per_replica_depth_max.items()},
        "param_generation": int(stats.param_generation),
        "param_staleness": int(stats.param_staleness),
        "kills": kills,
        "n_traced": int(sampler.n_sampled),
        "n_spans": int(spans.n_spans),
        "drained": batcher.queue_depth == 0,
        "conserved": bool(conserved),
        "stopped_early": bool(stopped),
    }
