"""Compiled-once sharded inference engine.

The serving counterpart of `parallel/evaluator.py`: one donated-buffer,
shard_map'd forward program over the partitioned graph — no dropout, no
grads, no metric reduce — plus five tiny companion programs (full halo
exchange, incremental dirty-row exchange, in-place feature patch,
changed-slot halo flush, and the replicated query gather). All are
built ONCE per engine and
traced once per input shape; the batcher's power-of-two ladder keeps
the shape population finite, so after `warmup()` steady-state traffic
never recompiles (pinned by the TRACE_COUNTS test in test_serve.py).

Serving inherits every training-side kernel win by construction: the
forward program aggregates through `trainer.make_device_spmm_closure`
(the tuner's measured kernel choice over the PR-9 slab/reorder layout)
and exchanges boundaries through the same send-lists as training.

State owned by the engine (per device, sharded over PARTS_AXIS):
  _feat   [P, n_max, F]      mutable feature shard (donated on patch)
  _halo0  [P, (P-1)*B, F]    layer-0 halo cache in the SEND VIEW —
                             compute dtype, GCN degree pre-scale
                             applied — exactly the buffer forward()
                             would exchange at layer 0
  _logits [P, n_max, C]      f32 logits of every owned node

Staleness ledger (docs/SERVING.md): `staleness_age` counts applied
update batches whose effects the served logits do not yet reflect.
apply_updates bumps it; refresh() collapses it to the halo lag (logits
now see current features, boundary as-of the halo cache);
refresh_boundary() zeroes the halo lag. age == 0 ⇔ fully fresh ⇔ a
cache hit.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..models.sage import forward
from ..obs.trace import named_phase
from ..parallel.halo import exchange_blocks, halo_exchange
from ..parallel.mesh import PARTS_AXIS
from ..parallel.trainer import _pad_cols
from ..utils.checkpoint import (CheckpointCorrupt, _generations,
                                load_checkpoint)
from .batcher import MicroBatcher, ServingStats, bucket_for, bucket_ladder
from .cache import Layer0Cache
from .freshness import FreshnessTracker, dirty_exchange_blocks

# Incremented at TRACE time inside each program body: a jit cache hit
# leaves them untouched, so the delta across a traffic window counts
# recompiles exactly. The no-recompile acceptance test pins these.
TRACE_COUNTS: Dict[str, int] = {
    "exchange": 0, "inc": 0, "refresh": 0, "patch": 0, "query": 0,
    "flush": 0,
}


def trace_counts() -> Dict[str, int]:
    return dict(TRACE_COUNTS)


# data keys the inference program must NOT close over as static input:
# feat is the mutable serving carry, the rest are training-only
_NON_STATIC = ("feat", "label", "train_mask", "val_mask", "test_mask",
               "row_mask")


class ServingEngine:
    """Persistent sharded inference over one Trainer's mesh + artifact.

    Built once per (trainer, batch-shape-ladder); `for_trainer` caches
    instances so repeated construction (bench legs, warm restarts in
    the same process) reuses the compiled programs."""

    def __init__(self, trainer, *, max_batch: int = 64,
                 ladder_min: int = 8, max_update_rows: int = 256):
        if trainer.emulated:
            raise ValueError(
                "serving requires a real device mesh; emulated trainers "
                "stack partitions on one device and cannot serve")
        self.trainer = trainer
        sg = trainer.sg
        self.sg = sg
        self.cfg = trainer.cfg
        self.P = trainer.P
        self.n_max = sg.n_max
        self.halo_size = sg.halo_size
        self.n_class = sg.n_class
        self.n_feat_raw = sg.n_feat
        self.num_global_nodes = int((sg.global_nid >= 0).sum())
        self.ladder = bucket_ladder(ladder_min, max_batch)
        self.update_ladder = bucket_ladder(ladder_min, max_update_rows)
        self.params_version = 0
        # parameter-generation axis (schema v7): the checkpoint epoch
        # the served params came from (-1 = fresh init), and how many
        # newer PUBLISHED generations the fleet has not swapped in yet
        self.param_generation = -1
        self.param_staleness = 0
        self._last_corrupt_gen = -1  # dedupe corrupt-gen fault records

        # ---------------- host-side routing ---------------------------
        # global nid -> (partition, local row); -1 rows are padding
        nid = np.asarray(sg.global_nid)
        self._q_part = np.full(self.num_global_nodes, -1, np.int32)
        self._q_local = np.zeros(self.num_global_nodes, np.int32)
        for p in range(self.P):
            own = np.nonzero(nid[p] >= 0)[0]
            self._q_part[nid[p, own]] = p
            self._q_local[nid[p, own]] = own.astype(np.int32)

        self.freshness = FreshnessTracker(self.P, self.n_max)
        self.cache = Layer0Cache(sg.send_idx, sg.send_mask)
        self._feat_lag = 0   # update batches not yet in _logits
        self._halo_lag = 0   # update batches whose boundary rows are
        #                      not yet in _halo0
        # topology-generation axis (schema v8): count of graph delta
        # batches this engine's topology reflects (docs/STREAMING.md)
        self.topo_generation = 0

        # ---------------- device state --------------------------------
        # private copy of the feature shard: serving patches it under
        # donation, the trainer's training/eval buffer must stay intact
        self._feat = jax.jit(
            lambda x: x + jnp.zeros((), x.dtype),
            out_shardings=trainer._shard)(trainer.data["feat"])
        self._static = {k: v for k, v in trainer.data.items()
                        if k not in _NON_STATIC}
        self._params = trainer.state["params"]
        self._norm = trainer.state["norm"]
        self._logits = None

        # ---------------- compiled programs ---------------------------
        P, n_max, cfg = self.P, self.n_max, self.cfg
        mesh = trainer.mesh
        spec = PartitionSpec(PARTS_AXIS)
        repl = PartitionSpec()
        tm = jax.tree_util.tree_map
        st_spec = tm(lambda _: spec, self._static)
        params_spec = tm(lambda _: repl, self._params)
        norm_spec = tm(lambda _: repl, self._norm)
        is_gcn = cfg.model == "gcn"
        cdt = cfg.compute_dtype

        def send_view(f, in_deg):
            # exactly forward()'s transform on the buffer it hands to
            # comm_update at layer 0: cast to the compute dtype, then
            # (GCN) the f32 symmetric-norm pre-scale cast back — the
            # op sequence must match bit-for-bit or the cached halo
            # diverges from a live exchange
            h = f.astype(cdt)
            if is_gcn:
                d_sqrt = jnp.sqrt(in_deg.astype(jnp.float32))
                h = (h.astype(jnp.float32)
                     / d_sqrt[: h.shape[0], None]).astype(cdt)
            return h

        def exchange_fn(feat, d):
            TRACE_COUNTS["exchange"] += 1
            d = {k: v[0] for k, v in d.items()}
            h = send_view(feat[0], d["in_deg"])
            return exchange_blocks(h, d["send_idx"], d["send_mask"],
                                   PARTS_AXIS, P)[None]

        self._exchange_prog = jax.jit(jax.shard_map(
            exchange_fn, mesh=mesh, in_specs=(spec, st_spec),
            out_specs=spec))

        # wire-integrity guard on the dirty-row exchange: a trace-time
        # choice (guard off compiles the historical byte-identical
        # program), so the no-recompile pin holds either way — the inc
        # program still traces exactly once per engine
        wire_guard = int(getattr(trainer.tcfg, "integrity_check_every",
                                 0) or 0) > 0
        self._wire_guard = wire_guard
        self.wire_bad_total = 0

        def inc_fn(feat, halo0, dirty, d):
            TRACE_COUNTS["inc"] += 1
            d = {k: v[0] for k, v in d.items()}
            h = send_view(feat[0], d["in_deg"])
            if wire_guard:
                new, bad = dirty_exchange_blocks(
                    h, halo0[0], dirty[0], d["send_idx"],
                    d["send_mask"], PARTS_AXIS, P, guard=True)
                return new[None], jax.lax.psum(bad, PARTS_AXIS)
            new = dirty_exchange_blocks(
                h, halo0[0], dirty[0], d["send_idx"], d["send_mask"],
                PARTS_AXIS, P)
            return new[None]

        self._inc_prog = jax.jit(jax.shard_map(
            inc_fn, mesh=mesh, in_specs=(spec, spec, spec, st_spec),
            out_specs=(spec, repl) if wire_guard else spec),
            donate_argnums=(1,))

        def refresh_fn(params, norm, feat, halo0, d):
            TRACE_COUNTS["refresh"] += 1
            d = {k: v[0] for k, v in d.items()}
            f, h0 = feat[0], halo0[0]
            # the first exchanged layer consumes the resident halo
            # cache (the freshness carry); deeper layers exchange live
            # exactly like the evaluator. Under use_pp layer 0 never
            # exchanges, so every comm_update call is live.
            first = None if cfg.use_pp else 0

            def comm_update(i, h):
                if i == first:
                    return jnp.concatenate(
                        [h, h0.astype(h.dtype)], axis=0)
                return halo_exchange(h, d["send_idx"], d["send_mask"],
                                     PARTS_AXIS, P)

            spmm = trainer.make_device_spmm_closure(
                d, n_max=n_max, n_src_rows=n_max + self.halo_size,
                transport=False)
            gat = trainer.make_device_gat_closure(
                d, n_max=n_max, n_src_rows=n_max + self.halo_size,
                transport=False)
            with named_phase("serve_refresh"):
                logits, _ = forward(
                    params, cfg, f, d["edge_src"], d["edge_dst"],
                    d["in_deg"], n_max, training=False, halo_eval=True,
                    comm_update=comm_update, norm_state=norm,
                    spmm_fn=spmm, gat_fn=gat)
            return logits[None]

        self._refresh_prog = jax.jit(jax.shard_map(
            refresh_fn, mesh=mesh,
            in_specs=(params_spec, norm_spec, spec, spec, st_spec),
            out_specs=spec))

        def patch_fn(feat, up, ul, uv):
            TRACE_COUNTS["patch"] += 1
            f = feat[0]
            r = jax.lax.axis_index(PARTS_AXIS)
            # rows owned elsewhere (and -1 padding) map out of bounds
            # and are dropped by the scatter
            idx = jnp.where(up == r, ul, f.shape[0])
            f = f.at[idx].set(uv.astype(f.dtype), mode="drop")
            return f[None]

        self._patch_prog = jax.jit(jax.shard_map(
            patch_fn, mesh=mesh, in_specs=(spec, repl, repl, repl),
            out_specs=spec), donate_argnums=(0,))

        def flush_fn(halo0, m):
            # zero receiver-side halo slots whose send-list entry a
            # topology delta moved or removed: a removed entry's slot
            # must read zero (what a full exchange produces for a
            # masked-off slot), a moved entry's slot is re-shipped by
            # the next incremental refresh
            TRACE_COUNTS["flush"] += 1
            return jnp.where(m, jnp.zeros((), halo0.dtype), halo0)

        self._flush_prog = jax.jit(jax.shard_map(
            flush_fn, mesh=mesh, in_specs=(spec, spec),
            out_specs=spec), donate_argnums=(0,))

        def query_fn(logits, qp, ql):
            TRACE_COUNTS["query"] += 1
            lg = logits[0]
            r = jax.lax.axis_index(PARTS_AXIS)
            rows = jnp.take(lg, ql, axis=0, mode="clip")
            rows = jnp.where((qp == r)[:, None], rows,
                             jnp.zeros((), rows.dtype))
            with named_phase("serve_query"):
                # each queried row is non-zero on exactly its owner, so
                # the psum both routes and replicates the answer
                return jax.lax.psum(rows, PARTS_AXIS)

        self._query_prog = jax.jit(jax.shard_map(
            query_fn, mesh=mesh, in_specs=(spec, repl, repl),
            out_specs=repl))

        # the layer-0 halo cache starts fully fresh
        self._halo0 = self._exchange_prog(self._feat, self._static)

    # ------------------------------------------------------------------
    @classmethod
    def for_trainer(cls, trainer, **kw) -> "ServingEngine":
        cache = getattr(trainer, "_serving_engines", None)
        if cache is None:
            cache = trainer._serving_engines = {}
        key = tuple(sorted(kw.items()))
        if key not in cache:
            cache[key] = cls(trainer, **kw)
        return cache[key]

    # ---------------- params / warmup ---------------------------------

    def load_params(self, params=None, norm=None,
                    generation: Optional[int] = None) -> None:
        """Swap serving weights (e.g. after a checkpoint restore on the
        trainer); logits are stale until the next refresh().
        `generation` records the checkpoint epoch the params came from
        (the v7 parameter-generation axis on serving records)."""
        self._params = self.trainer.state["params"] \
            if params is None else params
        self._norm = self.trainer.state["norm"] if norm is None else norm
        self.params_version += 1
        self._logits = None
        if generation is not None:
            self.param_generation = int(generation)

    def load_from_checkpoint(self, directory: str, ml=None) -> Dict:
        """CRC-hardened zero-downtime weight swap from a checkpoint
        directory (the fleet hot-swap path, docs/SERVING.md "Fleet").

        Loads only the serving subset {params, norm} of the newest
        generation that passes digest verification (load_pytree reads
        only the template's paths, so optimizer moments never leave
        disk). A corrupt/truncated newest generation walks back to an
        older good one — and if nothing newer than what we already
        serve survives verification, the OLD params keep serving and a
        ``serve-ckpt-corrupt`` fault record is emitted (once per bad
        generation, not once per poll). Returns a swap report:
        {swapped, param_generation, param_staleness, swap_ms?, reason?}.
        """
        newest = max((e for e, _ in _generations(directory) if e >= 0),
                     default=-1)
        t0 = time.monotonic()
        template = {"params": self._params, "norm": self._norm}
        try:
            state, epoch = load_checkpoint(directory, template)
        except FileNotFoundError:
            return {"swapped": False, "reason": "no-checkpoint",
                    "param_generation": self.param_generation,
                    "param_staleness": self.param_staleness}
        except CheckpointCorrupt as exc:
            self.param_staleness = sum(
                1 for e, _ in _generations(directory)
                if e > self.param_generation)
            if ml is not None and newest != self._last_corrupt_gen:
                ml.fault("serve-ckpt-corrupt", epoch=newest,
                         reason=str(exc)[:200])
            self._last_corrupt_gen = newest
            return {"swapped": False, "reason": "all-corrupt",
                    "param_generation": self.param_generation,
                    "param_staleness": self.param_staleness}
        # count published generations the served params still trail
        stale_after = sum(1 for e, _ in _generations(directory)
                          if e > epoch)
        if epoch <= self.param_generation:
            # nothing newer was READABLE; if something newer was
            # PUBLISHED, the newest generation(s) failed verification
            self.param_staleness = sum(
                1 for e, _ in _generations(directory)
                if e > self.param_generation)
            if newest > self.param_generation:
                if ml is not None and newest != self._last_corrupt_gen:
                    ml.fault("serve-ckpt-corrupt", epoch=newest,
                             reason="newest generation failed "
                                    "verification; kept serving "
                                    f"generation {self.param_generation}")
                self._last_corrupt_gen = newest
                reason = "newer-generation-corrupt"
            else:
                reason = "no-newer-generation"
            return {"swapped": False, "reason": reason,
                    "param_generation": self.param_generation,
                    "param_staleness": self.param_staleness}
        if epoch < newest and ml is not None \
                and newest != self._last_corrupt_gen:
            # walked back: swapping to an older-than-newest good gen
            ml.fault("serve-ckpt-corrupt", epoch=newest,
                     reason=f"walked back to generation {epoch}")
            self._last_corrupt_gen = newest
        self.load_params(state["params"], state["norm"],
                         generation=epoch)
        self.refresh()  # retrace-free: same shapes, compiled programs
        swap_ms = (time.monotonic() - t0) * 1000.0
        self.param_staleness = stale_after
        return {"swapped": True, "param_generation": epoch,
                "param_staleness": stale_after,
                "swap_ms": float(swap_ms)}

    def warmup(self, buckets=None) -> float:
        """Trace the refresh program and every query-ladder bucket so
        steady-state traffic replays compiled code. Returns seconds."""
        t0 = time.monotonic()
        if self._logits is None:
            self.refresh()
        for b in (buckets or self.ladder):
            qp = np.full(b, -1, np.int32)
            ql = np.zeros(b, np.int32)
            np.asarray(self._query_prog(self._logits, qp, ql))
        # trace the topology-delta flush with an all-clear mask so the
        # first live delta replays compiled code (no-op on the values)
        m = jax.device_put(
            jnp.zeros((self.P, (self.P - 1) * self.sg.b_max, 1), bool),
            self.trainer._shard)
        self._halo0 = self._flush_prog(self._halo0, m)
        return time.monotonic() - t0

    # ---------------- freshness path ----------------------------------

    @property
    def staleness_age(self) -> int:
        return self._feat_lag

    @property
    def fully_fresh(self) -> bool:
        return self._feat_lag == 0

    def apply_updates(self, node_ids, values) -> int:
        """Patch owned-node features in place (donated scatter), mark
        the dirty-row bitmap, and invalidate layer-0 cache slots off
        the send-lists. Returns the number of halo slots invalidated."""
        if self.cfg.use_pp:
            raise ValueError(
                "feature updates are unsupported under use_pp: the "
                "precompute folds raw features into a trainer-side "
                "aggregate; serve with use_pp off (or rebuild the "
                "engine) to ingest updates")
        ids = np.atleast_1d(np.asarray(node_ids, np.int64))
        vals = np.atleast_2d(np.asarray(values, np.float32))
        if vals.shape != (ids.size, self.n_feat_raw):
            raise ValueError(
                f"values must be [{ids.size}, {self.n_feat_raw}], "
                f"got {vals.shape}")
        if ids.size and (ids.min() < 0
                         or ids.max() >= self.num_global_nodes):
            raise ValueError("node id out of range")
        wide = _pad_cols(vals, self.trainer._feat_pad)
        parts = self._q_part[ids]
        local = self._q_local[ids]
        touched = 0
        top = self.update_ladder[-1]
        for i0 in range(0, ids.size, top):
            sl = slice(i0, min(i0 + top, ids.size))
            n = sl.stop - sl.start
            b = bucket_for(n, self.update_ladder)
            up = np.full(b, -1, np.int32)
            ul = np.zeros(b, np.int32)
            uv = np.zeros((b, wide.shape[1]), np.float32)
            up[:n], ul[:n], uv[:n] = parts[sl], local[sl], wide[sl]
            self._feat = self._patch_prog(self._feat, up, ul, uv)
        self.freshness.mark(parts, local)
        touched = self.cache.invalidate_rows(parts, local)
        self._feat_lag += 1
        if touched:
            self._halo_lag += 1
        return touched

    def apply_graph_deltas(self, report) -> int:
        """Sync the engine with a topology delta the TRAINER just
        applied (Trainer.apply_graph_deltas -> PatchReport): re-bind
        the patched static inputs (send-lists, degrees, kernel tables),
        extend the query routing for new nodes, feed new-node features
        through the compiled patch ladder, zero the layer-0 cache slots
        whose send-list entry moved or vanished, and mark the moved /
        degree-changed rows dirty so the next refresh_boundary() merge
        is bit-identical to a full exchange. No retracing: every shape
        is unchanged (the patcher's slack guarantee). Returns the
        number of halo cache slots invalidated.

        A re-padded report means every compiled program's shapes grew;
        the engine cannot be patched and must be rebuilt."""
        if self.cfg.use_pp:
            raise ValueError(
                "topology deltas are unsupported under use_pp (the "
                "precomputed layer-0 aggregate bakes in the old "
                "topology); serve with use_pp off")
        if report.repadded:
            cache = getattr(self.trainer, "_serving_engines", None)
            if cache:
                cache.clear()
            raise RuntimeError(
                "graph delta re-padded the sharded graph: compiled "
                "serving shapes grew; rebuild the engine via "
                "ServingEngine.for_trainer")
        from ..stream.patch import flush_masks

        sg = self.trainer.sg
        self.sg = sg
        # the trainer re-uploaded every patched array + rebuilt kernel
        # tables; same shapes, so the compiled programs replay
        self._static = {k: v for k, v in self.trainer.data.items()
                        if k not in _NON_STATIC}
        # ---- host routing: new nodes become queryable -----------------
        nid = np.asarray(sg.global_nid)
        self.num_global_nodes = int((nid >= 0).sum())
        self._q_part = np.full(self.num_global_nodes, -1, np.int32)
        self._q_local = np.zeros(self.num_global_nodes, np.int32)
        for p in range(self.P):
            own = np.nonzero(nid[p] >= 0)[0]
            self._q_part[nid[p, own]] = p
            self._q_local[nid[p, own]] = own.astype(np.int32)
        # ---- new-node features -> private feature shard ---------------
        if report.new_rows is not None and report.new_rows.any():
            pp, rr = np.nonzero(report.new_rows)
            vals = np.asarray(sg.feat)[pp, rr].astype(np.float32)
            wide = _pad_cols(vals, self.trainer._feat_pad)
            top = self.update_ladder[-1]
            for i0 in range(0, pp.size, top):
                sl = slice(i0, min(i0 + top, pp.size))
                n = sl.stop - sl.start
                b = bucket_for(n, self.update_ladder)
                up = np.full(b, -1, np.int32)
                ul = np.zeros(b, np.int32)
                uv = np.zeros((b, wide.shape[1]), np.float32)
                up[:n], ul[:n] = pp[sl].astype(np.int32), \
                    rr[sl].astype(np.int32)
                uv[:n] = wide[sl]
                self._feat = self._patch_prog(self._feat, up, ul, uv)
        # ---- layer-0 cache: rebuild the ledger on the patched
        # send-lists, carrying over hit accounting and still-valid
        # stale bits (slot positions are unchanged where the entry is) -
        old = self.cache
        self.cache = Layer0Cache(sg.send_idx, sg.send_mask)
        self.cache.hits, self.cache.misses = old.hits, old.misses
        if old.stale.shape == self.cache.stale.shape:
            self.cache.stale[:] = old.stale
        touched = 0
        recv = None
        ch = report.changed_send
        if ch is not None and ch.any():
            recv, _ = flush_masks(ch, self.P, sg.b_max)
            # zero every changed receiver slot (device): removed
            # entries must read zero, moved entries are re-shipped by
            # the incremental refresh below
            m = jax.device_put(jnp.asarray(recv[:, :, None]),
                               self.trainer._shard)
            self._halo0 = self._flush_prog(self._halo0, m)
            self.cache.stale |= recv
            touched += int(recv.sum())
            # owner rows behind surviving changed entries: dirty, so
            # the next incremental exchange re-ships their values
            si = np.asarray(sg.send_idx)
            sel = ch & np.asarray(sg.send_mask).astype(bool)
            for p in range(self.P):
                rows = si[p][sel[p]]
                if rows.size:
                    self.freshness.mark(np.full(rows.size, p), rows)
        # ---- degree-changed + new rows: their send view changed (GCN
        # pre-scales by in_deg) or they are brand new — re-ship every
        # slot they feed ------------------------------------------------
        dirty_rows = np.zeros((self.P, self.n_max), bool)
        if report.deg_changed is not None:
            dirty_rows |= report.deg_changed
        if report.new_rows is not None:
            dirty_rows |= report.new_rows
        pp, rr = np.nonzero(dirty_rows)
        if pp.size:
            self.freshness.mark(pp, rr)
            touched += self.cache.invalidate_rows(pp, rr)
        # ---- staleness ledger -----------------------------------------
        self._feat_lag += 1
        if touched:
            self._halo_lag += 1
        self.topo_generation += 1
        return touched

    def refresh_boundary(self, ml=None) -> int:
        """Replay the send-list exchange for dirty rows only, merging
        fresh values into the resident halo cache (bit-identical to a
        full re-exchange — pinned by test). Returns slots refreshed.

        With the wire-integrity guard on (--integrity-check-every),
        a checksum mismatch on any dirty-row block discards the merge
        and rebuilds the whole halo from a full exchange — the
        recovery hammer — recording a contracted ``integrity`` event
        on `ml` when a metrics logger is supplied."""
        if not self.freshness.any:
            return 0
        n = self.cache.n_stale
        if self._wire_guard:
            new_halo, bad = self._inc_prog(
                self._feat, self._halo0, self.freshness.dirty,
                self._static)
            wb = int(bad)
            if wb:
                self.wire_bad_total += wb
                # the merged halo is suspect: rebuild from scratch
                new_halo = self.full_boundary_exchange()
                if ml is not None:
                    ml.integrity(epoch=self.topo_generation,
                                 check="wire", outcome="mismatch",
                                 target="halo", cadence=0,
                                 overhead_s=0.0, blocks=wb,
                                 detail="serving dirty-row exchange; "
                                        "halo rebuilt via full "
                                        "exchange")
            self._halo0 = new_halo
        else:
            self._halo0 = self._inc_prog(
                self._feat, self._halo0, self.freshness.dirty,
                self._static)
        self.freshness.clear()
        self.cache.mark_fresh()
        self._halo_lag = 0
        return n

    def full_boundary_exchange(self):
        """Rebuild the whole halo block from scratch (the reference the
        incremental path is pinned against; also the recovery hammer)."""
        return self._exchange_prog(self._feat, self._static)

    def refresh(self) -> None:
        """Recompute the full logits shard from the current features +
        halo cache. Served staleness collapses to the halo lag."""
        self._logits = self._refresh_prog(
            self._params, self._norm, self._feat, self._halo0,
            self._static)
        self._feat_lag = self._halo_lag

    # ---------------- query path --------------------------------------

    def query(self, node_ids, stats: Optional[ServingStats] = None
              ) -> np.ndarray:
        """Logits for global node ids, [n, n_class] f32. Pads to the
        ladder bucket; chunks above the top bucket."""
        ids = np.atleast_1d(np.asarray(node_ids, np.int64))
        if ids.size and (ids.min() < 0
                         or ids.max() >= self.num_global_nodes):
            raise ValueError("node id out of range")
        if self._logits is None:
            self.refresh()
        out = np.empty((ids.size, self.n_class), np.float32)
        top = self.ladder[-1]
        for i0 in range(0, ids.size, top):
            sl = slice(i0, min(i0 + top, ids.size))
            n = sl.stop - sl.start
            b = bucket_for(n, self.ladder)
            qp = np.full(b, -1, np.int32)
            ql = np.zeros(b, np.int32)
            qp[:n] = self._q_part[ids[sl]]
            ql[:n] = self._q_local[ids[sl]]
            out[sl] = np.asarray(
                self._query_prog(self._logits, qp, ql))[:n]
        hit = self.fully_fresh
        self.cache.record_queries(ids.size, hit)
        if stats is not None:
            stats.note_serve(ids.size, hit, self.staleness_age)
            stats.note_params(self.param_generation, self.param_staleness)
        return out

    def make_batcher(self, stats: Optional[ServingStats] = None,
                     max_delay_ms: float = 5.0,
                     clock=time.monotonic,
                     max_queue: Optional[int] = None,
                     ticket_deadline_ms: Optional[float] = None,
                     ladder=None) -> MicroBatcher:
        return MicroBatcher(
            run=lambda ids: self.query(ids, stats=stats),
            max_batch=self.ladder[-1], max_delay_ms=max_delay_ms,
            ladder_min=self.ladder[0], clock=clock,
            observer=stats.note_batch if stats is not None else None,
            max_queue=max_queue, ticket_deadline_ms=ticket_deadline_ms,
            on_shed=stats.note_shed if stats is not None else None,
            admission_ladder=ladder)
