"""Layer-0 boundary embedding cache bookkeeping.

The serving engine keeps the layer-0 halo block — the `[(P-1)*B, F]`
concatenation of peer boundary features produced by
`parallel.halo.exchange_blocks` — resident on device and feeds it to
the first exchanged layer of every inference pass instead of paying a
live ring exchange per query. This module is the host-side staleness
ledger for that cache: when a feature update dirties owned rows, the
same send-lists that route training-time halo traffic tell us exactly
which receiver-side cache slots now hold stale values.

Slot math (mirrors `exchange_blocks`): at ring distance d, partition p
sends `send_idx[p, d-1]` to receiver q = (p+d) % P, and the receiver
stores that block at slots [(d-1)*B, d*B) in sender order. So a dirty
owned row r on p invalidates slot (d-1)*B + k on q for every (d, k)
with send_mask[p, d-1, k] and send_idx[p, d-1, k] == r.
"""

from __future__ import annotations

import numpy as np


class Layer0Cache:
    """Host-side staleness bitmap + hit accounting for the device-
    resident layer-0 halo block. The actual values live on device in
    ServingEngine._halo0; this class only answers "which slots are
    stale" and "what fraction of queries were served fully fresh"."""

    def __init__(self, send_idx: np.ndarray, send_mask: np.ndarray):
        # send_idx/send_mask: [P, P-1, B] as built by ShardedGraph
        self.send_idx = np.asarray(send_idx)
        self.send_mask = np.asarray(send_mask).astype(bool)
        self.num_parts = int(self.send_idx.shape[0])
        self.b_max = int(self.send_idx.shape[2]) \
            if self.send_idx.ndim == 3 and self.send_idx.shape[1] else 0
        n_dist = max(self.num_parts - 1, 0)
        self.stale = np.zeros((self.num_parts, n_dist * self.b_max), bool)
        self.hits = 0
        self.misses = 0

    # ---------------- invalidation ------------------------------------

    def invalidate_rows(self, parts: np.ndarray, rows: np.ndarray) -> int:
        """Mark receiver-side slots stale for dirty owned rows
        (partition-local indices). Returns the number of slots touched
        by THIS call (stale-or-not before), i.e. > 0 iff any dirty row
        is on a send-list and the halo therefore needs a refresh."""
        parts = np.atleast_1d(np.asarray(parts))
        rows = np.atleast_1d(np.asarray(rows))
        touched = 0
        for p in np.unique(parts):
            local = rows[parts == p]
            for d in range(1, self.num_parts):
                q = (p + d) % self.num_parts
                sel = self.send_mask[p, d - 1] & np.isin(
                    self.send_idx[p, d - 1], local)
                k = np.nonzero(sel)[0]
                if k.size:
                    self.stale[q, (d - 1) * self.b_max + k] = True
                    touched += int(k.size)
        return touched

    def stale_slots(self, part: int) -> np.ndarray:
        """Stale slot indices into this receiver's halo block."""
        return np.nonzero(self.stale[part])[0]

    @property
    def n_stale(self) -> int:
        return int(self.stale.sum())

    def mark_fresh(self) -> None:
        """The incremental exchange just replayed every dirty row."""
        self.stale[:] = False

    # ---------------- hit accounting ----------------------------------

    def record_queries(self, n: int, hit: bool) -> None:
        if hit:
            self.hits += int(n)
        else:
            self.misses += int(n)

    @property
    def hit_rate(self):
        served = self.hits + self.misses
        return (self.hits / served) if served else None
