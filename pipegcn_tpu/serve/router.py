"""Fault-aware request router for the serving fleet.

Host-side and jax-free: the router knows nothing about meshes or
compiled programs — it dispatches already-batched node-id arrays to
abstract replica clients (TcpReplicaClient in production, fakes in
tests) and owns three policies:

  placement   least-queue (default): the up replica with the fewest
              in-flight rows, ties broken by replica id — keeps every
              mesh busy under open-loop load, which is what makes
              aggregate QPS scale near-linearly in N (bench.py
              --serve --replicas N).
              hash: consistent hashing on the batch's first node id
              over a virtual-node ring, so a given node's queries keep
              landing on the same replica (layer-0 cache locality) and
              a replica death only remaps ITS arc, not the whole
              keyspace.

  failover    a dispatch that errors marks the replica down, fires
              `on_fault(replica, reason)`, and retries the batch
              against survivors under an overall timeout with
              exponential backoff between attempts. Only when NO
              replica answers inside the timeout does the router give
              up (FleetUnavailable) — the caller then sheds the batch
              explicitly rather than losing it.

  rejoin      `mark_up` (driven by the fleet manager's health checks /
              heartbeat watcher) puts a recovered replica back into
              rotation; the hash ring and least-queue choice pick it
              up on the next dispatch.

Thread-safety: dispatch runs on the fleet's worker threads; membership
and in-flight bookkeeping are guarded by one lock, while the blocking
client call happens outside it.
"""

from __future__ import annotations

import bisect
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

POLICIES = ("least-queue", "hash")


class FleetUnavailable(RuntimeError):
    """No replica answered the batch inside the retry timeout."""


def _ring_point(token: str) -> int:
    return zlib.crc32(token.encode()) & 0xFFFFFFFF


class Router:
    """Dispatch batches over a set of replica clients with failover.

    `clients` maps replica id -> client; a client needs only
    ``query(ids) -> np.ndarray`` (raising on failure). Everything else
    — health, liveness, relaunch — is the fleet manager's job; it
    drives `mark_down` / `mark_up` from heartbeats, and dispatch
    errors mark down eagerly on their own."""

    def __init__(self, clients: Dict[int, object], *,
                 policy: str = "least-queue",
                 retry_timeout_s: float = 5.0,
                 backoff_s: float = 0.05,
                 backoff_mult: float = 2.0,
                 max_backoff_s: float = 1.0,
                 ring_points: int = 64,
                 on_fault: Optional[Callable[[int, str], None]] = None,
                 on_failover: Optional[Callable[[int, int, int],
                                                None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; one of "
                             f"{POLICIES}")
        if not clients:
            raise ValueError("router needs at least one replica client")
        self.policy = policy
        self.retry_timeout_s = float(retry_timeout_s)
        self.backoff_s = float(backoff_s)
        self.backoff_mult = float(backoff_mult)
        self.max_backoff_s = float(max_backoff_s)
        self._clients = dict(clients)
        self._clock = clock
        self._sleep = sleep
        self._on_fault = on_fault
        # fires when a batch SUCCEEDS on a survivor after >= 1 failed
        # attempt: on_failover(to_replica, n_rows, n_attempts)
        self._on_failover = on_failover
        self._lock = threading.Lock()
        self._up = {rid: True for rid in self._clients}
        self._inflight = {rid: 0 for rid in self._clients}
        self.n_dispatched = {rid: 0 for rid in self._clients}
        self.n_failovers = 0
        self.n_retried_rows = 0
        # virtual-node hash ring, sorted by point: each replica owns
        # `ring_points` arcs so load stays even and a death (or an
        # autoscale retire) remaps only that replica's arcs
        self._ring_points = int(ring_points)
        self._ring: List[Tuple[int, int]] = []
        # per-replica topology generation (note_topo_generation): a
        # replica behind the fleet max is stale and routed around
        self._topo_gens: Dict[int, int] = {}
        self._topo_stale: set = set()
        self._rebuild_ring()

    def _rebuild_ring(self) -> None:
        """Recompute the vnode ring from current membership. Caller
        holds the lock (or is __init__, pre-threading)."""
        ring: List[Tuple[int, int]] = []
        for rid in self._clients:
            for v in range(self._ring_points):
                ring.append((_ring_point(f"replica-{rid}-vnode-{v}"),
                             rid))
        ring.sort()
        self._ring = ring

    # ---------------- membership --------------------------------------

    def mark_down(self, rid: int, reason: str = "") -> bool:
        """Take a replica out of rotation; returns True on the DOWN
        edge (so callers emit exactly one fault record per death)."""
        with self._lock:
            if rid not in self._clients:  # already retired
                return False
            was_up = self._up.get(rid, False)
            self._up[rid] = False
        if was_up and self._on_fault is not None:
            self._on_fault(rid, reason)
        return was_up

    def mark_up(self, rid: int) -> bool:
        """Put a replica back into rotation (rejoin); returns True on
        the UP edge. A replica held out for topology skew stays routed
        out — only `note_topo_generation` reporting the fleet
        generation clears that hold (else the manager's health-probe
        heal path would route a stale graph back in)."""
        with self._lock:
            if rid not in self._clients:  # already retired
                return False
            if rid in self._topo_stale:
                return False
            was_down = not self._up.get(rid, False)
            self._up[rid] = True
        return was_down

    def note_topo_generation(self, rid: int, gen: int) -> Optional[bool]:
        """Cross-replica topology-skew detection (stream/journal.py):
        record the ``topo_generation`` a replica last reported (health
        response / query meta / readiness file). A replica BEHIND the
        fleet's maximum is serving a stale graph — it is routed around
        (mark_down, firing `on_fault` with a ``topo-skew:`` reason) and
        rejoins automatically once it reports the fleet generation
        again (journal replay on its restart path). Returns True on the
        skew DOWN edge, False on the catch-up UP edge, None when
        nothing changed."""
        rid, gen = int(rid), int(gen)
        with self._lock:
            if rid not in self._clients:
                return None
            self._topo_gens[rid] = gen
            fleet_gen = max(self._topo_gens.values())
            stale = gen < fleet_gen
            was_stale = rid in self._topo_stale
            if stale:
                self._topo_stale.add(rid)
            else:
                self._topo_stale.discard(rid)
        if stale and not was_stale:
            self.mark_down(
                rid, f"topo-skew:replica at generation {gen}, fleet "
                     f"at {fleet_gen}")
            return True
        if was_stale and not stale:
            self.mark_up(rid)
            return False
        return None

    def topo_generations(self) -> Dict[int, int]:
        """Last reported topo_generation per replica (skew surface)."""
        with self._lock:
            return dict(self._topo_gens)

    def has_replica(self, rid: int) -> bool:
        with self._lock:
            return rid in self._clients

    def add_replica(self, rid: int, client) -> None:
        """Fold a newly spawned replica into routing (autoscale
        scale-up / elastic rejoin of a never-seen id): registers the
        client, marks it up, and remaps the vnode ring — only the new
        replica's arcs move."""
        with self._lock:
            self._clients[int(rid)] = client
            self._up[int(rid)] = True
            self._inflight.setdefault(int(rid), 0)
            self.n_dispatched.setdefault(int(rid), 0)
            self._rebuild_ring()

    def remove_replica(self, rid: int) -> None:
        """Retire a replica from routing entirely (autoscale
        scale-down): no new batches land on it, its arcs remap to
        survivors. In-flight batches on worker threads finish
        normally — the client object stays valid until the fleet
        manager closes it AFTER this returns."""
        with self._lock:
            self._clients.pop(rid, None)
            self._up.pop(rid, None)
            self._inflight.pop(rid, None)
            self._topo_gens.pop(rid, None)
            self._topo_stale.discard(rid)
            self._rebuild_ring()

    def is_up(self, rid: int) -> bool:
        with self._lock:
            return self._up.get(rid, False)

    def up_replicas(self) -> List[int]:
        with self._lock:
            return sorted(r for r, u in self._up.items() if u)

    def queue_depths(self) -> Dict[int, int]:
        """In-flight rows per replica (the least-queue signal)."""
        with self._lock:
            return dict(self._inflight)

    # ---------------- placement ---------------------------------------

    def _hash_pick(self, key: int, excluded: set) -> Optional[int]:
        point = _ring_point(f"key-{int(key)}")
        n = len(self._ring)
        i = bisect.bisect_left(self._ring, (point, -1))
        for step in range(n):
            _, rid = self._ring[(i + step) % n]
            if self._up.get(rid, False) and rid not in excluded:
                return rid
        return None

    def _pick(self, ids: np.ndarray, excluded: set) -> Optional[int]:
        with self._lock:
            if self.policy == "hash" and ids.size:
                return self._hash_pick(int(ids[0]), excluded)
            best, best_depth = None, None
            for rid in sorted(self._clients):
                if not self._up.get(rid, False) or rid in excluded:
                    continue
                d = self._inflight[rid]
                if best_depth is None or d < best_depth:
                    best, best_depth = rid, d
            return best

    # ---------------- dispatch ----------------------------------------

    def dispatch(self, ids: np.ndarray,
                 trace=None) -> Tuple[np.ndarray, int]:
        """Send one batch; returns (logits, replica id that served it).
        `trace` (optional) is a list of sampled trace ids riding this
        batch; it is forwarded to the client only when set, so fake
        clients with a bare ``query(ids)`` signature keep working at
        the default sample rate 0.

        On a replica error: mark it down, back off exponentially, and
        retry against survivors until `retry_timeout_s` elapses (the
        first attempt always runs). Raises FleetUnavailable when the
        whole fleet is down or the timeout expires."""
        deadline = self._clock() + self.retry_timeout_s
        delay = self.backoff_s
        excluded: set = set()
        attempt = 0
        last_err = "no replica available"
        while attempt == 0 or self._clock() < deadline:
            attempt += 1
            rid = self._pick(ids, excluded)
            if rid is None:
                # every non-excluded replica is down; if some replica
                # is still up but excluded (it already failed THIS
                # batch), give it another chance after the backoff —
                # it may have been a transient error
                if not self.up_replicas():
                    raise FleetUnavailable(
                        f"no up replicas (last error: {last_err})")
                excluded.clear()
                self._sleep(delay)
                delay = min(delay * self.backoff_mult,
                            self.max_backoff_s)
                continue
            with self._lock:
                # a concurrent remove_replica may have retired rid
                # between _pick and here: treat it like a miss
                client = self._clients.get(rid)
                if client is None:
                    continue
                self._inflight[rid] = (self._inflight.get(rid, 0)
                                       + int(ids.size))
            try:
                if trace:
                    out = client.query(ids, trace=trace)
                else:
                    out = client.query(ids)
            except Exception as exc:  # noqa: BLE001 — any client error
                last_err = f"{type(exc).__name__}: {exc}"
                excluded.add(rid)
                self.mark_down(rid, last_err)
                self.n_failovers += 1
                self.n_retried_rows += int(ids.size)
                self._sleep(delay)
                delay = min(delay * self.backoff_mult,
                            self.max_backoff_s)
                continue
            finally:
                with self._lock:
                    if rid in self._inflight:
                        self._inflight[rid] -= int(ids.size)
            self.n_dispatched[rid] = (self.n_dispatched.get(rid, 0)
                                      + int(ids.size))
            if attempt > 1 and self._on_failover is not None:
                self._on_failover(rid, int(ids.size), attempt)
            # the client's result is opaque to the router: a plain
            # ndarray (fakes) or (ndarray, meta) (TcpReplicaClient)
            return out, rid
        raise FleetUnavailable(
            f"retry timeout after {attempt} attempts "
            f"(last error: {last_err})")
