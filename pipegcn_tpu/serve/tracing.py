"""Sampled per-query tracing for the serving path (schema v10 `span`
records, docs/OBSERVABILITY.md "Live monitoring").

A trace id is minted at submit time with probability
``--trace-sample-rate`` and rides the :class:`~.batcher.Ticket`
through every hop — micro-batcher queue/dispatch on the driver, the
router RPC, the replica handler, and the engine's chunked execution —
each hop landing one contracted `span` record in that process's
metrics stream. ``cli.timeline`` stitches spans sharing a trace id
into Perfetto flow events.

Everything here is host-side bookkeeping: no jax, no effect on the
compiled programs (the no-recompile pin in tests/test_serve.py holds
with sampling at 100%). At the default rate 0 no ids are minted and
the per-submit cost is one comparison.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from typing import Callable, Optional


class TraceSampler:
    """Deterministic Bernoulli sampler minting trace ids at submit.

    ``rate`` 0 (the default everywhere) never mints; 1 always mints;
    in between a seeded PRNG decides, so a replayed load run samples
    the same queries. Ids are ``q<seq>-<run tag>`` — unique within a
    run and readable in raw JSONL."""

    def __init__(self, rate: float = 0.0, seed: int = 0,
                 tag: str = "t"):
        self.rate = float(rate)
        self.tag = str(tag)
        self._rng = random.Random(seed)
        self._seq = 0
        self.n_sampled = 0

    def sample(self) -> Optional[str]:
        """One submit's verdict: a fresh trace id, or None."""
        if self.rate <= 0.0:
            return None
        self._seq += 1
        if self.rate < 1.0 and self._rng.random() >= self.rate:
            return None
        self.n_sampled += 1
        return f"q{self._seq}-{self.tag}"


class SpanWriter:
    """Bridge from loop-clock span callbacks to contracted records.

    The serving loops and the batcher run on an injectable monotonic
    (or fake) clock; span records need cross-process-alignable unix
    t_start. The writer captures the clock->unix offset once per emit
    so fake-clock tests stay deterministic in shape while real runs
    stay alignable. Thread-safe (the fleet loop emits from worker
    threads)."""

    def __init__(self, ml, clock: Callable[[], float] = time.monotonic,
                 source: str = "", now: Callable[[], float] = time.time):
        self._ml = ml
        self._clock = clock
        self._now = now
        self.source = str(source)
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self.n_spans = 0

    def emit(self, trace_id: Optional[str], op: str, t0: float,
             t1: float, status: str = "ok", **extra) -> None:
        """One span: [t0, t1] in the loop clock's frame. No-op when
        the ticket was unsampled (trace_id None) or there is no sink."""
        if trace_id is None or self._ml is None:
            return
        off = self._now() - self._clock()
        with self._lock:
            sid = f"s{next(self._ids)}"
            self.n_spans += 1
        if self.source:
            extra.setdefault("source", self.source)
        self._ml.span(trace_id, sid, op, t0 + off,
                      max(t1 - t0, 0.0) * 1e3, status, **extra)
