"""Online serving runtime (docs/SERVING.md).

Compiled-once sharded inference over the partitioned graph, with
micro-batched queries, incremental halo freshness, bounded-queue load
shedding, and schema-v7 `serving`/`fleet` observability. Entry points:
`python -m pipegcn_tpu.cli.serve` (single mesh) and
`python -m pipegcn_tpu.cli.fleet` (N-replica fleet with failover
routing and zero-downtime checkpoint hot-swap).

The fleet/router modules are imported lazily by their entrypoints (the
router is jax-free; the fleet module pulls in resilience machinery) —
import them as `pipegcn_tpu.serve.router` / `pipegcn_tpu.serve.fleet`.
"""

from .batcher import (MicroBatcher, ServingStats, Ticket,  # noqa: F401
                      bucket_for, bucket_ladder)
from .cache import Layer0Cache  # noqa: F401
from .engine import ServingEngine, TRACE_COUNTS, trace_counts  # noqa: F401
from .freshness import FreshnessTracker, dirty_exchange_blocks  # noqa: F401
from .loadgen import OpenLoopGenerator, run_serving_loop  # noqa: F401
from .router import FleetUnavailable, Router  # noqa: F401
