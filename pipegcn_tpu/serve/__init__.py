"""Online serving runtime (docs/SERVING.md).

Compiled-once sharded inference over the partitioned graph, with
micro-batched queries, incremental halo freshness, and schema-v5
`serving` observability. Entry point: `python -m pipegcn_tpu.cli.serve`.
"""

from .batcher import (MicroBatcher, ServingStats, Ticket,  # noqa: F401
                      bucket_for, bucket_ladder)
from .cache import Layer0Cache  # noqa: F401
from .engine import ServingEngine, TRACE_COUNTS, trace_counts  # noqa: F401
from .freshness import FreshnessTracker, dirty_exchange_blocks  # noqa: F401
from .loadgen import OpenLoopGenerator, run_serving_loop  # noqa: F401
