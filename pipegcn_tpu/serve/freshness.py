"""Incremental halo freshness: the staleness-1 carry repurposed.

Training overlaps boundary communication with compute by consuming a
one-step-stale halo carry. Serving flips the same machinery into a
bounded-staleness freshness mechanism: feature updates patch the owned
feature shard in place, a per-partition dirty-row bitmap records which
rows changed, and `dirty_exchange_blocks` replays the send-list ring
exchange for ONLY the dirty rows — merging the fresh values into the
resident layer-0 halo cache and leaving clean slots byte-for-byte
untouched. The result is pinned bit-identical to a full re-exchange
(tests/test_serve.py::test_incremental_freshness_bit_identical).

Transport note: the incremental exchange always ships uncompressed
rows (no `halo_transport_dtypes` narrowing) — exactness against the
full exchange is the contract here, and dirty-row volume is tiny.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.halo import _fwd_perm, _permute_compressed


class FreshnessTracker:
    """Host-side dirty-row bitmap, one bool per (partition, local row).
    Marked by ServingEngine.apply_updates, consumed (as the mask fed to
    `dirty_exchange_blocks`) and cleared by refresh_boundary."""

    def __init__(self, num_parts: int, n_max: int):
        self.dirty = np.zeros((num_parts, n_max), bool)

    def mark(self, parts: np.ndarray, rows: np.ndarray) -> None:
        self.dirty[np.asarray(parts), np.asarray(rows)] = True

    @property
    def any(self) -> bool:
        return bool(self.dirty.any())

    def counts(self) -> np.ndarray:
        """Dirty rows per partition (observability)."""
        return self.dirty.sum(axis=1)

    def clear(self) -> None:
        self.dirty[:] = False


def dirty_exchange_blocks(h, halo, dirty, send_idx, send_mask,
                          axis_name: str, num_parts: int,
                          guard: bool = False):
    """Inside-shard_map: re-exchange only dirty send-list rows and
    merge them into the resident halo block `halo` ([(P-1)*B, F]).

    Bit-identity argument vs `exchange_blocks`: a dirty, masked row
    takes the identical take→where→ppermute path (same dtype, no
    transport compression), so its merged value equals the full
    exchange's; a clean masked row keeps its prior exact value; a
    masked-off slot was zero at init and its dirty bit never fires.

    guard=True rides the same wire-integrity checksum lane as the
    training exchange (parallel/halo.py): each distance block — the
    row payload AND its dirty-bit lane — ships its sender-side
    checksum through the SAME permutation and the return becomes
    ``(merged, bad)`` with ``bad`` an int32 count of mismatching
    blocks on this shard. guard=False compiles the byte-identical
    program this module always built.
    """
    if num_parts == 1:
        return (halo, jnp.zeros((), jnp.int32)) if guard else halo
    rows_out, bits_out = [], []
    bad = jnp.zeros((), jnp.int32)
    for d in range(1, num_parts):
        idx = send_idx[d - 1]
        blk = jnp.take(h, idx, axis=0, mode="clip")
        bit = jnp.take(dirty, idx, axis=0, mode="clip") & send_mask[d - 1]
        blk = jnp.where(bit[:, None], blk, jnp.zeros((), blk.dtype))
        perm = _fwd_perm(num_parts, d)
        # bool collectives are flaky across backends; ship the bit as u8
        bit8 = bit.astype(jnp.uint8)
        if guard:
            blk, b0 = _permute_compressed(blk, axis_name, perm, None,
                                          guard=True)
            bit8, b1 = _permute_compressed(bit8, axis_name, perm, None,
                                           guard=True)
            bad = bad + b0 + b1
        else:
            blk = jax.lax.ppermute(blk, axis_name, perm)
            bit8 = jax.lax.ppermute(bit8, axis_name, perm)
        rows_out.append(blk)
        bits_out.append(bit8 != 0)
    fresh = jnp.concatenate(rows_out, axis=0)
    bits = jnp.concatenate(bits_out, axis=0)
    merged = jnp.where(bits[:, None], fresh.astype(halo.dtype), halo)
    return (merged, bad) if guard else merged
