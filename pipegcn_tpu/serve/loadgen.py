"""Synthetic open-loop load generator + the shared serving loop.

Open-loop means arrival times are fixed up front (Poisson process at
the target QPS) and do NOT adapt to service time — the honest way to
measure a serving system, since closed-loop generators hide overload
by slowing down with the server (coordinated omission). `bench.py
--serve` and `python -m pipegcn_tpu.cli.serve` both drive the same
`run_serving_loop`, which owns the report / freshness-refresh / update-
churn cadences and emits schema-v5 `serving` records.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from .batcher import ServingStats
from .tracing import SpanWriter, TraceSampler


class OpenLoopGenerator:
    """Deterministic (seeded) Poisson arrival schedule over random
    node-id queries, with each query carrying `ids_per_query` ids."""

    def __init__(self, num_nodes: int, qps: float, duration_s: float,
                 ids_per_query: int = 1, seed: int = 0):
        rng = np.random.default_rng(seed)
        n = max(1, int(round(qps * duration_s)))
        gaps = rng.exponential(1.0 / max(qps, 1e-9), n)
        self.arrivals = np.minimum(np.cumsum(gaps), duration_s)
        self.queries = rng.integers(0, num_nodes, (n, ids_per_query),
                                    dtype=np.int64)
        self.duration_s = float(duration_s)

    def __len__(self) -> int:
        return len(self.arrivals)


def run_serving_loop(engine, *, duration_s: float, qps: float,
                     max_delay_ms: float = 5.0,
                     ids_per_query: int = 1,
                     report_every_s: float = 2.0,
                     refresh_every_s: float = 0.5,
                     update_every_s: float = 0.0,
                     update_rows: int = 32,
                     seed: int = 0,
                     ml=None,
                     max_queue: Optional[int] = None,
                     ticket_deadline_ms: Optional[float] = None,
                     trace_sample_rate: float = 0.0,
                     stop: Optional[Callable[[], bool]] = None,
                     clock: Callable[[], float] = time.monotonic,
                     sleep: Callable[[float], None] = time.sleep) -> dict:
    """Drive the engine under open-loop load; returns an aggregate
    summary dict (qps, p50/p95/p99_ms, batch_fill, cache_hit_rate,
    staleness_age_max, n_queries, n_records, drained).

    Cadences: every `report_every_s` a `serving` record goes to `ml`
    (a MetricsLogger, optional); every `refresh_every_s` the engine
    recomputes logits (picking up applied updates); every
    `update_every_s` (0 disables, forced off under use_pp) a synthetic
    churn batch of `update_rows` random features is applied and the
    dirty boundary rows incrementally re-exchanged.

    `stop()` (optional) is polled between arrivals — the SIGTERM path:
    on stop the loop drains the queue, emits a final record (extra
    field `final: true`), and returns. Every accepted query is
    answered before the function returns.

    Overload protection (docs/SERVING.md "Load shedding"): `max_queue`
    bounds the queued row count (over-bound submits are shed with
    reason queue-full), `ticket_deadline_ms` sheds tickets that waited
    past the deadline at flush time. Shed counts land in each serving
    record (`shed`) and the summary (`n_shed`)."""
    stats = ServingStats(clock)
    all_lat: list = []
    fills: list = []

    def observer(bucket, n_valid, lats):
        stats.note_batch(bucket, n_valid, lats)
        all_lat.extend(lats)
        fills.append(n_valid / bucket)

    batcher = engine.make_batcher(stats=stats,
                                  max_delay_ms=max_delay_ms, clock=clock,
                                  max_queue=max_queue,
                                  ticket_deadline_ms=ticket_deadline_ms)
    batcher._observer = observer
    # sampled per-query tracing (serve/tracing.py): off at rate 0; all
    # host-side, so the compiled-program population is untouched (the
    # trace_counts() pin in tests/test_serve.py holds at rate 1.0)
    sampler = TraceSampler(trace_sample_rate, seed=seed, tag="serve")
    spans = SpanWriter(ml if trace_sample_rate > 0 else None,
                       clock=clock, source="serve")
    batcher._on_span = spans.emit
    gen = OpenLoopGenerator(engine.num_global_nodes, qps, duration_s,
                            ids_per_query=ids_per_query, seed=seed)
    churn = np.random.default_rng(seed + 1)
    do_updates = update_every_s > 0 and not engine.cfg.use_pp

    t0 = clock()
    next_report = t0 + report_every_s
    next_refresh = t0 + refresh_every_s
    next_update = t0 + update_every_s if do_updates else float("inf")
    n_records = 0
    total_q = 0
    stale_max = 0
    hits = misses = 0
    total_shed = 0

    def emit(now, final=False):
        nonlocal n_records, total_q, stale_max, hits, misses, total_shed
        h, m = stats.hits, stats.misses
        rec = stats.snapshot(queue_depth=batcher.queue_depth)
        total_q += rec["queries"]
        stale_max = max(stale_max, rec["staleness_age"])
        hits += h
        misses += m
        total_shed += rec["shed"]
        if ml is not None:
            extra = {"final": True} if final else {}
            ml.serving(**rec, **extra)
        n_records += 1

    def tick(now):
        nonlocal next_report, next_refresh, next_update
        if do_updates and now >= next_update:
            ids = churn.integers(0, engine.num_global_nodes,
                                 update_rows, dtype=np.int64)
            vals = churn.standard_normal(
                (update_rows, engine.n_feat_raw)).astype(np.float32)
            engine.apply_updates(ids, vals)
            engine.refresh_boundary()
            next_update = now + update_every_s
        if now >= next_refresh:
            engine.refresh()
            next_refresh = now + refresh_every_s
        if now >= next_report:
            emit(now)
            next_report = now + report_every_s

    stopped = False
    for t_arr, q in zip(gen.arrivals, gen.queries):
        if stop is not None and stop():
            stopped = True
            break
        target = t0 + t_arr
        while True:
            now = clock()
            if now >= target:
                break
            batcher.pump(now)
            tick(now)
            if stop is not None and stop():
                stopped = True
                break
            sleep(min(target - now, 0.0005))
        if stopped:
            break
        batcher.submit(q, trace_id=sampler.sample())
        now = clock()
        batcher.pump(now)
        tick(now)

    # shutdown: answer everything accepted, then the final record —
    # written through MetricsLogger.serving's hard_flush so it survives
    # an unclean exit right after (the chaos drill's assertion)
    batcher.drain()
    emit(clock(), final=True)

    lat = np.asarray(all_lat, np.float64) * 1000.0
    dt = max(clock() - t0, 1e-9)
    served = hits + misses
    return {
        "qps": float(total_q / dt),
        "n_queries": int(total_q),
        "duration_s": float(dt),
        "p50_ms": float(np.percentile(lat, 50)) if lat.size else None,
        "p95_ms": float(np.percentile(lat, 95)) if lat.size else None,
        "p99_ms": float(np.percentile(lat, 99)) if lat.size else None,
        "batch_fill": float(np.mean(fills)) if fills else None,
        "cache_hit_rate": (float(hits / served) if served else None),
        "staleness_age_max": int(stale_max),
        "n_records": int(n_records),
        "drained": batcher.queue_depth == 0,
        "stopped_early": bool(stopped),
        "n_shed": int(total_shed),
        "n_traced": int(sampler.n_sampled),
        "n_spans": int(spans.n_spans),
        "n_submitted": int(batcher.n_submitted_rows),
        "n_served": int(batcher.n_served_rows),
        # zero tickets silently lost: submitted == served + shed once
        # the queue is drained (the kill drill pins this)
        "conserved": bool(
            batcher.n_submitted_rows
            == batcher.n_served_rows + batcher.n_shed_rows
            + batcher.queue_depth),
        "param_generation": int(stats.param_generation),
        "param_staleness": int(stats.param_staleness),
    }
