"""Synthetic open-loop load generator + the shared serving loop.

Open-loop means arrival times are fixed up front and do NOT adapt to
service time — the honest way to measure a serving system, since
closed-loop generators hide overload by slowing down with the server
(coordinated omission). `bench.py --serve` and
`python -m pipegcn_tpu.cli.serve` both drive the same
`run_serving_loop`, which owns the report / freshness-refresh / update-
churn cadences and emits schema-v5 `serving` records.

Traffic shapes (``--traffic``, docs/SERVING.md "Autoscaling &
overload"): the arrival process is a non-homogeneous Poisson process
against a rate function λ(t), realized by Lewis-Shedler THINNING —
draw a homogeneous process at the peak rate, keep each arrival t with
probability λ(t)/λ_peak. The schedule stays fixed up front (no
coordinated omission) and is a pure function of the seed, so shaped
episodes replay bitwise under the soak harness. Rescaling a constant-
rate stream would get the mean right but the burst statistics wrong —
thinning is the correct construction. Shapes:

  constant                        homogeneous Poisson at --serve-qps
                                  (the legacy stream, bit-identical to
                                  pre-shape seeds)
  diurnal[:<period_s>[:<floor>]]  sinusoid between floor*qps and qps
                                  (trough at t=0, peak at period/2);
                                  default period = duration, floor 0.25
  flash-crowd[:<mult>[:<t0>[:<t1>]]]
                                  base qps outside, qps*mult inside the
                                  [t0*T, t1*T) crowd window (defaults
                                  mult 4, t0 0.4, t1 0.7) — the step
                                  overload the autoscaler must absorb
  trace:<path>                    replay a recorded rate trace: a JSON
                                  list of [t_seconds, qps] breakpoints,
                                  piecewise-constant, last value held

A mixed update/query workload rides the same arrival stream: with
``update_fraction`` > 0 each arrival is independently (seeded) marked
as a feature-update instead of a query — updates churn the graph, they
never enter the ticket ledger, so conservation stays a statement about
queries alone.
"""

from __future__ import annotations

import json
import math
import time
from typing import Callable, List, Optional

import numpy as np

from .batcher import ServingStats
from .tracing import SpanWriter, TraceSampler

TRAFFIC_SHAPES = ("constant", "diurnal", "flash-crowd", "trace")


class RateShape:
    """A rate function λ(t) over [0, duration_s] with a known peak —
    everything thinning needs. Construct via :meth:`parse` from a
    ``--traffic`` spec string; `qps` is the PEAK rate for the shaped
    kinds (diurnal/flash-crowd scale relative to it)."""

    def __init__(self, kind: str, qps: float, duration_s: float, *,
                 period_s: Optional[float] = None, floor: float = 0.25,
                 mult: float = 4.0, t0_frac: float = 0.4,
                 t1_frac: float = 0.7,
                 points: Optional[List[List[float]]] = None):
        if kind not in TRAFFIC_SHAPES:
            raise ValueError(f"unknown traffic shape {kind!r}; one of "
                             f"{TRAFFIC_SHAPES}")
        self.kind = kind
        self.qps = float(qps)
        self.duration_s = float(duration_s)
        self.period_s = float(period_s if period_s else duration_s)
        self.floor = float(floor)
        self.mult = float(mult)
        self.t0_frac = float(t0_frac)
        self.t1_frac = float(t1_frac)
        if kind == "diurnal" and not 0.0 <= self.floor <= 1.0:
            raise ValueError(f"diurnal floor {self.floor} not in [0, 1]")
        if kind == "flash-crowd" and not (
                0.0 <= self.t0_frac < self.t1_frac <= 1.0):
            raise ValueError(
                f"flash-crowd window [{self.t0_frac}, {self.t1_frac}) "
                f"must satisfy 0 <= t0 < t1 <= 1")
        if kind == "trace":
            if not points:
                raise ValueError("trace shape needs [t, qps] points")
            pts = sorted((float(t), float(q)) for t, q in points)
            if any(q < 0 for _, q in pts):
                raise ValueError("trace rates must be >= 0")
            self._trace_t = np.asarray([t for t, _ in pts], np.float64)
            self._trace_q = np.asarray([q for _, q in pts], np.float64)
        else:
            self._trace_t = self._trace_q = None

    @classmethod
    def parse(cls, spec: Optional[str], qps: float,
              duration_s: float) -> "RateShape":
        """``--traffic`` grammar: ``constant`` |
        ``diurnal[:<period_s>[:<floor>]]`` |
        ``flash-crowd[:<mult>[:<t0_frac>[:<t1_frac>]]]`` |
        ``trace:<path>``. None/empty means constant."""
        spec = (spec or "constant").strip()
        if spec.startswith("trace:"):
            path = spec[len("trace:"):]
            with open(path, encoding="utf-8") as f:
                points = json.load(f)
            return cls("trace", qps, duration_s, points=points)
        parts = spec.split(":")
        kind, args = parts[0], parts[1:]
        if kind not in TRAFFIC_SHAPES or kind == "trace":
            raise ValueError(
                f"bad --traffic spec {spec!r}: expected constant | "
                f"diurnal[:period[:floor]] | "
                f"flash-crowd[:mult[:t0[:t1]]] | trace:<path>")
        try:
            nums = [float(a) for a in args]
        except ValueError as exc:
            raise ValueError(f"bad --traffic spec {spec!r}: non-numeric "
                             f"argument") from exc
        kw = {}
        if kind == "diurnal":
            if len(nums) > 2:
                raise ValueError(f"bad --traffic spec {spec!r}: diurnal "
                                 f"takes at most period,floor")
            if nums:
                kw["period_s"] = nums[0]
            if len(nums) > 1:
                kw["floor"] = nums[1]
        elif kind == "flash-crowd":
            if len(nums) > 3:
                raise ValueError(f"bad --traffic spec {spec!r}: "
                                 f"flash-crowd takes at most mult,t0,t1")
            for key, v in zip(("mult", "t0_frac", "t1_frac"), nums):
                kw[key] = v
        elif nums:
            raise ValueError(f"bad --traffic spec {spec!r}: constant "
                             f"takes no arguments")
        return cls(kind, qps, duration_s, **kw)

    # ---------------- the rate function --------------------------------

    def rate(self, t: np.ndarray) -> np.ndarray:
        """λ(t), vectorized (accepts scalars or arrays)."""
        t = np.asarray(t, np.float64)
        if self.kind == "constant":
            return np.full_like(t, self.qps)
        if self.kind == "diurnal":
            # trough floor*qps at t=0, peak qps at period/2
            lo = self.floor * self.qps
            amp = (self.qps - lo) * 0.5
            return lo + amp * (1.0 - np.cos(
                2.0 * math.pi * t / self.period_s))
        if self.kind == "flash-crowd":
            t0 = self.t0_frac * self.duration_s
            t1 = self.t1_frac * self.duration_s
            return np.where((t >= t0) & (t < t1),
                            self.qps * self.mult, self.qps)
        idx = np.clip(np.searchsorted(self._trace_t, t, side="right")
                      - 1, 0, len(self._trace_q) - 1)
        return self._trace_q[idx]

    @property
    def peak(self) -> float:
        """λ_peak — the thinning envelope."""
        if self.kind == "flash-crowd":
            return self.qps * self.mult
        if self.kind == "trace":
            return float(self._trace_q.max()) if len(self._trace_q) \
                else 0.0
        return self.qps

    def crowd_window(self):
        """(t0, t1) seconds of the flash-crowd step (None otherwise) —
        the soak harness schedules its mid-crowd net-partition off
        this."""
        if self.kind != "flash-crowd":
            return None
        return (self.t0_frac * self.duration_s,
                self.t1_frac * self.duration_s)


def thinned_arrivals(shape: RateShape, duration_s: float,
                     rng: np.random.Generator) -> np.ndarray:
    """Non-homogeneous Poisson arrivals over [0, duration_s] by
    Lewis-Shedler thinning: homogeneous candidates at λ_peak, each
    kept with probability λ(t)/λ_peak. Deterministic for a given rng
    state; sorted ascending."""
    lam = shape.peak
    if lam <= 0 or duration_s <= 0:
        return np.zeros(0, np.float64)
    out: List[np.ndarray] = []
    t = 0.0
    # chunked draw so a long/low-rate schedule never loops per-arrival
    chunk = max(64, int(lam * duration_s * 0.25) + 16)
    while t < duration_s:
        gaps = rng.exponential(1.0 / lam, chunk)
        cand = t + np.cumsum(gaps)
        keep = rng.random(chunk) * lam < shape.rate(cand)
        out.append(cand[keep & (cand < duration_s)])
        t = float(cand[-1])
    arr = np.concatenate(out) if out else np.zeros(0, np.float64)
    return arr[arr < duration_s]


class OpenLoopGenerator:
    """Deterministic (seeded) arrival schedule over random node-id
    queries, each carrying `ids_per_query` ids.

    With ``traffic`` unset/constant the stream is the legacy
    homogeneous Poisson draw (bit-identical to pre-shape seeds); a
    shaped spec switches arrival generation to thinning against the
    shape's λ(t). ``update_fraction`` > 0 marks arrivals as feature
    updates (`is_update`); the draw happens only when the fraction is
    non-zero so the zero-fraction bitstream is unchanged."""

    def __init__(self, num_nodes: int, qps: float, duration_s: float,
                 ids_per_query: int = 1, seed: int = 0,
                 traffic=None, update_fraction: float = 0.0):
        rng = np.random.default_rng(seed)
        shape = (traffic if isinstance(traffic, RateShape)
                 else RateShape.parse(traffic, qps, duration_s))
        self.shape = shape
        if shape.kind == "constant":
            n = max(1, int(round(qps * duration_s)))
            gaps = rng.exponential(1.0 / max(qps, 1e-9), n)
            self.arrivals = np.minimum(np.cumsum(gaps), duration_s)
        else:
            self.arrivals = thinned_arrivals(shape, duration_s, rng)
        n = len(self.arrivals)
        self.queries = rng.integers(0, num_nodes,
                                    (max(n, 1), ids_per_query),
                                    dtype=np.int64)[:n]
        if update_fraction > 0:
            self.is_update = rng.random(n) < float(update_fraction)
        else:
            self.is_update = np.zeros(n, bool)
        self.update_fraction = float(update_fraction)
        self.duration_s = float(duration_s)

    def __len__(self) -> int:
        return len(self.arrivals)


def run_serving_loop(engine, *, duration_s: float, qps: float,
                     max_delay_ms: float = 5.0,
                     ids_per_query: int = 1,
                     report_every_s: float = 2.0,
                     refresh_every_s: float = 0.5,
                     update_every_s: float = 0.0,
                     update_rows: int = 32,
                     seed: int = 0,
                     ml=None,
                     max_queue: Optional[int] = None,
                     ticket_deadline_ms: Optional[float] = None,
                     trace_sample_rate: float = 0.0,
                     traffic: Optional[str] = None,
                     update_fraction: float = 0.0,
                     ladder=None,
                     stop: Optional[Callable[[], bool]] = None,
                     clock: Callable[[], float] = time.monotonic,
                     sleep: Callable[[float], None] = time.sleep) -> dict:
    """Drive the engine under open-loop load; returns an aggregate
    summary dict (qps, p50/p95/p99_ms, batch_fill, cache_hit_rate,
    staleness_age_max, n_queries, n_records, drained).

    Cadences: every `report_every_s` a `serving` record goes to `ml`
    (a MetricsLogger, optional); every `refresh_every_s` the engine
    recomputes logits (picking up applied updates); every
    `update_every_s` (0 disables, forced off under use_pp) a synthetic
    churn batch of `update_rows` random features is applied and the
    dirty boundary rows incrementally re-exchanged.

    `stop()` (optional) is polled between arrivals — the SIGTERM path:
    on stop the loop drains the queue, emits a final record (extra
    field `final: true`), and returns. Every accepted query is
    answered before the function returns.

    Overload protection (docs/SERVING.md "Load shedding"): `max_queue`
    bounds the queued row count (over-bound submits are shed with
    reason queue-full), `ticket_deadline_ms` sheds tickets that waited
    past the deadline at flush time, and `ladder` (an AdmissionLadder)
    tightens both adaptively as queue pressure rises — brownout before
    blackout. Shed counts land in each serving record (`shed`) and the
    summary (`n_shed`).

    Traffic realism: `traffic` is a ``--traffic`` shape spec (module
    docstring); `update_fraction` turns that share of arrivals into
    feature-update churn instead of queries (inert under use_pp, like
    the timer-driven churn)."""
    stats = ServingStats(clock)
    all_lat: list = []
    fills: list = []

    def observer(bucket, n_valid, lats):
        stats.note_batch(bucket, n_valid, lats)
        all_lat.extend(lats)
        fills.append(n_valid / bucket)

    batcher = engine.make_batcher(stats=stats,
                                  max_delay_ms=max_delay_ms, clock=clock,
                                  max_queue=max_queue,
                                  ticket_deadline_ms=ticket_deadline_ms,
                                  ladder=ladder)
    batcher._observer = observer
    # sampled per-query tracing (serve/tracing.py): off at rate 0; all
    # host-side, so the compiled-program population is untouched (the
    # trace_counts() pin in tests/test_serve.py holds at rate 1.0)
    sampler = TraceSampler(trace_sample_rate, seed=seed, tag="serve")
    spans = SpanWriter(ml if trace_sample_rate > 0 else None,
                       clock=clock, source="serve")
    batcher._on_span = spans.emit
    gen = OpenLoopGenerator(engine.num_global_nodes, qps, duration_s,
                            ids_per_query=ids_per_query, seed=seed,
                            traffic=traffic,
                            update_fraction=update_fraction)
    churn = np.random.default_rng(seed + 1)
    do_updates = update_every_s > 0 and not engine.cfg.use_pp
    # update-arrival churn (mixed workload): same inertness rule as
    # the timer path — the pipelined engine owns no update seam
    do_arrival_updates = (gen.update_fraction > 0
                          and not engine.cfg.use_pp)
    n_update_arrivals = 0

    def apply_churn():
        ids = churn.integers(0, engine.num_global_nodes,
                             update_rows, dtype=np.int64)
        vals = churn.standard_normal(
            (update_rows, engine.n_feat_raw)).astype(np.float32)
        engine.apply_updates(ids, vals)
        engine.refresh_boundary()

    t0 = clock()
    next_report = t0 + report_every_s
    next_refresh = t0 + refresh_every_s
    next_update = t0 + update_every_s if do_updates else float("inf")
    n_records = 0
    total_q = 0
    stale_max = 0
    hits = misses = 0
    total_shed = 0

    def emit(now, final=False):
        nonlocal n_records, total_q, stale_max, hits, misses, total_shed
        h, m = stats.hits, stats.misses
        rec = stats.snapshot(queue_depth=batcher.queue_depth)
        total_q += rec["queries"]
        stale_max = max(stale_max, rec["staleness_age"])
        hits += h
        misses += m
        total_shed += rec["shed"]
        if ml is not None:
            extra = {"final": True} if final else {}
            ml.serving(**rec, **extra)
        n_records += 1

    def tick(now):
        nonlocal next_report, next_refresh, next_update
        if do_updates and now >= next_update:
            apply_churn()
            next_update = now + update_every_s
        if now >= next_refresh:
            engine.refresh()
            next_refresh = now + refresh_every_s
        if now >= next_report:
            emit(now)
            next_report = now + report_every_s

    stopped = False
    for i, (t_arr, q) in enumerate(zip(gen.arrivals, gen.queries)):
        if stop is not None and stop():
            stopped = True
            break
        target = t0 + t_arr
        while True:
            now = clock()
            if now >= target:
                break
            batcher.pump(now)
            tick(now)
            if stop is not None and stop():
                stopped = True
                break
            sleep(min(target - now, 0.0005))
        if stopped:
            break
        if gen.is_update[i]:
            # mixed workload: this arrival is churn, not a query — it
            # never enters the ticket ledger
            n_update_arrivals += 1
            if do_arrival_updates:
                apply_churn()
        else:
            batcher.submit(q, trace_id=sampler.sample())
        now = clock()
        batcher.pump(now)
        tick(now)

    # shutdown: answer everything accepted, then the final record —
    # written through MetricsLogger.serving's hard_flush so it survives
    # an unclean exit right after (the chaos drill's assertion)
    batcher.drain()
    emit(clock(), final=True)

    lat = np.asarray(all_lat, np.float64) * 1000.0
    dt = max(clock() - t0, 1e-9)
    served = hits + misses
    return {
        "qps": float(total_q / dt),
        "n_queries": int(total_q),
        "duration_s": float(dt),
        "p50_ms": float(np.percentile(lat, 50)) if lat.size else None,
        "p95_ms": float(np.percentile(lat, 95)) if lat.size else None,
        "p99_ms": float(np.percentile(lat, 99)) if lat.size else None,
        "batch_fill": float(np.mean(fills)) if fills else None,
        "cache_hit_rate": (float(hits / served) if served else None),
        "staleness_age_max": int(stale_max),
        "n_records": int(n_records),
        "drained": batcher.queue_depth == 0,
        "stopped_early": bool(stopped),
        "n_shed": int(total_shed),
        "traffic": gen.shape.kind,
        "n_update_arrivals": int(n_update_arrivals),
        "n_traced": int(sampler.n_sampled),
        "n_spans": int(spans.n_spans),
        "n_submitted": int(batcher.n_submitted_rows),
        "n_served": int(batcher.n_served_rows),
        # zero tickets silently lost: submitted == served + shed once
        # the queue is drained (the kill drill pins this)
        "conserved": bool(
            batcher.n_submitted_rows
            == batcher.n_served_rows + batcher.n_shed_rows
            + batcher.queue_depth),
        "param_generation": int(stats.param_generation),
        "param_staleness": int(stats.param_staleness),
    }
