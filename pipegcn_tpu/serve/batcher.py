"""Micro-batching request layer for the serving runtime.

Node-id queries accumulate under a max-latency / max-batch policy and
are padded to a small ladder of power-of-two batch shapes — the same
bucketed-padding trick the SpMM kernels use for their degree buckets,
applied to the query dimension — so steady-state traffic replays
already-compiled programs and never retraces (pinned by the
compile-counter test in tests/test_serve.py).

Everything here is host-side and jax-free: the batcher drives an
injected `run(ids) -> logits` callable (ServingEngine.query in
production, a fake in tests) and takes an injectable clock so the
latency policy is deterministically testable.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import numpy as np


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def bucket_ladder(min_bucket: int = 8, max_bucket: int = 64) -> List[int]:
    """Power-of-two batch shapes from min_bucket to max_bucket
    (both rounded up to powers of two). Every query batch pads to one
    of these, so the compiled-program population is O(log max/min)."""
    lo = _next_pow2(max(1, int(min_bucket)))
    hi = _next_pow2(max(lo, int(max_bucket)))
    ladder, b = [], lo
    while b <= hi:
        ladder.append(b)
        b *= 2
    return ladder


def bucket_for(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder shape holding n rows (callers chunk above the
    top shape, so n must not exceed ladder[-1])."""
    if n > ladder[-1]:
        raise ValueError(f"batch of {n} exceeds max bucket {ladder[-1]}")
    for b in ladder:
        if n <= b:
            return b
    return ladder[-1]


class AdmissionLadder:
    """Graduated admission control: brownout before blackout
    (docs/SERVING.md "Autoscaling & overload").

    As queue pressure (queued rows / max_queue) climbs through the
    rung thresholds, the EFFECTIVE admission bound and ticket deadline
    tighten, so the queue brakes progressively instead of slamming
    into the hard queue-full wall — overload sheds the newest, most
    deferrable work first while the autoscaler's capacity catches up.
    Each rung is ``(pressure_threshold, queue_frac, deadline_frac)``:
    at pressure >= threshold, the admission bound is
    ``queue_frac * max_queue`` and the deadline ``deadline_frac *
    ticket_deadline``. Rung 0 must be ``(0.0, 1.0, 1.0)`` (no
    tightening at rest). Sheds caused by a tightened bound (the queue
    was below the HARD bound) carry reason ``brownout`` so per-reason
    accounting separates graceful degradation from blackout.

    Stateful only for observability: `rung` is the last observed rung
    and `n_transitions` counts rung changes; the rung->bounds mapping
    itself is pure, so fake-clock tests drive it directly."""

    DEFAULT_RUNGS = ((0.0, 1.0, 1.0),
                     (0.5, 0.9, 0.5),
                     (0.75, 0.8, 0.25))

    def __init__(self, rungs=DEFAULT_RUNGS):
        rungs = tuple(tuple(map(float, r)) for r in rungs)
        if not rungs or rungs[0][0] != 0.0:
            raise ValueError("ladder rung 0 must start at pressure 0.0")
        if list(rungs) != sorted(rungs):
            raise ValueError("ladder rungs must be sorted by pressure")
        for p, qf, df in rungs:
            if not (0.0 <= p <= 1.0 and 0.0 < qf <= 1.0
                    and 0.0 < df <= 1.0):
                raise ValueError(f"bad ladder rung ({p}, {qf}, {df})")
        self.rungs = rungs
        self.rung = 0
        self.n_transitions = 0

    def rung_for(self, pressure: float) -> int:
        """Highest rung whose threshold is <= pressure (pure)."""
        r = 0
        for i, (thr, _, _) in enumerate(self.rungs):
            if pressure >= thr:
                r = i
        return r

    def observe(self, queue_depth: int, max_queue: int) -> int:
        """Fold the current pressure into `rung`; returns it."""
        pressure = queue_depth / max(int(max_queue), 1)
        r = self.rung_for(pressure)
        if r != self.rung:
            self.n_transitions += 1
            self.rung = r
        return r

    def effective(self, max_queue: Optional[int],
                  deadline_s: Optional[float]):
        """(effective max_queue, effective deadline_s) at the current
        rung; None inputs stay None (unbounded)."""
        _, qf, df = self.rungs[self.rung]
        eff_q = None if max_queue is None else int(max_queue * qf)
        eff_d = None if deadline_s is None else deadline_s * df
        return eff_q, eff_d


class Ticket:
    """One submitted query: node ids in, logits rows out after the
    batch it rode in flushes — or ``shed=True`` when the ticket was
    explicitly rejected (bounded queue / deadline / shutdown with no
    serving capacity) instead of being silently dropped."""

    __slots__ = ("ids", "t_submit", "result", "latency_s", "done",
                 "shed", "shed_reason", "trace_id", "t_dispatch")

    def __init__(self, ids: np.ndarray, t_submit: float,
                 trace_id: Optional[str] = None):
        self.ids = ids
        self.t_submit = t_submit
        self.result: Optional[np.ndarray] = None
        self.latency_s: Optional[float] = None
        self.done = False
        self.shed = False
        self.shed_reason: Optional[str] = None
        # sampled tracing (serve/tracing.py): None = unsampled
        self.trace_id = trace_id
        self.t_dispatch: Optional[float] = None


class MicroBatcher:
    """Accumulate query tickets; flush when the batch fills or the
    oldest ticket has waited max_delay_ms (the latency-vs-batch-fill
    tradeoff knob, docs/SERVING.md).

    `run(ids)` is called with the concatenated UNPADDED ids — padding
    to the ladder shape is the engine's job (it owns the compiled
    programs) — and `observer(bucket, n_valid, latencies_s)` fires per
    flushed batch for stats collection.

    Overload protection (docs/SERVING.md "Load shedding"): with
    ``max_queue`` set, a submit that would push the queued row count
    past the bound is REJECTED — the ticket comes back ``shed=True``
    immediately, bounding both memory and the tail latency of what IS
    accepted. With ``ticket_deadline_ms`` set, tickets that have
    already waited past the deadline at flush time are shed rather
    than served uselessly late. Every shed fires ``on_shed(ticket,
    reason)``; nothing is ever dropped without a record."""

    def __init__(self, run: Callable[[np.ndarray], np.ndarray],
                 max_batch: int = 64, max_delay_ms: float = 5.0,
                 ladder_min: int = 8,
                 clock: Callable[[], float] = time.monotonic,
                 observer: Optional[Callable] = None,
                 max_queue: Optional[int] = None,
                 ticket_deadline_ms: Optional[float] = None,
                 on_shed: Optional[Callable] = None,
                 on_span: Optional[Callable] = None,
                 admission_ladder: Optional[AdmissionLadder] = None):
        self._run = run
        self.ladder = bucket_ladder(ladder_min, max_batch)
        self.max_batch = self.ladder[-1]
        self.max_delay_s = max_delay_ms / 1000.0
        self.max_queue = None if max_queue is None else int(max_queue)
        self.deadline_s = (None if ticket_deadline_ms is None
                           else ticket_deadline_ms / 1000.0)
        self._clock = clock
        self._observer = observer
        self._on_shed = on_shed
        # on_span(trace_id, op, t0, t1, status, **extra) — sampled
        # tracing sink (SpanWriter.emit); None = tracing off. Spans
        # fire only for tickets carrying a trace_id, so the default
        # path never pays more than a None check per ticket.
        self._on_span = on_span
        # graceful-degradation ladder: tightens the EFFECTIVE admission
        # bound and deadline as pressure rises (brownout before
        # blackout); None = legacy hard-wall-only behaviour
        self.ladder_ctl = admission_ladder
        self._pending: List[Ticket] = []
        self.n_flushed_batches = 0
        self.n_shed_tickets = 0
        self.n_shed_rows = 0
        self.n_served_rows = 0
        # every row ever handed to submit(): the conservation invariant
        # submitted == served + shed + queue_depth holds at all times,
        # so "zero tickets silently lost" is checkable from outside
        self.n_submitted_rows = 0

    # ---------------- intake ------------------------------------------

    def _shed(self, t: Ticket, reason: str) -> Ticket:
        t.shed = True
        t.shed_reason = reason
        t.done = True
        self.n_shed_tickets += 1
        self.n_shed_rows += t.ids.size
        if self._on_shed is not None:
            self._on_shed(t, reason)
        if self._on_span is not None and t.trace_id is not None:
            # terminal span: a sampled submit ends in exactly one of
            # shed | dispatch (tests/test_monitor.py conservation pin)
            self._on_span(t.trace_id, "shed", t.t_submit, self._clock(),
                          "shed", reason=reason, rows=int(t.ids.size))
        return t

    def submit(self, node_ids,
               trace_id: Optional[str] = None) -> Ticket:
        ids = np.atleast_1d(np.asarray(node_ids, np.int64))
        if ids.size > self.max_batch:
            raise ValueError(
                f"a single query of {ids.size} ids exceeds max_batch "
                f"{self.max_batch}; split it")
        t = Ticket(ids, self._clock(), trace_id=trace_id)
        self.n_submitted_rows += ids.size
        depth = self.queue_depth
        eff_queue = self.max_queue
        if self.ladder_ctl is not None and self.max_queue is not None:
            self.ladder_ctl.observe(depth, self.max_queue)
            eff_queue, _ = self.ladder_ctl.effective(self.max_queue,
                                                     self.deadline_s)
        if self.max_queue is not None and depth + ids.size > self.max_queue:
            return self._shed(t, "queue-full")
        if eff_queue is not None and depth + ids.size > eff_queue:
            # below the hard wall but above the ladder-tightened bound:
            # graceful brownout, accounted separately from blackout
            return self._shed(t, "brownout")
        self._pending.append(t)
        return t

    @property
    def rung(self) -> int:
        """Current degradation rung (0 when no ladder is attached)."""
        return 0 if self.ladder_ctl is None else self.ladder_ctl.rung

    @property
    def queue_depth(self) -> int:
        """Queued query rows (node ids) not yet flushed."""
        return int(sum(t.ids.size for t in self._pending))

    def oldest_wait_s(self, now: Optional[float] = None) -> float:
        if not self._pending:
            return 0.0
        now = self._clock() if now is None else now
        return now - self._pending[0].t_submit

    def due(self, now: Optional[float] = None) -> bool:
        if not self._pending:
            return False
        if self.queue_depth >= self.max_batch:
            return True
        return self.oldest_wait_s(now) >= self.max_delay_s

    # ---------------- flush -------------------------------------------

    def _expire(self, now: float) -> int:
        """Shed queued tickets that already waited past the deadline —
        under overload the answer would arrive uselessly late, and
        serving it would push every younger ticket later still."""
        if self.deadline_s is None or not self._pending:
            return 0
        deadline = self.deadline_s
        if self.ladder_ctl is not None:
            _, deadline = self.ladder_ctl.effective(self.max_queue,
                                                    self.deadline_s)
        keep, n = [], 0
        for t in self._pending:
            if now - t.t_submit > deadline:
                self._shed(t, "deadline")
                n += 1
            else:
                keep.append(t)
        self._pending = keep
        return n

    def take_batch(self, now: Optional[float] = None,
                   force: bool = False):
        """Pop one due batch WITHOUT running it: returns (tickets,
        concatenated ids) for the caller to dispatch (the fleet router
        path, serve/fleet.py — dispatch happens on worker threads so
        N replicas serve concurrently), or None when nothing is due.
        Deadline-expired tickets are shed first. Finish the batch with
        :meth:`complete_batch` (or shed every ticket explicitly)."""
        now = self._clock() if now is None else now
        self._expire(now)
        if not self._pending or not (force or self.due(now)):
            return None
        take, rows = [], 0
        while self._pending and rows + self._pending[0].ids.size \
                <= self.max_batch:
            t = self._pending.pop(0)
            t.t_dispatch = now
            take.append(t)
            rows += t.ids.size
        if not take:  # single oversized ticket is rejected at submit
            return None
        return take, np.concatenate([t.ids for t in take])

    def complete_batch(self, take: List[Ticket], out: np.ndarray,
                       t_done: Optional[float] = None) -> None:
        """Fill a taken batch's tickets from the concatenated result
        rows and fire the observer. Thread-safety: per-batch state is
        local, counters are int += under the GIL — safe for the fleet's
        worker threads."""
        t_done = self._clock() if t_done is None else t_done
        off = 0
        lats = []
        rows = 0
        for t in take:
            t.result = out[off:off + t.ids.size]
            off += t.ids.size
            rows += t.ids.size
            t.latency_s = t_done - t.t_submit
            t.done = True
            lats.extend([t.latency_s] * t.ids.size)
            if self._on_span is not None and t.trace_id is not None:
                td = t.t_dispatch if t.t_dispatch is not None \
                    else t.t_submit
                self._on_span(t.trace_id, "queue", t.t_submit, td, "ok",
                              rows=int(t.ids.size))
                self._on_span(t.trace_id, "dispatch", td, t_done, "ok",
                              rows=int(t.ids.size))
        self.n_flushed_batches += 1
        self.n_served_rows += rows
        if self._observer is not None:
            self._observer(bucket_for(rows, self.ladder), rows, lats)

    def shed_batch(self, take: List[Ticket],
                   reason: str = "no-capacity") -> None:
        """Explicitly shed a taken batch (shutdown with every replica
        down): the tickets are answered 'no' rather than lost."""
        for t in take:
            self._shed(t, reason)

    def pump(self, now: Optional[float] = None, force: bool = False) -> int:
        """Flush every due batch (or everything with force=True);
        returns the number of batches dispatched."""
        n = 0
        while True:
            batch = self.take_batch(now, force=force)
            if batch is None:
                return n
            take, ids = batch
            traced = self._on_span is not None \
                and any(t.trace_id is not None for t in take)
            t_run0 = self._clock() if traced else 0.0
            out = self._run(ids)
            if traced:
                t_run1 = self._clock()
                for t in take:
                    if t.trace_id is not None:
                        self._on_span(t.trace_id, "engine", t_run0,
                                      t_run1, "ok", rows=int(ids.size))
            self.complete_batch(take, out)
            n += 1

    def drain(self) -> int:
        """Flush the whole queue regardless of policy (shutdown path:
        the engine must answer every accepted query before exiting)."""
        return self.pump(force=True)


class ServingStats:
    """Windowed aggregation of serving metrics, snapshotted into the
    contracted schema-v5 `serving` record (obs/schema.py)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        # parameter-generation axis (v7): persists across windows — the
        # served generation doesn't vanish just because a window rolled
        self.param_generation = -1
        self.param_staleness = 0
        self.reset()

    def reset(self) -> None:
        self._t0 = self._clock()
        self.n_queries = 0
        self.n_batches = 0
        self._lat_s: List[float] = []
        self._fills: List[float] = []
        self.hits = 0
        self.misses = 0
        self.max_staleness = 0
        self.n_shed = 0
        self.shed_by_reason: dict = {}

    # fed by MicroBatcher's observer hook
    def note_batch(self, bucket: int, n_valid: int,
                   latencies_s: Sequence[float]) -> None:
        self.n_batches += 1
        self._fills.append(n_valid / max(bucket, 1))
        self._lat_s.extend(latencies_s)

    # fed by ServingEngine.query (which knows freshness at serve time)
    def note_serve(self, n: int, hit: bool, staleness_age: int) -> None:
        self.n_queries += int(n)
        if hit:
            self.hits += int(n)
        else:
            self.misses += int(n)
        self.max_staleness = max(self.max_staleness, int(staleness_age))

    # fed by MicroBatcher's on_shed hook (ticket, reason)
    def note_shed(self, ticket, reason: str = "") -> None:
        self.n_shed += int(ticket.ids.size)
        key = reason or "unknown"
        self.shed_by_reason[key] = (self.shed_by_reason.get(key, 0)
                                    + int(ticket.ids.size))

    # fed by the checkpoint watcher / engine after a (non-)swap
    def note_params(self, generation: int, staleness: int = 0) -> None:
        self.param_generation = int(generation)
        self.param_staleness = int(staleness)

    def snapshot(self, queue_depth: int = 0, reset: bool = True) -> dict:
        """One `serving` record's worth of fields; resets the window."""
        dt = max(self._clock() - self._t0, 1e-9)
        lat = np.asarray(self._lat_s, np.float64) * 1000.0
        served = self.hits + self.misses
        rec = {
            "window_s": float(dt),
            "queries": int(self.n_queries),
            "qps": float(self.n_queries / dt),
            "batch_fill": (float(np.mean(self._fills))
                           if self._fills else None),
            "queue_depth": int(queue_depth),
            "p50_ms": float(np.percentile(lat, 50)) if lat.size else None,
            "p95_ms": float(np.percentile(lat, 95)) if lat.size else None,
            "p99_ms": float(np.percentile(lat, 99)) if lat.size else None,
            "cache_hit_rate": (float(self.hits / served)
                               if served else None),
            "staleness_age": int(self.max_staleness),
            "shed": int(self.n_shed),
            "param_generation": int(self.param_generation),
            "param_staleness": int(self.param_staleness),
            # uncontracted extra: rides into the serving record so the
            # live exporter can break pipegcn_serving_shed_total out by
            # reason (queue-full | deadline | fleet-down | ...)
            "shed_by_reason": dict(self.shed_by_reason),
        }
        if reset:
            self.reset()
        return rec
