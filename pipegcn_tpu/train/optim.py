"""Adam optimizer, in-repo pure JAX.

Matches torch.optim.Adam semantics used by the reference
(train.py:321-323): L2 weight decay folded into the gradient (not
decoupled/AdamW), bias-corrected first/second moments, update
lr * m_hat / (sqrt(v_hat) + eps). Implemented here rather than via optax
so optimizer state is a plain pytree the checkpoint/restore and SPMD
paths fully control.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

Params = Any
OptState = dict


def adam_init(params: Params) -> OptState:
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    return {
        "mu": zeros,
        "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
        "step": jnp.zeros((), jnp.int32),
    }


def adam_update(
    grads: Params,
    state: OptState,
    params: Params,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> Tuple[Params, OptState]:
    """One Adam step; returns (new_params, new_state)."""
    step = state["step"] + 1
    if weight_decay:
        grads = jax.tree_util.tree_map(
            lambda g, p: g + weight_decay * p, grads, params
        )
    mu = jax.tree_util.tree_map(
        lambda m, g: b1 * m + (1 - b1) * g, state["mu"], grads
    )
    nu = jax.tree_util.tree_map(
        lambda v, g: b2 * v + (1 - b2) * (g * g), state["nu"], grads
    )
    t = step.astype(jnp.float32)
    bc1 = 1 - b1 ** t
    bc2 = 1 - b2 ** t
    new_params = jax.tree_util.tree_map(
        lambda p, m, v: p - lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps),
        params, mu, nu,
    )
    return new_params, {"mu": mu, "nu": nu, "step": step}
