from .losses import cross_entropy_sum, bce_logits_sum
from .metrics import accuracy, micro_f1, calc_acc
from .optim import adam_init, adam_update

__all__ = [
    "cross_entropy_sum",
    "bce_logits_sum",
    "accuracy",
    "micro_f1",
    "calc_acc",
    "adam_init",
    "adam_update",
]
