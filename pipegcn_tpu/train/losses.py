"""Loss functions.

Sum-reduced (not mean) losses over masked train rows, matching the
reference exactly: CrossEntropyLoss(reduction='sum') for single-label
datasets, BCEWithLogitsLoss(reduction='sum') for multi-label/Yelp
(reference train.py:317-320). The 1/n_train normalization happens on the
*gradients* during reduction (reference helper/reducer.py:27), not here —
so per-partition loss sums psum to the global sum.

Masks make the padded-row/static-shape scheme work: every function takes
the full padded [N, ...] arrays and a boolean row mask.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_sum(logits: jax.Array, labels: jax.Array,
                      mask: jax.Array) -> jax.Array:
    """Sum of CE over rows where mask is True. labels: int [N]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    # clip labels so padded rows (label 0 or -1) index validly; masked out
    safe = jnp.clip(labels, 0, logits.shape[-1] - 1)
    picked = jnp.take_along_axis(logp, safe[:, None], axis=-1)[:, 0]
    return -(picked * mask).sum()


def bce_logits_sum(logits: jax.Array, labels: jax.Array,
                   mask: jax.Array) -> jax.Array:
    """Sum of element-wise binary CE with logits over masked rows.
    labels: float [N, C] in {0, 1}."""
    # numerically stable: max(x,0) - x*y + log1p(exp(-|x|))
    x = logits
    per_elem = jnp.maximum(x, 0.0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return (per_elem * mask[:, None]).sum()
