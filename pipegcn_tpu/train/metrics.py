"""Evaluation metrics: accuracy for single-label, micro-F1 for multi-label
(reference train.py:11-17 `calc_acc`: multi-label predictions are
`logits > 0`, scored with sklearn micro-F1 — reimplemented here in numpy
so no sklearn dependency is needed on the eval path)."""

from __future__ import annotations

import numpy as np


def accuracy(logits: np.ndarray, labels: np.ndarray) -> float:
    return float((logits.argmax(axis=-1) == labels).mean()) if len(labels) else 0.0


def micro_f1(logits: np.ndarray, labels: np.ndarray) -> float:
    """Micro-averaged F1 with predictions = logits > 0 (multi-label)."""
    pred = logits > 0
    lab = labels > 0.5
    tp = float(np.logical_and(pred, lab).sum())
    fp = float(np.logical_and(pred, ~lab).sum())
    fn = float(np.logical_and(~pred, lab).sum())
    denom = 2 * tp + fp + fn
    return 2 * tp / denom if denom else 0.0


def calc_acc(logits: np.ndarray, labels: np.ndarray) -> float:
    """Dispatch on label rank, like reference train.py:11-17."""
    if labels.ndim == 1:
        return accuracy(logits, labels)
    return micro_f1(logits, labels)
