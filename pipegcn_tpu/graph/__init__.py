from .csr import Graph, add_self_loops, remove_self_loops, normalize_self_loops
from .synthetic import synthetic_graph, karate_club
from .datasets import load_data

__all__ = [
    "Graph",
    "add_self_loops",
    "remove_self_loops",
    "normalize_self_loops",
    "synthetic_graph",
    "karate_club",
    "load_data",
]
