"""Dataset loaders.

Re-implements the reference's `load_data` dispatch (helper/utils.py:74-96)
without DGL/OGB: each loader reads the dataset's standard on-disk raw format
directly with numpy/scipy. All loaders apply the reference's
canonicalization — self-loop normalization (helper/utils.py:94-95), class
count inferred from label rank (helper/utils.py:88-91), and full-graph
in-degree precompute (helper/utils.py:142).

Synthetic datasets (no download needed) are first-class here, unlike the
reference: 'karate', 'synthetic', 'synthetic-reddit' (Reddit-scale shape
stats), and parameterized 'synthetic:<nodes>:<deg>:<feat>:<classes>'.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from .csr import Graph, finalize
from .synthetic import karate_club, synthetic_graph


def n_classes(g: Graph) -> int:
    """Infer class count: 1-D integer labels -> max+1 (single-label);
    2-D labels -> second dim (multi-label). Reference helper/utils.py:88-91."""
    label = g.ndata["label"]
    if label.ndim == 1:
        return int(label.max()) + 1
    return int(label.shape[1])


def is_multilabel(g: Graph) -> bool:
    return g.ndata["label"].ndim == 2


def load_reddit(root: str) -> Graph:
    """Reddit from the standard DGL raw archive layout:
    <root>/reddit/reddit_data.npz (feature/label/node_types) +
    <root>/reddit/reddit_graph.npz (scipy sparse adjacency)."""
    import scipy.sparse as sp

    d = os.path.join(root, "reddit")
    data = np.load(os.path.join(d, "reddit_data.npz"))
    adj = sp.load_npz(os.path.join(d, "reddit_graph.npz")).tocoo()
    types = data["node_types"]
    g = Graph(
        num_nodes=int(data["feature"].shape[0]),
        src=adj.row.astype(np.int64),
        dst=adj.col.astype(np.int64),
        ndata={
            "feat": data["feature"].astype(np.float32),
            "label": data["label"].astype(np.int64),
            "train_mask": types == 1,
            "val_mask": types == 2,
            "test_mask": types == 3,
        },
    )
    return finalize(g)


def _read_csv_gz(path: str, dtype):
    """Fast csv.gz reader: pandas C engine when available, else numpy."""
    try:
        import pandas as pd

        return pd.read_csv(path, header=None, dtype=dtype).to_numpy()
    except ImportError:
        return np.loadtxt(path, delimiter=",", dtype=dtype, ndmin=2)


def load_ogb(name: str, root: str) -> Graph:
    """ogbn-products / ogbn-papers100M from OGB's extracted raw layouts.

    Handles both on-disk flavors: plain arrays (`raw/{edge,node-feat,
    node-label}.{npy,csv.gz}`, used by ogbn-products) and compressed-npz
    (`raw/data.npz` + `raw/node-label.npz`, used by ogbn-papers100M).
    papers100M labels are float with NaN for unlabeled nodes; they are
    converted to int64 with -1 for unlabeled. Masks are rebuilt from the
    split index files like reference helper/utils.py:17-30.
    """
    dirname = name.replace("-", "_")
    base = os.path.join(root, dirname)
    raw = os.path.join(base, "raw")

    data_npz = os.path.join(raw, "data.npz")
    if os.path.exists(data_npz):
        # papers100M layout
        data = np.load(data_npz)
        edges = data["edge_index"].reshape(2, -1).T.astype(np.int64)
        feat = data["node_feat"].astype(np.float32)
        label_f = np.load(os.path.join(raw, "node-label.npz"))["node_label"]
        label_f = np.asarray(label_f, dtype=np.float64).reshape(-1)
        label = np.where(np.isnan(label_f), -1, label_f).astype(np.int64)
    else:

        def _load_any(stem: str, dtype):
            npy = os.path.join(raw, stem + ".npy")
            if os.path.exists(npy):
                return np.load(npy)
            csv = os.path.join(raw, stem + ".csv.gz")
            if os.path.exists(csv):
                return _read_csv_gz(csv, dtype)
            raise FileNotFoundError(f"{name}: missing {stem} under {raw}")

        edges = _load_any("edge", np.int64).reshape(-1, 2)
        feat = _load_any("node-feat", np.float32).astype(np.float32)
        label_f = _load_any("node-label", np.float64).reshape(-1)
        label = np.where(np.isnan(label_f), -1, label_f).astype(np.int64)
    num_nodes = feat.shape[0]

    split_dir = None
    for cand in ("sales_ranking", "time"):
        p = os.path.join(base, "split", cand)
        if os.path.isdir(p):
            split_dir = p
            break
    if split_dir is None:
        raise FileNotFoundError(f"{name}: no split dir under {base}/split")

    masks = {}
    for part, key in (("train", "train_mask"), ("valid", "val_mask"), ("test", "test_mask")):
        idx = _read_csv_gz(
            os.path.join(split_dir, part + ".csv.gz"), np.int64
        ).reshape(-1)
        m = np.zeros(num_nodes, dtype=bool)
        m[idx] = True
        masks[key] = m

    # OGB edges are directed; the reference's DGL graphs for these datasets
    # are symmetric — mirror them.
    src = np.concatenate([edges[:, 0], edges[:, 1]])
    dst = np.concatenate([edges[:, 1], edges[:, 0]])
    g = Graph(
        num_nodes=num_nodes,
        src=src,
        dst=dst,
        ndata={"feat": feat, "label": label, **masks},
    )
    return finalize(g)


def load_yelp(root: str) -> Graph:
    """Yelp from the GraphSAINT raw layout (adj_full.npz, feats.npy,
    class_map.json, role.json), with feature standardization fit on train
    nodes only — reference helper/utils.py:33-71."""
    import scipy.sparse as sp

    d = os.path.join(root, "yelp")
    adj = sp.load_npz(os.path.join(d, "adj_full.npz")).tocoo()
    feats = np.load(os.path.join(d, "feats.npy")).astype(np.float32)
    n = feats.shape[0]
    with open(os.path.join(d, "class_map.json")) as f:
        class_map = json.load(f)
    with open(os.path.join(d, "role.json")) as f:
        role = json.load(f)

    label = np.zeros((n, len(next(iter(class_map.values())))), dtype=np.float32)
    for k, v in class_map.items():
        label[int(k)] = v

    train_mask = np.zeros(n, dtype=bool)
    val_mask = np.zeros(n, dtype=bool)
    test_mask = np.zeros(n, dtype=bool)
    train_mask[role["tr"]] = True
    val_mask[role["va"]] = True
    test_mask[role["te"]] = True
    assert not (train_mask & val_mask).any()
    assert not (train_mask & test_mask).any()
    assert not (val_mask & test_mask).any()
    assert (train_mask | val_mask | test_mask).all()

    # Standardize features with statistics from train nodes only
    # (reference helper/utils.py:66-69 via sklearn StandardScaler).
    mu = feats[train_mask].mean(axis=0)
    sd = feats[train_mask].std(axis=0)
    sd[sd == 0] = 1.0
    feats = (feats - mu) / sd

    g = Graph(
        num_nodes=n,
        src=adj.row.astype(np.int64),
        dst=adj.col.astype(np.int64),
        ndata={
            "feat": feats,
            "label": label,
            "train_mask": train_mask,
            "val_mask": val_mask,
            "test_mask": test_mask,
        },
    )
    return finalize(g)


def load_data(dataset: str, root: Optional[str] = None) -> Graph:
    """Dispatch mirroring reference helper/utils.py:74-96, plus synthetic
    datasets. `root` defaults to $PIPEGCN_DATA or ./dataset."""
    root = root or os.environ.get("PIPEGCN_DATA", "./dataset")
    name = dataset.lower()
    if name == "karate":
        return karate_club()
    if name == "synthetic":
        return synthetic_graph()
    if name == "synthetic-reddit":
        # Reddit-scale shape statistics: 232,965 nodes, ~114.6M directed
        # edges (avg in-degree ~492) in the reference's normalized graph,
        # 602 features, 41 classes. avg_degree counts undirected edges per
        # node before mirroring, so 492 here yields ~114.6M directed edges.
        return synthetic_graph(
            num_nodes=232_965, avg_degree=492, n_feat=602, n_class=41, seed=0
        )
    if name.startswith("synthetic:"):
        parts = name.split(":")[1:]
        nodes, deg, feat, cls = (int(x) for x in parts[:4])
        multilabel = len(parts) > 4 and parts[4] == "ml"
        return synthetic_graph(
            num_nodes=nodes, avg_degree=deg, n_feat=feat, n_class=cls,
            multilabel=multilabel,
        )
    if name == "reddit":
        return load_reddit(root)
    if name in ("ogbn-products", "ogbn-papers100m"):
        return load_ogb(name, root)
    if name == "yelp":
        return load_yelp(root)
    raise ValueError(f"unknown dataset: {dataset}")


def inductive_split(g: Graph) -> "tuple[Graph, Graph, Graph]":
    """(train_g, val_g, test_g) for inductive mode: train graph = subgraph of
    train nodes; val graph = subgraph of train+val; test graph = full graph.
    Reference helper/utils.py:226-230."""
    train_g = g.node_subgraph(g.ndata["train_mask"])
    val_g = g.node_subgraph(g.ndata["train_mask"] | g.ndata["val_mask"])
    return train_g, val_g, g
