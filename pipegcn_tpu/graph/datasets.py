"""Dataset loaders.

Re-implements the reference's `load_data` dispatch (helper/utils.py:74-96)
without DGL/OGB: each loader reads the dataset's standard on-disk raw format
directly with numpy/scipy. All loaders apply the reference's
canonicalization — self-loop normalization (helper/utils.py:94-95), class
count inferred from label rank (helper/utils.py:88-91), and full-graph
in-degree precompute (helper/utils.py:142).

Synthetic datasets (no download needed) are first-class here, unlike the
reference: 'karate', 'synthetic', 'synthetic-reddit' (Reddit-scale shape
stats), and parameterized 'synthetic:<nodes>:<deg>:<feat>:<classes>'.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from .csr import Graph, finalize
from .synthetic import karate_club, synthetic_graph


def n_classes(g: Graph) -> int:
    """Infer class count: 1-D integer labels -> max+1 (single-label);
    2-D labels -> second dim (multi-label). Reference helper/utils.py:88-91."""
    label = g.ndata["label"]
    if label.ndim == 1:
        return int(label.max()) + 1
    return int(label.shape[1])


def is_multilabel(g: Graph) -> bool:
    return g.ndata["label"].ndim == 2


def load_reddit(root: str) -> Graph:
    """Reddit from the standard DGL raw archive layout:
    <root>/reddit/reddit_data.npz (feature/label/node_types) +
    <root>/reddit/reddit_graph.npz (scipy sparse adjacency)."""
    import scipy.sparse as sp

    d = os.path.join(root, "reddit")
    data = np.load(os.path.join(d, "reddit_data.npz"))
    adj = sp.load_npz(os.path.join(d, "reddit_graph.npz")).tocoo()
    types = data["node_types"]
    g = Graph(
        num_nodes=int(data["feature"].shape[0]),
        src=adj.row.astype(np.int64),
        dst=adj.col.astype(np.int64),
        ndata={
            "feat": data["feature"].astype(np.float32),
            "label": data["label"].astype(np.int64),
            "train_mask": types == 1,
            "val_mask": types == 2,
            "test_mask": types == 3,
        },
    )
    return finalize(g)


def _read_csv_gz(path: str, dtype):
    """Fast csv.gz reader: pandas C engine when available, else numpy."""
    try:
        import pandas as pd

        return pd.read_csv(path, header=None, dtype=dtype).to_numpy()
    except ImportError:
        return np.loadtxt(path, delimiter=",", dtype=dtype, ndmin=2)


# raw directed-edge count above which load_ogb switches to the
# RAM-bounded finalized-edge cache (papers100M territory; products'
# 124M directed edges stay on the simple path by a hair under the
# reference's own RAM expectations)
_OGB_MMAP_EDGES = 200_000_000

# chunk for one-time cache construction passes
_CACHE_CHUNK = 1 << 25


def _npz_member_shape(path: str, member: str):
    """Shape of one array inside an .npz WITHOUT decompressing it."""
    import zipfile

    with zipfile.ZipFile(path) as zf:
        with zf.open(member + ".npy") as f:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, _, _ = np.lib.format.read_array_header_1_0(f)
            else:
                shape, _, _ = np.lib.format.read_array_header_2_0(f)
    return shape


def _build_finalized_edge_cache(cache: str, edges, num_nodes: int,
                                chunk: int = _CACHE_CHUNK) -> None:
    """One-time chunked symmetrize + self-loop-normalize of a raw
    directed [E, 2] edge array into int32/int64 memmaps.

    Writes src.npy / dst.npy (mirrored non-self edges then one self loop
    per node — the chunked equivalent of load_ogb's concat + finalize,
    reference helper/utils.py:94-95) plus in_deg.npy (f32 finalized
    in-degrees) and meta.json. Edge scratch stays O(chunk); `edges` may
    be a memmap (plain layout) or an in-RAM array (npz layout, where
    decompression already materialized it)."""
    os.makedirs(cache, exist_ok=True)
    E = int(edges.shape[0])
    dtype = np.int32 if num_nodes <= np.iinfo(np.int32).max else np.int64
    keep = 0
    in_deg = np.zeros(num_nodes, np.int64)
    for i0 in range(0, E, chunk):
        e = np.asarray(edges[i0:i0 + chunk])
        u, v = e[:, 0], e[:, 1]
        # validate once here, while the pages are hot — meta.json is
        # only written after every chunk passed, so load never re-checks
        if e.size and (int(e.max()) >= num_nodes or int(e.min()) < 0):
            raise ValueError(f"edge ids out of range in chunk at {i0}")
        ns = u != v
        keep += int(ns.sum())
        # symmetric graph: each non-self raw edge lands in both degrees
        in_deg += np.bincount(v[ns], minlength=num_nodes)
        in_deg += np.bincount(u[ns], minlength=num_nodes)
    e_final = 2 * keep + num_nodes
    src_mm = np.lib.format.open_memmap(
        os.path.join(cache, "src.npy.tmp"), mode="w+", dtype=dtype,
        shape=(e_final,))
    dst_mm = np.lib.format.open_memmap(
        os.path.join(cache, "dst.npy.tmp"), mode="w+", dtype=dtype,
        shape=(e_final,))
    pos = 0
    for flip in (False, True):
        for i0 in range(0, E, chunk):
            e = np.asarray(edges[i0:i0 + chunk])
            u, v = e[:, 0], e[:, 1]
            ns = u != v
            uu, vv = u[ns], v[ns]
            if flip:
                uu, vv = vv, uu
            src_mm[pos:pos + uu.size] = uu.astype(dtype)
            dst_mm[pos:pos + vv.size] = vv.astype(dtype)
            pos += uu.size
    loop = np.arange(num_nodes, dtype=dtype)
    src_mm[pos:] = loop
    dst_mm[pos:] = loop
    src_mm.flush()
    dst_mm.flush()
    del src_mm, dst_mm
    np.save(os.path.join(cache, "in_deg.npy"),
            (in_deg + 1).astype(np.float32))  # +1: the self loop
    # meta last + atomic renames: a crashed build never half-validates
    os.replace(os.path.join(cache, "src.npy.tmp"),
               os.path.join(cache, "src.npy"))
    os.replace(os.path.join(cache, "dst.npy.tmp"),
               os.path.join(cache, "dst.npy"))
    with open(os.path.join(cache, "meta.json"), "w") as f:
        json.dump({"num_nodes": num_nodes, "raw_edges": E,
                   "final_edges": e_final}, f)


def _edge_cache_ready(cache: str, num_nodes: int, raw_edges: int) -> bool:
    meta = os.path.join(cache, "meta.json")
    if not os.path.exists(meta):
        return False
    with open(meta) as f:
        m = json.load(f)
    return (m.get("num_nodes") == num_nodes
            and m.get("raw_edges") == raw_edges)


def load_ogb(name: str, root: str,
             mmap: Optional[bool] = None) -> Graph:
    """ogbn-products / ogbn-papers100M from OGB's extracted raw layouts.

    Handles both on-disk flavors: plain arrays (`raw/{edge,node-feat,
    node-label}.{npy,csv.gz}`, used by ogbn-products) and compressed-npz
    (`raw/data.npz` + `raw/node-label.npz`, used by ogbn-papers100M).
    papers100M labels are float with NaN for unlabeled nodes; they are
    converted to int64 with -1 for unlabeled. Masks are rebuilt from the
    split index files like reference helper/utils.py:17-30.

    `mmap` (default: auto at papers100M scale) switches to the
    RAM-bounded path the reference solves with a >=120 GB host
    (reference README.md:29-30, helper/utils.py:17-30): a one-time
    chunked pass writes a finalized-edge cache (mirrored, self-loop
    normalized, int32, plus in-degrees) under raw/finalized_cache/, and
    the returned Graph memmaps src/dst/feat — so repeat runs touch only
    the pages the partition build streams through. The npz flavor still
    materializes each compressed member once while building the cache
    (inherent to the format); the plain-npy flavor never does."""
    dirname = name.replace("-", "_")
    base = os.path.join(root, dirname)
    raw = os.path.join(base, "raw")

    num_nodes = None
    data_npz = os.path.join(raw, "data.npz")
    npz_layout = os.path.exists(data_npz)
    if npz_layout:
        n_raw_edges = int(np.prod(_npz_member_shape(
            data_npz, "edge_index"))) // 2
        num_nodes = int(_npz_member_shape(data_npz, "node_feat")[0])
    else:
        edge_npy = os.path.join(raw, "edge.npy")
        if os.path.exists(edge_npy):
            n_raw_edges = int(np.load(edge_npy, mmap_mode="r")
                              .reshape(-1, 2).shape[0])
        else:
            n_raw_edges = 0  # csv flavor: small datasets only
            if mmap:
                import warnings

                warnings.warn(f"{name}: csv.gz edge flavor cannot build "
                              "the finalized-edge cache; ignoring mmap")
                mmap = False
    if mmap is None:
        mmap = n_raw_edges >= _OGB_MMAP_EDGES

    def _load_any(stem: str, dtype, mmap_mode=None):
        npy = os.path.join(raw, stem + ".npy")
        if os.path.exists(npy):
            return np.load(npy, mmap_mode=mmap_mode)
        csv = os.path.join(raw, stem + ".csv.gz")
        if os.path.exists(csv):
            return _read_csv_gz(csv, dtype)
        raise FileNotFoundError(f"{name}: missing {stem} under {raw}")

    # ---- node label (N-sized: always in RAM) --------------------------
    if npz_layout:
        label_f = np.load(os.path.join(raw, "node-label.npz"))["node_label"]
        label_f = np.asarray(label_f, dtype=np.float64).reshape(-1)
    else:
        label_f = np.asarray(_load_any("node-label", np.float64),
                             np.float64).reshape(-1)
    label = np.where(np.isnan(label_f), -1, label_f).astype(np.int64)

    # ---- features -----------------------------------------------------
    feat_cache = os.path.join(raw, "finalized_cache", "feat.npy")
    feat_meta = feat_cache + ".meta.json"
    if mmap and npz_layout:
        # one-time extraction so repeat runs memmap instead of
        # decompressing the 50+ GB member; stamped with the source's
        # size+mtime so a re-downloaded data.npz invalidates the cache
        # (existence alone would silently serve stale features)
        st = os.stat(data_npz)
        stamp = {"size": st.st_size, "mtime": st.st_mtime}
        fresh = False
        if os.path.exists(feat_cache) and os.path.exists(feat_meta):
            with open(feat_meta) as f:
                fresh = json.load(f) == stamp
        if not fresh:
            os.makedirs(os.path.dirname(feat_cache), exist_ok=True)
            f32 = np.load(data_npz)["node_feat"].astype(np.float32)
            np.save(feat_cache + ".tmp.npy", f32)
            os.replace(feat_cache + ".tmp.npy", feat_cache)
            del f32
            with open(feat_meta, "w") as f:
                json.dump(stamp, f)
        feat = np.load(feat_cache, mmap_mode="r")
    elif mmap:
        feat = _load_any("node-feat", np.float32, mmap_mode="r")
    elif npz_layout:
        feat = np.load(data_npz)["node_feat"].astype(np.float32)
    else:
        feat = np.asarray(_load_any("node-feat", np.float32), np.float32)
    num_nodes = int(feat.shape[0])

    # ---- split masks --------------------------------------------------
    split_dir = None
    for cand in ("sales_ranking", "time"):
        p = os.path.join(base, "split", cand)
        if os.path.isdir(p):
            split_dir = p
            break
    if split_dir is None:
        raise FileNotFoundError(f"{name}: no split dir under {base}/split")

    masks = {}
    for part, key in (("train", "train_mask"), ("valid", "val_mask"),
                      ("test", "test_mask")):
        idx = _read_csv_gz(
            os.path.join(split_dir, part + ".csv.gz"), np.int64
        ).reshape(-1)
        m = np.zeros(num_nodes, dtype=bool)
        m[idx] = True
        masks[key] = m

    # ---- edges --------------------------------------------------------
    if mmap:
        cache = os.path.join(raw, "finalized_cache")
        if not _edge_cache_ready(cache, num_nodes, n_raw_edges):
            if npz_layout:
                edges = np.load(data_npz)["edge_index"] \
                    .reshape(2, -1).T  # transient (format forces it)
            else:
                edges = np.load(os.path.join(raw, "edge.npy"),
                                mmap_mode="r").reshape(-1, 2)
            _build_finalized_edge_cache(cache, edges, num_nodes)
            del edges
        src = np.load(os.path.join(cache, "src.npy"), mmap_mode="r")
        dst = np.load(os.path.join(cache, "dst.npy"), mmap_mode="r")
        in_deg = np.load(os.path.join(cache, "in_deg.npy"))
        g = Graph(num_nodes=num_nodes, src=src, dst=dst,
                  ndata={"feat": feat, "label": label, **masks})
        g.ndata["in_deg"] = in_deg
        # bounds were validated once when the cache was built (before
        # meta.json existed); re-streaming ~26 GB of memmap on every
        # warm load would defeat the cache
        return g

    if npz_layout:
        edges = np.load(data_npz)["edge_index"].reshape(2, -1).T \
            .astype(np.int64)
    else:
        edges = np.asarray(_load_any("edge", np.int64),
                           np.int64).reshape(-1, 2)
    # OGB edges are directed; the reference's DGL graphs for these
    # datasets are symmetric — mirror them.
    src = np.concatenate([edges[:, 0], edges[:, 1]])
    dst = np.concatenate([edges[:, 1], edges[:, 0]])
    g = Graph(
        num_nodes=num_nodes,
        src=src,
        dst=dst,
        ndata={"feat": feat, "label": label, **masks},
    )
    return finalize(g)


def load_yelp(root: str) -> Graph:
    """Yelp from the GraphSAINT raw layout (adj_full.npz, feats.npy,
    class_map.json, role.json), with feature standardization fit on train
    nodes only — reference helper/utils.py:33-71."""
    import scipy.sparse as sp

    d = os.path.join(root, "yelp")
    adj = sp.load_npz(os.path.join(d, "adj_full.npz")).tocoo()
    feats = np.load(os.path.join(d, "feats.npy")).astype(np.float32)
    n = feats.shape[0]
    with open(os.path.join(d, "class_map.json")) as f:
        class_map = json.load(f)
    with open(os.path.join(d, "role.json")) as f:
        role = json.load(f)

    label = np.zeros((n, len(next(iter(class_map.values())))), dtype=np.float32)
    for k, v in class_map.items():
        label[int(k)] = v

    train_mask = np.zeros(n, dtype=bool)
    val_mask = np.zeros(n, dtype=bool)
    test_mask = np.zeros(n, dtype=bool)
    train_mask[role["tr"]] = True
    val_mask[role["va"]] = True
    test_mask[role["te"]] = True
    assert not (train_mask & val_mask).any()
    assert not (train_mask & test_mask).any()
    assert not (val_mask & test_mask).any()
    assert (train_mask | val_mask | test_mask).all()

    # Standardize features with statistics from train nodes only
    # (reference helper/utils.py:66-69 via sklearn StandardScaler).
    mu = feats[train_mask].mean(axis=0)
    sd = feats[train_mask].std(axis=0)
    sd[sd == 0] = 1.0
    feats = (feats - mu) / sd

    g = Graph(
        num_nodes=n,
        src=adj.row.astype(np.int64),
        dst=adj.col.astype(np.int64),
        ndata={
            "feat": feats,
            "label": label,
            "train_mask": train_mask,
            "val_mask": val_mask,
            "test_mask": test_mask,
        },
    )
    return finalize(g)


def load_data(dataset: str, root: Optional[str] = None) -> Graph:
    """Dispatch mirroring reference helper/utils.py:74-96, plus synthetic
    datasets. `root` defaults to $PIPEGCN_DATA or ./dataset."""
    root = root or os.environ.get("PIPEGCN_DATA", "./dataset")
    name = dataset.lower()
    if name == "karate":
        return karate_club()
    if name == "synthetic":
        return synthetic_graph()
    if name == "synthetic-reddit":
        # Reddit-scale shape statistics: 232,965 nodes, ~114.6M directed
        # edges (avg in-degree ~492) in the reference's normalized graph,
        # 602 features, 41 classes. avg_degree counts undirected edges per
        # node before mirroring, so 492 here yields ~114.6M directed edges.
        return synthetic_graph(
            num_nodes=232_965, avg_degree=492, n_feat=602, n_class=41, seed=0
        )
    if name.startswith("synthetic:"):
        parts = name.split(":")[1:]
        nodes, deg, feat, cls = (int(x) for x in parts[:4])
        multilabel = len(parts) > 4 and parts[4] == "ml"
        return synthetic_graph(
            num_nodes=nodes, avg_degree=deg, n_feat=feat, n_class=cls,
            multilabel=multilabel,
        )
    if name == "reddit":
        return load_reddit(root)
    if name in ("ogbn-products", "ogbn-papers100m"):
        return load_ogb(name, root)
    if name == "yelp":
        return load_yelp(root)
    raise ValueError(f"unknown dataset: {dataset}")


def inductive_split(g: Graph) -> "tuple[Graph, Graph, Graph]":
    """(train_g, val_g, test_g) for inductive mode: train graph = subgraph of
    train nodes; val graph = subgraph of train+val; test graph = full graph.
    Reference helper/utils.py:226-230."""
    train_g = g.node_subgraph(g.ndata["train_mask"])
    val_g = g.node_subgraph(g.ndata["train_mask"] | g.ndata["val_mask"])
    return train_g, val_g, g
