"""Synthetic graph generators.

The reference validates correctness only via convergence on real datasets
(SURVEY.md §4); this framework adds synthetic graphs so unit/integration
tests and benchmarks run hermetically (no dataset downloads). Graphs have
planted community structure so GNN training is meaningful: labels follow
communities, features are noisy class prototypes, and edges are mostly
intra-community — a stochastic-block-model flavor.
"""

from __future__ import annotations

import numpy as np

from .csr import Graph, finalize


def synthetic_graph(
    num_nodes: int = 1000,
    avg_degree: int = 10,
    n_feat: int = 32,
    n_class: int = 7,
    multilabel: bool = False,
    homophily: float = 0.8,
    train_frac: float = 0.6,
    val_frac: float = 0.2,
    seed: int = 0,
    noise: float = 1.0,
    label_noise: float = 0.0,
) -> Graph:
    """SBM-style synthetic graph with class-correlated features.

    Returns a Graph with 'feat', 'label', 'train_mask', 'val_mask',
    'test_mask' populated, self-loops normalized, and edges symmetric
    (each generated undirected edge is stored in both directions, like the
    datasets the reference uses).
    """
    rng = np.random.default_rng(seed)
    comm = rng.integers(0, n_class, size=num_nodes)

    n_edges = num_nodes * avg_degree // 2
    order = np.argsort(comm, kind="stable")
    sorted_comm = comm[order]
    starts = np.searchsorted(sorted_comm, np.arange(n_class))
    ends = np.searchsorted(sorted_comm, np.arange(n_class), side="right")

    def sample_pairs(k: int) -> np.ndarray:
        """k undirected candidate pairs as canonical lo*N+hi keys
        (self-pairs dropped). Endpoint A uniform; endpoint B
        intra-community w.p. `homophily` via a community-sorted
        lookup, else uniform."""
        a = rng.integers(0, num_nodes, size=k)
        intra = rng.random(k) < homophily
        ca = comm[a]
        span = np.maximum(ends[ca] - starts[ca], 1)
        b_intra = order[starts[ca]
                        + (rng.integers(0, 1 << 62, size=k) % span)]
        b = np.where(intra, b_intra, rng.integers(0, num_nodes, size=k))
        lo, hi = np.minimum(a, b), np.maximum(a, b)
        return (lo * num_nodes + hi)[lo != hi]

    # The real datasets this generator stands in for (Reddit, ogbn-*)
    # are SIMPLE graphs; duplicate sampled pairs are dropped and topped
    # up so the graph is simple at exactly the requested edge count
    # (multiplicity-1 adjacency is also what lets the block-dense
    # kernel bit-pack its A tiles, ops/block_spmm.pack_a_blocks).
    keys = np.unique(sample_pairs(n_edges))
    while keys.size < n_edges:
        extra = sample_pairs(2 * (n_edges - keys.size))
        merged = np.union1d(keys, extra)
        if merged.size == keys.size:  # saturated (requested degree
            break                     # exceeds the simple-pair space)
        keys = merged
    if keys.size > n_edges:
        keys = rng.permutation(keys)[:n_edges]

    a = keys // num_nodes
    b = keys % num_nodes
    src = np.concatenate([a, b]).astype(np.int64)
    dst = np.concatenate([b, a]).astype(np.int64)

    # Class-prototype features + noise. `noise` scales the per-node
    # gaussian: at the default 1.0 a wide-feature task is nearly
    # linearly separable from raw features; convergence studies that
    # need a non-trivial learning curve (accuracy plateauing below
    # 100%, like the real datasets) raise it so aggregation over the
    # neighborhood is what recovers the signal.
    protos = rng.normal(0.0, 1.0, size=(n_class, n_feat)).astype(np.float32)
    feat = protos[comm] + rng.normal(
        0.0, noise, size=(num_nodes, n_feat)).astype(np.float32)

    if multilabel:
        # Each node gets its community label plus random extra labels.
        label = np.zeros((num_nodes, n_class), dtype=np.float32)
        label[np.arange(num_nodes), comm] = 1.0
        extra = rng.random((num_nodes, n_class)) < 0.1
        label = np.maximum(label, extra.astype(np.float32))
    else:
        label = comm.astype(np.int64)
        if label_noise > 0.0:
            # flip a fraction of labels (all splits) to a random OTHER
            # class: imposes an irreducible-error ceiling of ~1-p like
            # the real datasets (Reddit tops out at 97.1%, reference
            # README.md:98) — without it, high-degree aggregation
            # saturates SBM tasks at 100% and convergence comparisons
            # lose their resolution. Drawn from a DEDICATED generator
            # so the split permutation below is identical across
            # label_noise settings at a fixed seed (a clean-vs-noisy
            # comparison must not also change train/val/test masks).
            nrng = np.random.default_rng(seed ^ 0x5EED)
            flip = nrng.random(num_nodes) < label_noise
            shift = nrng.integers(1, n_class, size=num_nodes)
            label = np.where(flip, (label + shift) % n_class, label)

    perm = rng.permutation(num_nodes)
    n_train = int(train_frac * num_nodes)
    n_val = int(val_frac * num_nodes)
    train_mask = np.zeros(num_nodes, dtype=bool)
    val_mask = np.zeros(num_nodes, dtype=bool)
    test_mask = np.zeros(num_nodes, dtype=bool)
    train_mask[perm[:n_train]] = True
    val_mask[perm[n_train : n_train + n_val]] = True
    test_mask[perm[n_train + n_val :]] = True

    g = Graph(
        num_nodes=num_nodes,
        src=src,
        dst=dst,
        ndata={
            "feat": feat,
            "label": label,
            "train_mask": train_mask,
            "val_mask": val_mask,
            "test_mask": test_mask,
        },
    )
    return finalize(g)


def synthetic_delta_schedule(
    g: Graph,
    n_batches: int = 4,
    edges_per_batch: int = 8,
    dels_per_batch: int = 4,
    nodes_per_batch: int = 1,
    nbrs_per_node: int = 3,
    seed: int = 0,
    start_seq: int = 0,
):
    """Deterministic synthetic delta batches against `g` (tests/bench).

    Each batch deletes ``dels_per_batch`` existing undirected non-self-
    loop edges (both directions), adds ``edges_per_batch`` new
    undirected edges between existing nodes, and grows the graph by
    ``nodes_per_batch`` nodes wired to ``nbrs_per_node`` random
    neighbors each with class-prototype-free random features — the
    mutation mix an evolving production graph sees. Batches track the
    evolving edge set so a schedule is always applicable in order:
    no double-deletes, no duplicate adds, and later batches may touch
    earlier batches' nodes. Fully determined by (g, sizes, seed).

    Returns a list of :class:`pipegcn_tpu.stream.DeltaBatch`.
    """
    from ..stream.deltas import DeltaBatch

    rng = np.random.default_rng(seed)
    num_nodes = g.num_nodes
    label = np.asarray(g.ndata["label"])
    multilabel = label.ndim == 2
    n_class = label.shape[1] if multilabel else int(label.max()) + 1
    n_feat = int(g.ndata["feat"].shape[1])
    cap = num_nodes + n_batches * nodes_per_batch  # fused-key base

    nondir = g.src < g.dst  # one representative per undirected edge
    keys = set((g.src[nondir].astype(np.int64) * cap
                + g.dst[nondir]).tolist())

    batches = []
    for bi in range(n_batches):
        # ---- deletions: sample existing undirected pairs ------------
        pool = np.fromiter(keys, np.int64, len(keys))
        pool.sort()  # set order is not deterministic across runs
        n_del = min(dels_per_batch, pool.size)
        dele = []
        if n_del:
            picked = pool[rng.choice(pool.size, size=n_del,
                                     replace=False)]
            for k in picked:
                u, v = int(k // cap), int(k % cap)
                dele += [[u, v], [v, u]]
                keys.discard(int(k))

        # ---- new nodes ----------------------------------------------
        node_feat = rng.normal(
            0.0, 1.0, size=(nodes_per_batch, n_feat)).astype(np.float32)
        if multilabel:
            node_label = np.zeros((nodes_per_batch, n_class), np.float32)
            node_label[np.arange(nodes_per_batch),
                       rng.integers(0, n_class, nodes_per_batch)] = 1.0
        else:
            node_label = rng.integers(
                0, n_class, nodes_per_batch).astype(np.int64)
        nbrs = []
        for i in range(nodes_per_batch):
            k = min(nbrs_per_node, num_nodes)
            nb = rng.choice(num_nodes, size=k, replace=False)
            nbrs.append(np.sort(nb).astype(np.int64))
            u = num_nodes + i
            for v in nb:
                keys.add(int(min(u, v)) * cap + int(max(u, v)))
        num_nodes += nodes_per_batch

        # ---- additions: fresh undirected pairs ----------------------
        add = []
        tries = 0
        while len(add) < 2 * edges_per_batch and tries < 50:
            tries += 1
            a = int(rng.integers(0, num_nodes))
            b = int(rng.integers(0, num_nodes))
            if a == b:
                continue
            k = min(a, b) * cap + max(a, b)
            if k in keys:
                continue
            keys.add(k)
            add += [[a, b], [b, a]]

        batches.append(DeltaBatch.make(
            seq=start_seq + bi,
            add_edges=np.asarray(add, np.int64).reshape(-1, 2),
            del_edges=np.asarray(dele, np.int64).reshape(-1, 2),
            node_feat=node_feat,
            node_label=node_label,
            node_nbrs=nbrs,
        ))
    return batches


_KARATE_EDGES = [
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8), (0, 10),
    (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21), (0, 31), (1, 2),
    (1, 3), (1, 7), (1, 13), (1, 17), (1, 19), (1, 21), (1, 30), (2, 3),
    (2, 7), (2, 8), (2, 9), (2, 13), (2, 27), (2, 28), (2, 32), (3, 7),
    (3, 12), (3, 13), (4, 6), (4, 10), (5, 6), (5, 10), (5, 16), (6, 16),
    (8, 30), (8, 32), (8, 33), (9, 33), (13, 33), (14, 32), (14, 33),
    (15, 32), (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32), (23, 33),
    (24, 25), (24, 27), (24, 31), (25, 31), (26, 29), (26, 33), (27, 33),
    (28, 31), (28, 33), (29, 32), (29, 33), (30, 32), (30, 33), (31, 32),
    (31, 33), (32, 33),
]

_KARATE_LABELS = np.array(
    [0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 0, 0, 0, 0, 1, 1, 0, 0, 1, 0, 1, 0, 1,
     1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1],
    dtype=np.int64,
)


def karate_club(n_feat: int = 8, seed: int = 0) -> Graph:
    """Zachary's karate club (34 nodes) with random features — the smallest
    integration-test graph. Labels are the canonical 2-community split."""
    rng = np.random.default_rng(seed)
    e = np.array(_KARATE_EDGES, dtype=np.int64)
    src = np.concatenate([e[:, 0], e[:, 1]])
    dst = np.concatenate([e[:, 1], e[:, 0]])
    n = 34
    feat = rng.normal(size=(n, n_feat)).astype(np.float32)
    feat[:, 0] = _KARATE_LABELS * 2.0 - 1.0  # make it learnable
    train_mask = np.zeros(n, dtype=bool)
    train_mask[rng.permutation(n)[:20]] = True
    val_mask = ~train_mask
    g = Graph(
        num_nodes=n,
        src=src,
        dst=dst,
        ndata={
            "feat": feat,
            "label": _KARATE_LABELS.copy(),
            "train_mask": train_mask,
            "val_mask": val_mask,
            "test_mask": val_mask.copy(),
        },
    )
    return finalize(g)
