"""Host-side graph container.

Replaces the reference's DGL graph objects (C++ backed, reference
helper/utils.py:74-96, train.py:113-131) with plain numpy COO/CSR arrays.
All graph preprocessing (loading, self-loop normalization, partitioning,
halo indexing) happens on host in numpy; only static-shaped padded arrays
ever reach the device.

Edge (src, dst) means a message flows src -> dst: aggregation at `dst`
sums features of its in-neighbors `src` (the semantics of DGL
`update_all(copy_src, sum)` in reference module/layer.py:47-49).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np


@dataclasses.dataclass
class Graph:
    """A directed graph in COO form with per-node data.

    Attributes:
        num_nodes: node count N.
        src, dst: int32/int64 arrays of shape [E]; message direction src->dst.
        ndata: dict of per-node arrays, each with leading dimension N.
            Conventional keys: 'feat' [N, F] float32, 'label' [N] int or
            [N, C] float multi-label, 'train_mask'/'val_mask'/'test_mask'
            [N] bool, 'in_deg' [N] float32 (full-graph in-degrees,
            reference helper/utils.py:142).
    """

    num_nodes: int
    src: np.ndarray
    dst: np.ndarray
    ndata: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    def validate(self) -> None:
        assert self.src.shape == self.dst.shape
        if self.num_edges:
            assert int(self.src.max()) < self.num_nodes
            assert int(self.dst.max()) < self.num_nodes
            assert int(self.src.min()) >= 0 and int(self.dst.min()) >= 0
        for k, v in self.ndata.items():
            assert v.shape[0] == self.num_nodes, (k, v.shape, self.num_nodes)

    # ---- degrees ----------------------------------------------------------

    def in_degrees(self) -> np.ndarray:
        """In-degree per node (number of messages each dst receives)."""
        return np.bincount(self.dst, minlength=self.num_nodes).astype(np.int64)

    def out_degrees(self) -> np.ndarray:
        return np.bincount(self.src, minlength=self.num_nodes).astype(np.int64)

    # ---- CSR views --------------------------------------------------------

    def in_csr(self):
        """CSR over in-edges: (indptr [N+1], src_indices [E], edge_ids [E]).

        Row i of the CSR lists the source nodes of edges pointing *into*
        node i. `edge_ids` maps CSR positions back to COO positions.
        """
        order = np.argsort(self.dst, kind="stable")
        indptr = np.zeros(self.num_nodes + 1, dtype=np.int64)
        np.cumsum(np.bincount(self.dst, minlength=self.num_nodes), out=indptr[1:])
        return indptr, self.src[order], order

    def out_csr(self):
        """CSR over out-edges: (indptr [N+1], dst_indices [E], edge_ids [E])."""
        order = np.argsort(self.src, kind="stable")
        indptr = np.zeros(self.num_nodes + 1, dtype=np.int64)
        np.cumsum(np.bincount(self.src, minlength=self.num_nodes), out=indptr[1:])
        return indptr, self.dst[order], order

    # ---- transforms -------------------------------------------------------

    def node_subgraph(self, nodes: np.ndarray) -> "Graph":
        """Node-induced subgraph with relabeled node IDs.

        `nodes` is an int array of node IDs (order defines new labels) or a
        boolean mask of length N. ndata rows are sliced accordingly.
        Equivalent of DGL `node_subgraph` used at reference train.py:117 and
        helper/utils.py:226-230 (inductive split).
        """
        nodes = np.asarray(nodes)
        if nodes.dtype == np.bool_:
            nodes = np.nonzero(nodes)[0]
        new_id = np.full(self.num_nodes, -1, dtype=np.int64)
        new_id[nodes] = np.arange(nodes.shape[0], dtype=np.int64)
        keep = (new_id[self.src] >= 0) & (new_id[self.dst] >= 0)
        sub = Graph(
            num_nodes=int(nodes.shape[0]),
            src=new_id[self.src[keep]],
            dst=new_id[self.dst[keep]],
            ndata={k: v[nodes] for k, v in self.ndata.items()},
        )
        if "in_deg" in sub.ndata:
            # derived data: recompute for the induced graph rather than
            # keeping the full-graph degrees sliced above
            sub.ndata["in_deg"] = sub.in_degrees().astype(np.float32)
        return sub

    def copy(self) -> "Graph":
        return Graph(
            num_nodes=self.num_nodes,
            src=self.src.copy(),
            dst=self.dst.copy(),
            ndata={k: v.copy() for k, v in self.ndata.items()},
        )


def remove_self_loops(g: Graph) -> Graph:
    keep = g.src != g.dst
    return Graph(g.num_nodes, g.src[keep], g.dst[keep], dict(g.ndata))


def add_self_loops(g: Graph) -> Graph:
    loop = np.arange(g.num_nodes, dtype=g.src.dtype)
    return Graph(
        g.num_nodes,
        np.concatenate([g.src, loop]),
        np.concatenate([g.dst, loop]),
        dict(g.ndata),
    )


def normalize_self_loops(g: Graph) -> Graph:
    """Ensure exactly one self-loop per node: remove all, then add one.

    Mirrors the reference's canonicalization applied to every dataset
    (helper/utils.py:94-95: `remove_self_loop` then `add_self_loop`).
    """
    return add_self_loops(remove_self_loops(g))


def finalize(g: Graph) -> Graph:
    """Canonicalize a freshly-loaded graph: one self-loop per node, validated,
    with full-graph in-degrees precomputed into ndata['in_deg'] (the degrees
    used for mean aggregation, reference helper/utils.py:142)."""
    g = normalize_self_loops(g)
    g.ndata["in_deg"] = g.in_degrees().astype(np.float32)
    g.validate()
    return g
