"""Elastic membership supervisor CLI.

    python -m pipegcn_tpu.cli.elastic [supervisor flags] -- <train flags>

Everything after ``--`` is a verbatim ``cli.main`` flag list (it must
include ``--checkpoint-dir``); the supervisor launches the fleet,
watches for rank death, redistributes partitions over the survivors
and relaunches from the last good checkpoint (docs/RESILIENCE.md,
"Elastic membership"). Exit code: 0 when training completed, 75 when
the supervisor stopped resumably (max-restarts / restart-storm /
SIGTERM) with the last checkpoint intact.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..resilience.elastic import ElasticConfig, ElasticSupervisor


def create_elastic_parser() -> argparse.ArgumentParser:
    d = ElasticConfig()
    ap = argparse.ArgumentParser(
        prog="pipegcn_tpu.cli.elastic",
        description="Supervise a multi-rank run; redistribute partitions "
                    "over survivors when a rank dies.")
    ap.add_argument("--max-restarts", type=int, default=d.max_restarts,
                    help="hard cap on lifetime relaunches before a "
                         "resumable stop (default %(default)s)")
    ap.add_argument("--backoff-base", type=float, default=d.backoff_base_s,
                    help="first relaunch delay, seconds; doubles per "
                         "consecutive restart (default %(default)s)")
    ap.add_argument("--backoff-max", type=float, default=d.backoff_max_s,
                    help="relaunch delay ceiling, seconds "
                         "(default %(default)s)")
    ap.add_argument("--storm-window", type=float, default=d.storm_window_s,
                    help="restart-storm sliding window, seconds "
                         "(default %(default)s)")
    ap.add_argument("--storm-threshold", type=int,
                    default=d.storm_threshold,
                    help="restarts inside the window that trip the "
                         "circuit breaker (default %(default)s)")
    ap.add_argument("--stable-s", type=float, default=d.stable_s,
                    help="a generation surviving this long resets the "
                         "backoff exponent (default %(default)s)")
    ap.add_argument("--grace-extra", type=float, default=d.grace_extra_s,
                    help="seconds past the watchdog horizon before "
                         "wedged survivors are culled "
                         "(default %(default)s)")
    ap.add_argument("--metrics-out", default="",
                    help="supervisor membership-record JSONL (default: "
                         "<coord dir>/membership.jsonl)")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        create_elastic_parser().print_usage(sys.stderr)
        print("error: expected '-- <cli.main train flags>' after the "
              "supervisor flags", file=sys.stderr)
        return 2
    split = argv.index("--")
    sup_argv, train_argv = argv[:split], argv[split + 1:]
    sup = create_elastic_parser().parse_args(sup_argv)
    cfg = ElasticConfig(
        max_restarts=sup.max_restarts,
        backoff_base_s=sup.backoff_base,
        backoff_max_s=sup.backoff_max,
        storm_window_s=sup.storm_window,
        storm_threshold=sup.storm_threshold,
        stable_s=sup.stable_s,
        grace_extra_s=sup.grace_extra,
        metrics_out=sup.metrics_out)
    try:
        return ElasticSupervisor(train_argv, cfg).run()
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
