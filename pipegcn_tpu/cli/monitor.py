"""Live monitoring CLI (docs/OBSERVABILITY.md "Live monitoring").

    python -m pipegcn_tpu.cli.monitor <run-dir|stem|file> \
        [--serve-http PORT] [--follow] [--alert-rules rules.json] \
        [--alerts-out alerts.jsonl] [--poll-s 1.0] [--duration-s N]

Tail-follows every metrics JSONL stream the target names (per-
generation elastic files, the supervisor ledger, replica streams,
window.jsonl — discovered live as they appear, obs/live.py), evaluates
the SLO alert rules each tick (edge-triggered `alert` records into
--alerts-out, obs/health.py), and optionally serves /metrics
(Prometheus text) + /health (JSON) on --serve-http.

--follow prints a one-line snapshot per tick; --once does a single
poll + evaluate, prints the /health JSON, and exits (the scriptable
drill mode). Exit code: 0, or 2 with --once when a page-severity
alert is firing (so shell drills can assert on health)."""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Optional, Sequence

from ..obs.health import (AlertEngine, MonitorServer, health_json,
                          load_rules)
from ..obs.live import LiveAggregator
from ..obs.metrics import MetricsLogger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pipegcn_tpu.cli.monitor",
        description="Live telemetry monitor: tail-follow a run's "
                    "metrics streams, evaluate SLO alerts, export "
                    "/metrics + /health")
    p.add_argument("target",
                   help="run directory, metrics stem, or JSONL file")
    p.add_argument("--serve-http", type=int, default=None,
                   metavar="PORT",
                   help="serve /metrics (Prometheus text) and /health "
                        "(JSON) on this port (0 = ephemeral; the "
                        "bound port is printed)")
    p.add_argument("--follow", action="store_true",
                   help="print a one-line snapshot every poll tick")
    p.add_argument("--once", action="store_true",
                   help="single poll + alert evaluation, print the "
                        "/health JSON, exit (rc 2 if a page-severity "
                        "alert is firing)")
    p.add_argument("--poll-s", type=float, default=1.0,
                   help="tail-follow / alert evaluation cadence")
    p.add_argument("--duration-s", type=float, default=0.0,
                   help="stop after this long (0 = run until "
                        "interrupted)")
    p.add_argument("--alert-rules", default=None, metavar="RULES.JSON",
                   help="JSON list of alert rule entries "
                        "({'rule': id, ...overrides}); default: the "
                        "built-in rule set (obs/health.RULE_DEFAULTS)")
    p.add_argument("--alerts-out", default=None, metavar="PATH",
                   help="JSONL sink for the contracted alert records "
                        "(default: <target-dir>/alerts.jsonl; '-' "
                        "disables the sink, alerts still print)")
    return p


def _alerts_path(target: str, flag: Optional[str]) -> Optional[str]:
    if flag == "-":
        return None
    if flag:
        return flag
    d = target if os.path.isdir(target) else (os.path.dirname(
        os.path.abspath(target)) or ".")
    return os.path.join(d, "alerts.jsonl")


def _follow_line(agg: LiveAggregator, engine: AlertEngine) -> str:
    snap = agg.snapshot()
    bits = [f"streams={snap['n_streams']}",
            f"records={snap['n_records']}"]
    train = snap.get("train") or {}
    if train:
        src, t = sorted(train.items())[-1]
        bits.append(f"epoch={t.get('epoch')} "
                    f"loss={t.get('loss'):.4f}"
                    if isinstance(t.get("loss"), float)
                    else f"epoch={t.get('epoch')}")
    serving = snap.get("serving") or {}
    if serving:
        agg_qps = sum(v.get("qps") or 0.0 for v in serving.values())
        bits.append(f"qps={agg_qps:.1f}")
    if snap["fault_counts"]:
        bits.append("faults=" + ",".join(
            f"{k}:{v}" for k, v in sorted(snap["fault_counts"].items())))
    firing = engine.firing()
    bits.append(f"alerts={len(firing)}"
                + ("" if not firing else
                   " [" + " ".join(f"{a['rule']}@{a['source']}"
                                   for a in firing) + "]"))
    return "monitor: " + " ".join(bits)


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    rules = load_rules(args.alert_rules)
    alerts_path = _alerts_path(args.target, args.alerts_out)
    ml = MetricsLogger(alerts_path) if alerts_path else None
    agg = LiveAggregator(args.target)
    engine = AlertEngine(rules, ml=ml)
    lock = threading.Lock()

    server = None
    if args.serve_http is not None:
        server = MonitorServer(
            agg, engine,
            sink_stats=(ml.stats if ml is not None else None),
            port=args.serve_http, lock=lock).start()
        print(f"monitor: serving /metrics and /health on "
              f"http://127.0.0.1:{server.port}")

    rc = 0
    t_end = (time.monotonic() + args.duration_s
             if args.duration_s > 0 else float("inf"))
    try:
        while True:
            with lock:
                agg.poll()
                edges = engine.evaluate(agg)
            for e in edges:
                print(f"monitor: ALERT {e['state'].upper()} "
                      f"{e['rule']} source={e['source']}: "
                      f"{e['message']}")
            if args.follow:
                print(_follow_line(agg, engine))
            if args.once:
                print(json.dumps(health_json(
                    agg, engine, ml.stats() if ml else None), indent=2))
                rc = 2 if health_json(agg, engine)["status"] \
                    == "critical" else 0
                break
            if time.monotonic() >= t_end:
                break
            time.sleep(args.poll_s)
    except KeyboardInterrupt:
        pass
    finally:
        if server is not None:
            server.stop()
        if ml is not None:
            ml.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
