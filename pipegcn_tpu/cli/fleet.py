"""Serving-fleet entrypoint: `python -m pipegcn_tpu.cli.fleet`.

Two modes sharing one parser:

  driver (default)       resolves the partition artifact once, launches
                         --replicas N replica subprocesses (each a full
                         CPU/TPU mesh), waits for their readiness
                         files, fronts them with the failover Router,
                         and drives the open-loop fleet load loop
                         (serve/fleet.py). SIGTERM/SIGINT drain: every
                         accepted ticket is served by a survivor or
                         explicitly shed before the final record.

  replica (--replica-id K)  builds the ServingEngine exactly like
                         cli/serve.py (same flags — the driver forwards
                         its own argv) and serves it over TCP with
                         heartbeats + the zero-downtime checkpoint
                         hot-swap watcher. Its metrics land in
                         <fleet-dir>/replica-mK-iI-metrics.jsonl.

The replica-kill@W[:mK] entries of --fault-plan fire at serving-window
boundaries in the driver (SIGKILL replica K at window W), which is how
scripts/chaos.sh's fleet lane drills the failover path.
"""

from __future__ import annotations

import json
import os
import signal
import sys

from .serve import build_parser as _serve_build_parser


def build_parser():
    p = _serve_build_parser()
    g = p.add_argument_group("fleet")
    g.add_argument("--replicas", type=int, default=1,
                   help="number of serving replicas (each its own "
                        "process + mesh)")
    g.add_argument("--replica-id", "--replica_id", type=int, default=-1,
                   help="INTERNAL: run as replica K instead of the "
                        "driver")
    g.add_argument("--incarnation", type=int, default=0,
                   help="INTERNAL: relaunch count of this replica slot")
    g.add_argument("--fleet-dir", "--fleet_dir", type=str, default="",
                   help="shared directory for readiness files, "
                        "heartbeats, and per-replica logs "
                        "(default: <partition-dir>/fleet)")
    g.add_argument("--fleet-policy", "--fleet_policy", type=str,
                   default="least-queue", choices=("least-queue", "hash"),
                   help="router placement: least in-flight rows, or "
                        "consistent-hash on the batch's first node id")
    g.add_argument("--fleet-swap-poll", "--fleet_swap_poll", type=float,
                   default=0.5,
                   help="seconds between replica checkpoint-watcher "
                        "polls (zero-downtime hot-swap cadence)")
    g.add_argument("--fleet-heartbeat-timeout",
                   "--fleet_heartbeat_timeout", type=float, default=3.0,
                   help="replica heartbeat silence that counts as death")
    g.add_argument("--fleet-retry-timeout", "--fleet_retry_timeout",
                   type=float, default=5.0,
                   help="per-batch failover retry budget before the "
                        "batch is shed")
    g.add_argument("--fleet-max-restarts", "--fleet_max_restarts",
                   type=int, default=4,
                   help="lifetime relaunch cap per replica slot")
    g.add_argument("--fleet-ready-timeout", "--fleet_ready_timeout",
                   type=float, default=180.0,
                   help="seconds to wait for a replica's readiness file")
    a = p.add_argument_group("autoscale")
    a.add_argument("--autoscale", action="store_true",
                   help="close the loop: feed each window's telemetry "
                        "(+ alert fire edges) to the scale policy "
                        "(serve/autoscale.py) and let it spawn/retire "
                        "replicas between --autoscale-min/max; turns "
                        "on the graceful-degradation admission ladder")
    a.add_argument("--autoscale-min", "--autoscale_min", type=int,
                   default=1, help="replica floor under scale-down")
    a.add_argument("--autoscale-max", "--autoscale_max", type=int,
                   default=0,
                   help="replica ceiling under scale-up "
                        "(0 = max(4, --replicas))")
    a.add_argument("--autoscale-queue-high", "--autoscale_queue_high",
                   type=int, default=0,
                   help="queue rows that count as sustained pressure "
                        "(0 = half of --serve-max-queue, else 64)")
    a.add_argument("--autoscale-queue-low", "--autoscale_queue_low",
                   type=int, default=0,
                   help="queue rows below which a window counts as "
                        "idle (0 = an eighth of --serve-max-queue, "
                        "else 8)")
    a.add_argument("--autoscale-shed-high", "--autoscale_shed_high",
                   type=float, default=0.01,
                   help="window shed fraction that triggers an "
                        "immediate scale-up")
    a.add_argument("--autoscale-p99-slo", "--autoscale_p99_slo",
                   type=float, default=0.0,
                   help="p99 latency SLO in ms; sustained violation "
                        "triggers scale-up (0 = no latency trigger)")
    a.add_argument("--autoscale-cooldown", "--autoscale_cooldown",
                   type=float, default=10.0,
                   help="seconds between executed scale actions (the "
                        "anti-flap brake and the ramp rate)")
    a.add_argument("--degrade-ladder", "--degrade_ladder",
                   action="store_true",
                   help="graceful-degradation admission ladder without "
                        "autoscaling: tighten the effective queue "
                        "bound and ticket deadline as pressure rises "
                        "(brownout before blackout)")
    return p


def _replica_main(args) -> int:
    """Child mode: one serving replica process."""
    from ..obs import MetricsLogger
    from ..serve.fleet import ReplicaServer
    from .serve import build_serving_engine

    if not args.fleet_dir:
        raise ValueError("--replica-id requires --fleet-dir")
    os.makedirs(args.fleet_dir, exist_ok=True)
    rid, inc = args.replica_id, args.incarnation

    def log(msg):
        print(f"[replica {rid} i{inc}] {msg}", flush=True)

    # replicas never build the artifact (the driver did; N builders
    # would race) — they await it like any late-joining server
    args.serve_build = False
    trainer, engine, _epoch = build_serving_engine(args, log=log)

    ml = MetricsLogger(os.path.join(
        args.fleet_dir, f"replica-m{rid}-i{inc}-metrics.jsonl"))
    ml.run_header(config={"replica": rid, "incarnation": inc,
                          "n_partitions": args.n_partitions})

    # crash-consistent streaming: when a durable delta journal exists
    # (the trainer's WAL, stream/journal.py), the replica replays every
    # journaled topology delta against its freshly-loaded NOMINAL
    # artifact BEFORE publishing readiness — the fleet never routes to
    # a replica serving a stale graph. The callable runs inside
    # serve_forever, after the port binds but before the ready file.
    journal_dir = getattr(args, "journal_dir", "") or (
        os.path.join(args.checkpoint_dir, "journal")
        if args.checkpoint_dir else "")
    replay = None
    if journal_dir and os.path.isdir(journal_dir):
        def replay():
            import numpy as np

            from ..graph.datasets import load_data
            from ..stream import DeltaJournal, GraphPatcher

            journal = DeltaJournal(journal_dir)
            entries = journal.entries()
            if not entries:
                return 0
            # the patcher needs the host graph + partition assignment
            # the artifact was built from: reload the dataset (replicas
            # share the driver's flags, so this is the same graph) and
            # derive the assignment from the shard's global-id rows
            g = load_data(args.dataset, args.data_root)
            sg = trainer.sg
            parts = np.zeros(g.num_nodes, np.int32)
            for p in range(sg.num_parts):
                n = int(sg.inner_count[p])
                parts[np.asarray(sg.global_nid[p, :n])] = p
            patcher = GraphPatcher(
                g, sg, parts,
                slack=getattr(args, "stream_slack", 0.10))
            trainer.enable_stream(patcher)
            for _gen, batch in entries:
                rep = trainer.apply_graph_deltas(batch)
                engine.apply_graph_deltas(rep)
            engine.refresh_boundary()
            return len(entries)

    server = ReplicaServer(
        engine, args.fleet_dir, rid, incarnation=inc, ml=ml,
        checkpoint_dir=args.checkpoint_dir or None,
        swap_poll_s=args.fleet_swap_poll,
        report_every_s=args.serve_report_every, replay=replay, log=log)

    def _on_signal(signum, frame):  # noqa: ARG001
        server.request_stop()

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_signal)
    try:
        server.serve_forever()
    finally:
        ml.close()
    return 0


def _driver_main(args, argv) -> int:
    from ..resilience.faults import FaultPlan
    from ..serve.fleet import FleetManager, run_fleet_loop
    from ..serve.router import Router
    from .serve import _load_partition

    import numpy as np

    if args.replicas < 1:
        raise ValueError("--replicas must be >= 1")
    fleet_dir = args.fleet_dir or os.path.join(
        args.partition_dir, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)

    # resolve (and, under --serve-build, build) the artifact ONCE
    # before any replica launches — the replicas then just load it
    sg = _load_partition(args)
    num_nodes = int((np.asarray(sg.global_nid) >= 0).sum())

    ml = None
    if args.metrics_out:
        from ..obs import MetricsLogger

        ml = MetricsLogger(args.metrics_out)
        ml.run_header(config=vars(args),
                      mesh={"n_parts": args.n_partitions,
                            "replicas": args.replicas})

    # children inherit the environment; make sure the virtual-device
    # trick covers the mesh when nobody set XLA_FLAGS explicitly
    env = dict(os.environ)
    if "xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count="
            f"{args.n_partitions}").strip()
    env.setdefault("PIPEGCN_PLATFORM",
                   os.environ.get("PIPEGCN_PLATFORM", "cpu"))
    env.setdefault("JAX_PLATFORMS", env["PIPEGCN_PLATFORM"])

    manager = FleetManager(
        fleet_dir, args.replicas, child_args=list(argv), ml=ml,
        env=env, heartbeat_timeout_s=args.fleet_heartbeat_timeout,
        ready_timeout_s=args.fleet_ready_timeout,
        max_restarts=args.fleet_max_restarts)
    clients = manager.launch_all()

    def on_fault(rid, reason):
        # one replica-dead + one kind="fleet" fault per death edge,
        # whether the router's dispatch or the supervisor saw it first
        if ml is not None:
            ml.fleet("replica-dead", rid, window=manager.window,
                     reason=reason)
            ml.fault("fleet", epoch=max(manager.window, 0), rank=rid,
                     reason=reason)

    def on_failover(to_rid, n_rows, n_attempts):
        if ml is not None:
            ml.fleet("failover", to_rid, window=manager.window,
                     n_retried=n_rows, attempts=n_attempts)

    router = Router(clients, policy=args.fleet_policy,
                    retry_timeout_s=args.fleet_retry_timeout,
                    on_fault=on_fault, on_failover=on_failover)

    fault_plan = None
    if getattr(args, "fault_plan", None):
        fault_plan = FaultPlan.parse(args.fault_plan)

    # ---- the closed loop: telemetry -> policy -> fleet actuation ----
    autoscaler = None
    ladder = None
    alerts_fn = None
    if args.autoscale or args.degrade_ladder:
        from ..serve.batcher import AdmissionLadder

        ladder = AdmissionLadder()
    if args.autoscale:
        from ..serve.autoscale import AutoscalePolicy

        max_q = args.serve_max_queue or 0
        q_high = args.autoscale_queue_high or (max_q // 2 if max_q
                                               else 64)
        q_low = args.autoscale_queue_low or max(1, (max_q // 8
                                                    if max_q else 8))
        autoscaler = AutoscalePolicy(
            min_replicas=args.autoscale_min,
            max_replicas=args.autoscale_max or max(4, args.replicas),
            queue_high=q_high, queue_low=q_low,
            shed_high=args.autoscale_shed_high,
            p99_slo_ms=args.autoscale_p99_slo or None,
            cooldown_s=args.autoscale_cooldown)
        if ml is not None:
            # the AlertEngine leg of the loop: tail the driver's own
            # metrics stream (plus any sibling streams in its dir) and
            # surface fire edges as policy evidence
            import time as _time

            from ..obs.health import AlertEngine
            from ..obs.live import LiveAggregator

            _agg = LiveAggregator(
                os.path.dirname(os.path.abspath(args.metrics_out))
                or ".", clock=_time.time)
            _alert_engine = AlertEngine(clock=_time.time)

            def alerts_fn():
                _agg.poll()
                edges = _alert_engine.evaluate(_agg)
                return [e["rule"] for e in edges
                        if e.get("state") == "fire"]

    stop_flag = {"stop": False}

    def _on_signal(signum, frame):  # noqa: ARG001
        stop_flag["stop"] = True

    old = [signal.signal(s, _on_signal)
           for s in (signal.SIGTERM, signal.SIGINT)]
    try:
        summary = run_fleet_loop(
            manager, router,
            num_nodes=num_nodes,
            duration_s=args.serve_duration,
            qps=args.serve_qps,
            max_batch=args.serve_max_batch,
            max_delay_ms=args.serve_max_delay_ms,
            ladder_min=args.serve_ladder_min,
            report_every_s=args.serve_report_every,
            max_queue=args.serve_max_queue or None,
            ticket_deadline_ms=args.serve_ticket_deadline_ms or None,
            seed=args.seed,
            ml=ml,
            fault_plan=fault_plan,
            traffic=args.traffic or None,
            update_fraction=args.update_fraction,
            ladder=ladder,
            autoscaler=autoscaler,
            alerts_fn=alerts_fn,
            trace_sample_rate=args.trace_sample_rate,
            stop=lambda: stop_flag["stop"],
        )
    finally:
        for s, h in zip((signal.SIGTERM, signal.SIGINT), old):
            signal.signal(s, h)
        manager.stop_all()
        if ml is not None:
            ml.close()
    print(json.dumps({"fleet": True, "replicas": args.replicas,
                      **summary}))
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    args = build_parser().parse_args(argv)
    if args.replica_id >= 0:
        return _replica_main(args)
    return _driver_main(args, argv)


if __name__ == "__main__":
    sys.exit(main())
