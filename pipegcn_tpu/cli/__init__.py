from .parser import create_parser
from .main import run, cli_entry

__all__ = ["create_parser", "run", "cli_entry"]
