"""Online serving entrypoint: `python -m pipegcn_tpu.cli.serve`.

Loads (or awaits) the partition artifact, builds the Trainer purely as
the host of the mesh + tuned kernel tables + sharded data, optionally
restores trained params from --checkpoint-dir, then hands everything to
the serve/ runtime: compiled-once ServingEngine, micro-batched query
path, incremental halo freshness, and a synthetic open-loop load
generator emitting schema-v5 `serving` records (docs/SERVING.md).

SIGTERM/SIGINT request a graceful stop: the loop drains every accepted
query, emits a hard-flushed final `serving` record (`final: true`), and
exits 0 — the contract the scripts/chaos.sh serving lane kills a live
process to verify.
"""

from __future__ import annotations

import json
import os
import signal
import sys

from .parser import create_parser


def build_parser():
    p = create_parser()
    g = p.add_argument_group("serving")
    g.add_argument("--serve-duration", "--serve_duration", type=float,
                   default=10.0,
                   help="seconds of open-loop load to serve")
    g.add_argument("--serve-qps", "--serve_qps", type=float, default=50.0,
                   help="target query arrival rate (open-loop Poisson)")
    g.add_argument("--serve-max-batch", "--serve_max_batch", type=int,
                   default=64, help="top of the padded batch ladder")
    g.add_argument("--serve-max-delay-ms", "--serve_max_delay_ms",
                   type=float, default=5.0,
                   help="max queueing delay before a partial batch "
                        "flushes (latency-vs-fill tradeoff)")
    g.add_argument("--serve-ladder-min", "--serve_ladder_min", type=int,
                   default=8, help="bottom of the padded batch ladder")
    g.add_argument("--serve-report-every", "--serve_report_every",
                   type=float, default=2.0,
                   help="seconds between `serving` metric records")
    g.add_argument("--serve-refresh-every", "--serve_refresh_every",
                   type=float, default=0.5,
                   help="seconds between logits recomputes (bounded-"
                        "staleness window)")
    g.add_argument("--serve-update-every", "--serve_update_every",
                   type=float, default=0.0,
                   help="seconds between synthetic feature-update "
                        "churn batches (0 disables)")
    g.add_argument("--serve-update-rows", "--serve_update_rows",
                   type=int, default=32,
                   help="rows per synthetic update batch")
    g.add_argument("--serve-artifact-timeout", "--serve_artifact_timeout",
                   type=float, default=600.0,
                   help="seconds to wait for a missing partition "
                        "artifact before giving up")
    g.add_argument("--serve-build", "--serve_build", action="store_true",
                   help="build the partition artifact locally when "
                        "missing instead of awaiting it")
    g.add_argument("--serve-max-queue", "--serve_max_queue", type=int,
                   default=0,
                   help="bound on queued query rows; overload sheds "
                        "tickets (counted as `shed`) instead of "
                        "growing the queue. 0 = unbounded")
    g.add_argument("--serve-ticket-deadline-ms",
                   "--serve_ticket_deadline_ms", type=float, default=0.0,
                   help="shed tickets that waited past this deadline "
                        "at flush time. 0 = no deadline")
    g.add_argument("--traffic", type=str, default="",
                   help="shaped open-loop arrival schedule "
                        "(serve/loadgen.RateShape): constant | "
                        "diurnal[:period[:floor]] | "
                        "flash-crowd[:mult[:t0[:t1]]] | trace:<path>. "
                        "Empty = legacy constant-rate Poisson")
    g.add_argument("--update-fraction", "--update_fraction",
                   type=float, default=0.0,
                   help="fraction of arrivals that are feature UPDATES "
                        "instead of queries (mixed workload; seeded "
                        "per arrival). 0 = query-only")
    g.add_argument("--trace-sample-rate", "--trace_sample_rate",
                   type=float, default=0.0,
                   help="fraction of submitted queries that mint a "
                        "trace id and land per-hop `span` records "
                        "(queue/dispatch, rpc, replica, engine) in "
                        "the metrics stream; cli.timeline renders "
                        "them as Perfetto flows. 0 = tracing off")
    return p


def _load_partition(args):
    """Resolve the partition artifact exactly like training's
    prepare(), but a missing path AWAITS (the shared-filesystem backoff
    poll) or builds under --serve-build — a serving replica must not
    crash because it raced the partitioner."""
    from ..partition.halo import ShardedGraph
    from .main import _await_partition_artifact, derive_graph_name

    graph_name = args.graph_name or derive_graph_name(args)
    from ..partition.partitioner import cluster_suffix

    csuf = "-c" + cluster_suffix(args.cluster_size) \
        if args.local_reorder == "cluster" else ""
    part_path = os.path.join(args.partition_dir, graph_name + csuf)

    if ShardedGraph.exists(part_path):
        sg = ShardedGraph.load(part_path)
        if sg.num_parts != args.n_partitions:
            raise ValueError(
                f"partition artifact at {part_path} has {sg.num_parts} "
                f"parts, requested {args.n_partitions}")
        return sg
    if args.serve_build:
        from ..graph.datasets import load_data
        from ..partition.partitioner import (locality_clusters,
                                             partition_graph)

        g = load_data(args.dataset, args.data_root)
        seed = args.seed if args.fix_seed else 0
        parts = partition_graph(g, args.n_partitions,
                                method=args.partition_method,
                                obj=args.partition_obj, seed=seed)
        cluster = None
        if args.local_reorder == "cluster":
            cluster = locality_clusters(
                g, target_size=args.cluster_size, seed=seed)
        sg = ShardedGraph.build(g, parts, n_parts=args.n_partitions,
                                cluster=cluster)
        os.makedirs(args.partition_dir, exist_ok=True)
        sg.save(part_path)
        sg.cache_dir = part_path
        return sg
    return _await_partition_artifact(
        part_path, args.n_partitions,
        timeout_s=args.serve_artifact_timeout)


def build_serving_engine(args, log=print):
    """Everything between parsed args and a warm ServingEngine —
    shared by this entrypoint and each fleet replica process
    (cli/fleet.py --replica-id K). Returns (trainer, engine, epoch)
    where epoch is the restored checkpoint generation (-1 when serving
    freshly-initialized params); the engine's parameter-generation
    axis is already set to it."""
    if args.model not in ("graphsage", "gcn", "gat"):
        raise ValueError(f"unknown model: {args.model}")
    if args.model in ("gcn", "gat") and args.use_pp:
        raise ValueError("--use-pp is a GraphSAGE-only optimization")

    import jax

    plat = os.environ.get("PIPEGCN_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    from .main import _maybe_init_distributed

    _maybe_init_distributed(args)

    from ..models.sage import ModelConfig
    from ..parallel.trainer import TrainConfig, Trainer
    from ..serve import ServingEngine
    from ..utils.checkpoint import checkpoint_exists, load_checkpoint

    sg = _load_partition(args)
    n_feat = args.n_feat or sg.n_feat
    n_class = args.n_class or sg.n_class
    layer_sizes = (n_feat,) + (args.n_hidden,) * (args.n_layers - 1) \
        + (n_class,)
    cfg = ModelConfig(
        layer_sizes=layer_sizes,
        model=args.model,
        n_heads=args.n_heads,
        n_linear=args.n_linear,
        use_pp=args.use_pp,
        norm=None if args.norm == "none" else args.norm,
        dropout=args.dropout,
        train_size=args.n_train or sg.n_train_global,
        spmm_chunk=args.spmm_chunk or None,
        spmm_impl=args.spmm_impl,
        block_tile=args.block_tile,
        block_nnz=args.block_nnz or None,
        block_group=args.block_group,
        bucket_merge=args.bucket_merge,
        tune=args.tune,
        tuner_samples=args.tuner_samples,
        rem_dtype=args.rem_dtype,
        rem_amax=args.rem_amax,
        dropout_bits=args.dropout_bits,
        dtype=args.dtype,
    )
    # the trainer is only the serving substrate here: mesh, tuned kernel
    # tables, sharded data, params template. No epochs run.
    tcfg = TrainConfig(lr=args.lr, n_epochs=0,
                       enable_pipeline=False, seed=args.seed,
                       eval=False, halo_dtype=args.halo_dtype)
    trainer = Trainer(sg, cfg, tcfg)

    epoch = -1
    if args.checkpoint_dir and checkpoint_exists(args.checkpoint_dir):
        host_state, epoch = load_checkpoint(args.checkpoint_dir,
                                            trainer.host_state())
        trainer.restore_state(host_state)
        log(f"serving params restored from {args.checkpoint_dir} "
            f"(epoch {epoch})")
    elif args.checkpoint_dir:
        log(f"WARNING: no checkpoint in {args.checkpoint_dir!r}; "
            f"serving freshly-initialized params")

    engine = ServingEngine.for_trainer(
        trainer, max_batch=args.serve_max_batch,
        ladder_min=args.serve_ladder_min)
    engine.param_generation = int(epoch)
    warm_s = engine.warmup()
    log(f"serve: engine warm in {warm_s:.2f}s "
        f"(ladder {engine.ladder}, {engine.num_global_nodes} nodes, "
        f"{trainer.P} partitions)")
    return trainer, engine, epoch


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from ..serve import run_serving_loop

    trainer, engine, _epoch = build_serving_engine(args)

    ml = None
    if args.metrics_out:
        from ..obs import MetricsLogger, device_info, mesh_info

        ml = MetricsLogger(args.metrics_out)
        ml.run_header(config=vars(args), device=device_info(),
                      mesh={"n_parts": args.n_partitions,
                            **mesh_info(trainer.mesh)})

    stop_flag = {"stop": False}

    def _on_signal(signum, frame):  # noqa: ARG001
        stop_flag["stop"] = True

    old = [signal.signal(s, _on_signal)
           for s in (signal.SIGTERM, signal.SIGINT)]
    try:
        summary = run_serving_loop(
            engine,
            duration_s=args.serve_duration,
            qps=args.serve_qps,
            max_delay_ms=args.serve_max_delay_ms,
            report_every_s=args.serve_report_every,
            refresh_every_s=args.serve_refresh_every,
            update_every_s=args.serve_update_every,
            update_rows=args.serve_update_rows,
            seed=args.seed,
            ml=ml,
            traffic=args.traffic or None,
            update_fraction=args.update_fraction,
            max_queue=args.serve_max_queue or None,
            ticket_deadline_ms=args.serve_ticket_deadline_ms or None,
            trace_sample_rate=args.trace_sample_rate,
            stop=lambda: stop_flag["stop"],
        )
    finally:
        for s, h in zip((signal.SIGTERM, signal.SIGINT), old):
            signal.signal(s, h)
        if ml is not None:
            ml.close()
    print(json.dumps({"serve": True, **summary}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
