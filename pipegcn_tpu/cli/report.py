"""Reporting CLI over metrics JSONL files.

    python -m pipegcn_tpu.cli.report run1.jsonl [run2.jsonl ...] [--json]

Reads files written by the MetricsLogger sink (obs/metrics.py; schema
obs/schema.py) and emits a per-run summary: epoch-time statistics,
loss-curve deltas, gradient-norm tail, halo traffic, memory peak,
comm/compute overlap fraction and (when the run recorded FLOPs on a
known chip) MFU. `--json` emits one JSON object per file instead of
the human block — the form the bench trajectory consumes.

Everything is best-effort per field: a run that never measured comm
cost, or ran on a platform without memory stats, summarizes without
those rows rather than erroring (consumers must tolerate absent
fields, the schema contract)."""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Sequence

from ..obs.hw import peak_flops_for
from ..obs.metrics import read_metrics


def _median(xs: List[float]) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def summarize_run(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Collapse one run's records into the summary dict the CLI
    prints. Tolerates missing header/summary (partial files from
    crashed runs still summarize their epochs)."""
    header = next((r for r in records if r.get("event") == "run"), None)
    summary = next((r for r in records if r.get("event") == "summary"),
                   None)
    epochs = [r for r in records if r.get("event") == "epoch"]
    evals = [r for r in records if r.get("event") == "eval"]

    out: Dict[str, Any] = {"n_epoch_records": len(epochs),
                           "n_eval_records": len(evals)}
    if header:
        out["schema_version"] = header.get("schema_version")
        dev = header.get("device") or {}
        out["device"] = dev.get("device_kind") or dev.get("platform")
        out["n_devices"] = dev.get("n_devices")
        cfg = header.get("config") or {}
        # CLI headers carry args flat; trainer fallback headers nest
        # the TrainConfig under "train"
        out["pipeline"] = bool(
            cfg.get("enable_pipeline",
                    (cfg.get("train") or {}).get("enable_pipeline",
                                                 False)))

    bench = next((r for r in records if r.get("event") == "bench"), None)
    if bench:
        # bench.py --metrics-out: surface the headline measurement
        out["bench_metric"] = bench.get("metric")
        out["bench_value"] = bench.get("value")
        out["bench_unit"] = bench.get("unit")
        out["vs_baseline"] = bench.get("vs_baseline")
        if "pipeline" in bench:
            out["pipeline"] = bool(bench["pipeline"])
        # --reorder layout lever: which layout produced the number, how
        # contiguous its gather streams were, and (when the bench ran
        # its reorder_slab before/after pass) the measured deltas
        if bench.get("reorder") is not None:
            out["reorder"] = bench["reorder"]
        gc = bench.get("gather_contiguity")
        if isinstance(gc, dict):
            if isinstance(gc.get("mean_run_len"), (int, float)):
                out["gather_mean_run_len"] = round(gc["mean_run_len"], 4)
            if isinstance(gc.get("slab_frac"), (int, float)):
                out["gather_slab_frac"] = round(gc["slab_frac"], 4)
        for k in ("reorder_delta_s", "slab_delta_s"):
            if isinstance(bench.get(k), (int, float)):
                out[k] = bench[k]

    steps = [r["step_time_s"] for r in epochs
             if isinstance(r.get("step_time_s"), (int, float))]
    if steps:
        out["median_epoch_s"] = round(_median(steps), 6)
        out["mean_epoch_s"] = round(sum(steps) / len(steps), 6)
        out["total_step_s"] = round(sum(steps), 6)
    losses = [r["loss"] for r in epochs
              if isinstance(r.get("loss"), (int, float))]
    if losses:
        out["loss_first"] = round(losses[0], 6)
        out["loss_last"] = round(losses[-1], 6)
        out["loss_delta"] = round(losses[-1] - losses[0], 6)
    gnorms = [r["grad_norm"] for r in epochs
              if isinstance(r.get("grad_norm"), (int, float))]
    if gnorms:
        out["grad_norm_last"] = round(gnorms[-1], 6)
    halo = [r["halo_bytes"] for r in epochs
            if isinstance(r.get("halo_bytes"), int)]
    if halo:
        out["halo_bytes_per_epoch"] = max(halo)
    # --halo-dtype compression: epochs carry the uncompressed figure
    # alongside, so the report can show wire bytes before/after
    halo_unc = [r["halo_bytes_uncompressed"] for r in epochs
                if isinstance(r.get("halo_bytes_uncompressed"), int)]
    if halo_unc:
        out["halo_bytes_uncompressed_per_epoch"] = max(halo_unc)
        if halo and max(halo):
            out["halo_compression_ratio"] = round(
                max(halo_unc) / max(halo), 4)
    ages = [r["staleness_age"] for r in epochs
            if isinstance(r.get("staleness_age"), int)]
    if ages:
        out["staleness_age_max"] = max(ages)
    peaks = [(r.get("memory") or {}).get("peak_bytes_in_use")
             for r in epochs]
    peaks = [p for p in peaks if isinstance(p, int)]
    if peaks:
        out["memory_peak_bytes"] = max(peaks)

    faults = [r for r in records if r.get("event") == "fault"]
    recoveries = [r for r in records if r.get("event") == "recovery"]
    if faults:
        kinds: Dict[str, int] = {}
        for r in faults:
            k = str(r.get("kind"))
            kinds[k] = kinds.get(k, 0) + 1
        out["n_faults"] = len(faults)
        out["fault_kinds"] = kinds
        out["n_recoveries"] = len(recoveries)
        # multi-host attribution: which rank observed each fault, and
        # which raised the consensus-propagated ones (several ranks'
        # JSONL streams may be concatenated into one file)
        ranks: Dict[str, int] = {}
        sources: Dict[str, int] = {}
        agreed = 0
        for r in faults:
            if isinstance(r.get("rank"), int):
                ranks[f"r{r['rank']}"] = ranks.get(f"r{r['rank']}", 0) + 1
            if r.get("agreed"):
                agreed += 1
            src = r.get("source_rank")
            if isinstance(src, int) and src >= 0:
                sources[f"r{src}"] = sources.get(f"r{src}", 0) + 1
        if ranks:
            out["fault_ranks"] = ranks
        if sources:
            out["fault_source_ranks"] = sources
        if agreed:
            out["n_agreed_faults"] = agreed

    accs = [r["val_acc"] for r in evals
            if isinstance(r.get("val_acc"), (int, float))]
    if accs:
        out["best_val"] = round(max(accs), 6)
        out["final_val"] = round(accs[-1], 6)
    ets = [r["eval_time_s"] for r in evals
           if isinstance(r.get("eval_time_s"), (int, float))]
    if ets:
        out["mean_eval_s"] = round(sum(ets) / len(ets), 6)

    if summary:
        for k in ("best_val", "best_epoch", "test_acc", "n_epochs"):
            if summary.get(k) is not None:
                out[k] = summary[k]
        if summary.get("epoch_time_s") is not None:
            # fit()'s warmup-excluded mean beats the raw record median
            out["epoch_time_s"] = summary["epoch_time_s"]
        cc = summary.get("comm_cost") or {}
        comm_total = sum(v for v in cc.values()
                         if isinstance(v, (int, float)))
        base = out.get("epoch_time_s") or out.get("median_epoch_s")
        if cc and base:
            out["comm_cost_s"] = round(comm_total, 6)
            # standalone collective cost as a fraction of the epoch: in
            # pipelined mode this is the comm the staleness-1 carry
            # lets XLA overlap with compute (the exposed wait is ~0,
            # results/overlap_study.md); in vanilla mode it is an
            # upper bound on the exposed fraction
            out["comm_fraction"] = round(min(comm_total / base, 1.0), 4)
            if out.get("pipeline"):
                out["overlapped_comm_fraction"] = out["comm_fraction"]
        fl = summary.get("flops_per_epoch")
        base = out.get("epoch_time_s") or out.get("median_epoch_s")
        peak = peak_flops_for(str(out.get("device") or ""))
        nd = out.get("n_devices") or 1
        if isinstance(fl, (int, float)) and fl and base and peak:
            out["mfu_pct"] = round(100.0 * fl / (base * peak * nd), 2)

    # ---- measured profiling window (obs/profiler.py) ----
    profiles = [r for r in records if r.get("event") == "profile"]
    if profiles:
        p = profiles[-1]  # the freshest capture wins
        if isinstance(p.get("overlap_fraction"), (int, float)):
            out["measured_overlap_fraction"] = round(
                p["overlap_fraction"], 4)
        if isinstance(p.get("phases"), dict):
            out["profile_phases"] = p["phases"]
        for k in ("comm_s", "compute_s"):
            if isinstance(p.get(k), (int, float)):
                out[f"profile_{k}"] = p[k]
        win = (p.get("epoch_start"), p.get("epoch_end"))
        if all(isinstance(x, int) for x in win):
            out["profile_window"] = list(win)
        # the host-side estimate and the measured fraction describe
        # the same quantity; flag when they disagree materially so
        # the estimate is never trusted past its error
        est = out.get("overlapped_comm_fraction",
                      out.get("comm_fraction"))
        meas = out.get("measured_overlap_fraction")
        if isinstance(est, (int, float)) and isinstance(meas,
                                                        (int, float)):
            out["overlap_divergence"] = bool(abs(meas - est) > 0.25)

    # ---- training-path spans (obs/trainspan.py, schema v14) ----
    # the always-on span plane yields a MEASURED overlap verdict with
    # no profiler capture window, plus per-rank comm-wait share and
    # straggler attribution on the tracesync-aligned clock
    from ..obs.trainspan import fold_spans, train_spans

    if train_spans(records):
        fold = fold_spans(records)
        if fold.get("overlap_spans") is not None:
            out["overlap_spans"] = round(fold["overlap_spans"], 4)
        if fold.get("comm_wait_share_by_rank"):
            out["comm_wait_share_by_rank"] = {
                f"r{r}": round(v, 4)
                for r, v in fold["comm_wait_share_by_rank"].items()}
        if fold.get("straggler_max_gap_s") is not None:
            out["straggler_max_gap_s"] = fold["straggler_max_gap_s"]
            out["straggler_rank"] = fold["straggler_rank"]
        if fold.get("offsets"):
            out["trace_clock_offsets"] = {
                f"r{r}": v for r, v in fold["offsets"].items()}
        # span-derived divergence fallback: the same 0.25 threshold as
        # the profiler window, applied whenever no window ran — runs
        # without a capture still get the trust check
        est = out.get("overlapped_comm_fraction",
                      out.get("comm_fraction"))
        if (isinstance(est, (int, float))
                and out.get("overlap_spans") is not None
                and "overlap_divergence" not in out):
            out["overlap_divergence"] = bool(
                abs(out["overlap_spans"] - est) > 0.25)

    # ---- staleness probes (--staleness-probe-every) ----
    stale = [r for r in records if r.get("event") == "staleness"]
    drifts = [r["max_rel_drift"] for r in stale
              if isinstance(r.get("max_rel_drift"), (int, float))]
    if drifts:
        out["staleness_probes"] = len(drifts)
        out["staleness_max_rel_drift"] = round(max(drifts), 6)
        out["staleness_last_rel_drift"] = round(drifts[-1], 6)

    # ---- numerics health (resilience/numerics.py): first-NaN phase,
    # loss-scale backoff/skip counts, kernel fallbacks taken ----
    from ..resilience.numerics import summarize_numerics

    out.update(summarize_numerics(records))

    # ---- compiled-step anatomy (obs/anatomy.py) ----
    anatomies = [r for r in records if r.get("event") == "anatomy"]
    if anatomies:
        a = anatomies[-1]
        if isinstance(a.get("attributed_flops_fraction"), (int, float)):
            out["anatomy_attributed_flops_fraction"] = round(
                a["attributed_flops_fraction"], 4)
        ph = a.get("phases")
        ef = a.get("est_flops")
        if isinstance(ph, dict) and isinstance(ef, (int, float)) and ef:
            out["anatomy_flop_shares"] = {
                k: round(v.get("flops", 0.0) / ef, 4)
                for k, v in ph.items() if isinstance(v, dict)}
            # the non-SpMM floor: everything the epoch spends that is
            # NOT the aggregation kernel (ROADMAP item 1's target; the
            # four --rng-impl/--halo-dtype/--epoch-block/--comm-prefetch
            # levers attack exactly this share)
            spmm = sum(v for k, v in out["anatomy_flop_shares"].items()
                       if "spmm" in k)
            out["anatomy_non_spmm_share"] = round(
                max(0.0, 1.0 - spmm), 4)

    # ---- online serving windows (serve/, schema v5) ----
    serving = [r for r in records if r.get("event") == "serving"]
    if serving:
        out["n_serving_records"] = len(serving)
        qs = [r.get("queries") for r in serving]
        qs = [q for q in qs if isinstance(q, int)]
        total_q = sum(qs)
        out["serving_queries"] = total_q
        wins = [r.get("window_s") for r in serving]
        total_w = sum(w for w in wins if isinstance(w, (int, float)))
        if total_w > 0:
            out["serving_qps"] = round(total_q / total_w, 2)
        # query-weighted percentile means: an empty window (null
        # percentiles) must not drag the latency picture
        for key in ("p50_ms", "p95_ms", "p99_ms", "batch_fill",
                    "cache_hit_rate"):
            num = den = 0.0
            for r in serving:
                v, q = r.get(key), r.get("queries")
                if isinstance(v, (int, float)) and isinstance(q, int) \
                        and q > 0:
                    num += v * q
                    den += q
            if den:
                out[f"serving_{key}"] = round(num / den, 4)
        ages = [r.get("staleness_age") for r in serving]
        ages = [a for a in ages if isinstance(a, int)]
        if ages:
            out["serving_staleness_age_max"] = max(ages)
        depths = [r.get("queue_depth") for r in serving]
        depths = [d for d in depths if isinstance(d, int)]
        if depths:
            out["serving_queue_depth_max"] = max(depths)
        sheds = [r.get("shed") for r in serving]
        sheds = [x for x in sheds if isinstance(x, int)]
        if sheds:
            out["serving_shed_total"] = sum(sheds)
        gens = [r.get("param_generation") for r in serving]
        gens = [g for g in gens if isinstance(g, int) and g >= 0]
        if gens:
            out["serving_param_generation_last"] = gens[-1]
        stale = [r.get("param_staleness") for r in serving]
        stale = [x for x in stale if isinstance(x, int)]
        if stale:
            out["serving_param_staleness_max"] = max(stale)
        out["serving_drained"] = any(r.get("final") for r in serving)

    # ---- serving fleet (serve/fleet.py, schema v7) ----
    fleet = [r for r in records if r.get("event") == "fleet"]
    if fleet:
        out["n_fleet_records"] = len(fleet)
        by_kind: Dict[str, int] = {}
        for r in fleet:
            k = r.get("kind")
            if isinstance(k, str):
                by_kind[k] = by_kind.get(k, 0) + 1
        out["fleet_events"] = by_kind
        swaps = [r.get("swap_ms") for r in fleet
                 if r.get("kind") == "hot-swap"]
        swaps = [x for x in swaps if isinstance(x, (int, float))]
        if swaps:
            out["fleet_param_swap_ms_max"] = round(max(swaps), 2)
        gens = [r.get("param_generation") for r in fleet
                if r.get("kind") == "hot-swap"]
        gens = [g for g in gens if isinstance(g, int)]
        if gens:
            out["fleet_param_generation_last"] = max(gens)

    # ---- elastic membership timeline (resilience/elastic.py, v6) ----
    membership = [r for r in records if r.get("event") == "membership"]
    if membership:
        membership = sorted(
            membership, key=lambda r: r.get("generation", -1)
            if isinstance(r.get("generation"), int) else -1)
        out["n_membership_records"] = len(membership)
        gens = [r["generation"] for r in membership
                if isinstance(r.get("generation"), int)]
        if gens:
            out["membership_last_generation"] = max(gens)
        timeline = []
        for r in membership:
            a = r.get("assignment") or {}
            timeline.append({
                "generation": r.get("generation"),
                "trigger": r.get("trigger"),
                "n_members": (len(a.get("members", []))
                              if isinstance(a.get("members"), list)
                              else r.get("n_members")),
                "parts_per_node": a.get("parts_per_node"),
                "restart_latency_s": r.get("restart_latency_s"),
            })
        out["membership_timeline"] = timeline
        lats = [r.get("restart_latency_s") for r in membership]
        lats = [x for x in lats if isinstance(x, (int, float))]
        if lats:
            out["restart_latency_max_s"] = round(max(lats), 3)
        stops = [r.get("trigger") for r in membership
                 if r.get("trigger") in ("max-restarts", "restart-storm")]
        if stops:
            out["membership_stopped"] = stops[-1]

    # ---- forensics (obs/flight.py + obs/postmortem.py, v11) ----
    boxes = [r for r in records if r.get("event") == "blackbox"]
    if boxes:
        out["n_blackbox_records"] = len(boxes)
        reasons: Dict[str, int] = {}
        for r in boxes:
            k = str(r.get("reason"))
            reasons[k] = reasons.get(k, 0) + 1
        out["blackbox_reasons"] = reasons
    diags = [r for r in records if r.get("event") == "diagnosis"]
    if diags:
        d = diags[-1]  # the latest postmortem verdict wins
        out["diagnosis_verdict"] = d.get("verdict")
        if isinstance(d.get("confidence"), (int, float)):
            out["diagnosis_confidence"] = round(d["confidence"], 3)
        out["diagnosis_deterministic"] = bool(d.get("deterministic"))
        if isinstance(d.get("remediation"), str):
            out["diagnosis_remediation"] = d["remediation"]

    # ---- streaming graph deltas (stream/, schema v8) ----
    stream = [r for r in records if r.get("event") == "stream"]
    if stream:
        out["n_stream_records"] = len(stream)
        for key in ("edges_added", "edges_deleted", "nodes_added"):
            vals = [r.get(key) for r in stream]
            vals = [v for v in vals if isinstance(v, int)]
            if vals:
                out[f"stream_{key}"] = sum(vals)
        pms = [r.get("patch_ms") for r in stream]
        pms = [v for v in pms if isinstance(v, (int, float))]
        if pms:
            out["stream_patch_ms_median"] = round(_median(pms), 3)
            out["stream_patch_ms_max"] = round(max(pms), 3)
        drifts = [r.get("drift") for r in stream]
        drifts = [v for v in drifts if isinstance(v, (int, float))]
        if drifts:
            out["stream_drift_max"] = round(max(drifts), 6)
            out["stream_drift_last"] = round(drifts[-1], 6)
        reb = [r.get("tables_rebuilt") for r in stream]
        reb = [v for v in reb if isinstance(v, int)]
        if reb:
            out["stream_tables_rebuilt"] = sum(reb)
        out["stream_repads"] = sum(1 for r in stream if r.get("repadded"))
        slack = [r.get("slack_remaining") for r in stream
                 if isinstance(r.get("slack_remaining"), dict)]
        if slack:
            out["stream_slack_remaining_last"] = slack[-1]
    return out


def format_summary(path: str, s: Dict[str, Any]) -> str:
    lines = [f"== {path} =="]

    def row(label, key, fmt="{}", scale=1.0):
        v = s.get(key)
        if v is None:
            return
        if isinstance(v, (int, float)) and scale != 1.0:
            v = v * scale
        lines.append(f"  {label:<26} {fmt.format(v)}")

    row("schema version", "schema_version")
    row("device", "device")
    row("devices", "n_devices")
    row("pipeline", "pipeline")
    if s.get("bench_value") is not None:
        lines.append("  {:<26} {} {} ({})".format(
            "bench headline", s["bench_value"], s.get("bench_unit", ""),
            s.get("bench_metric", "")))
        row("vs baseline", "vs_baseline", "{:.3f}x")
    row("epochs recorded", "n_epoch_records")
    row("epoch time (fit mean)", "epoch_time_s", "{:.4f} s")
    row("median epoch", "median_epoch_s", "{:.4f} s")
    row("loss first -> last", "loss_first", "{:.4f}")
    row("loss last", "loss_last", "{:.4f}")
    row("loss delta", "loss_delta", "{:+.4f}")
    row("grad norm (last)", "grad_norm_last", "{:.4e}")
    row("halo bytes / epoch", "halo_bytes_per_epoch", "{:,}")
    if s.get("halo_bytes_uncompressed_per_epoch") is not None:
        lines.append("  {:<26} {:,} -> {:,} ({}x)".format(
            "halo wire compression",
            s["halo_bytes_uncompressed_per_epoch"],
            s.get("halo_bytes_per_epoch", 0),
            s.get("halo_compression_ratio", "?")))
    row("staleness age (max)", "staleness_age_max")
    row("memory peak", "memory_peak_bytes", "{:,} bytes")
    row("comm cost (standalone)", "comm_cost_s", "{:.4f} s")
    row("comm fraction of epoch", "comm_fraction", "{:.2%}")
    # estimated and measured side by side: the estimate is the
    # host-derived comm_cost/epoch ratio, the measurement a folded
    # device trace (obs/profiler.py) — divergence means the estimate
    # can no longer be trusted at this config
    row("overlap (estimated)", "overlapped_comm_fraction", "{:.2%}")
    row("overlap (measured)", "measured_overlap_fraction", "{:.2%}")
    # always-on span verdict (obs/trainspan.py) — present even when no
    # profiler window ran, so every traced run gets a measured number
    row("overlap (spans)", "overlap_spans", "{:.2%}")
    if s.get("comm_wait_share_by_rank"):
        lines.append("  {:<26} {}".format(
            "comm wait share (spans)", ", ".join(
                f"{k}={v:.1%}" for k, v in
                sorted(s["comm_wait_share_by_rank"].items()))))
    if s.get("straggler_max_gap_s") is not None:
        lines.append("  {:<26} r{} (+{:.0f} ms behind median start)"
                     .format("straggler (spans)",
                             s.get("straggler_rank", "?"),
                             s["straggler_max_gap_s"] * 1e3))
    if s.get("overlap_divergence"):
        lines.append(f"  {'!! overlap divergence':<26} measured and "
                     f"estimated overlap differ by > 0.25")
    if s.get("profile_phases"):
        top = sorted(s["profile_phases"].items(),
                     key=lambda kv: -kv[1])[:4]
        lines.append("  {:<26} {}".format(
            "profiled device time", ", ".join(
                f"{k} {v:.4f}s" for k, v in top)))
    if s.get("staleness_probes"):
        lines.append("  {:<26} {} probes, max {:.4f}, last {:.4f}"
                     .format("staleness rel drift",
                             s["staleness_probes"],
                             s.get("staleness_max_rel_drift", 0.0),
                             s.get("staleness_last_rel_drift", 0.0)))
    if s.get("anatomy_flop_shares"):
        top = sorted(s["anatomy_flop_shares"].items(),
                     key=lambda kv: -kv[1])[:4]
        lines.append("  {:<26} {}".format(
            "anatomy flop shares", ", ".join(
                f"{k} {v:.1%}" for k, v in top)))
        row("non-SpMM floor share", "anatomy_non_spmm_share", "{:.1%}")
        row("anatomy attributed", "anatomy_attributed_flops_fraction",
            "{:.1%}")
    # gather-stream contiguity sits beside the non-SpMM floor: the
    # reorder lever moves this number, the slab path cashes it in
    if s.get("gather_mean_run_len") is not None:
        tail = f" (reorder={s['reorder']})" if s.get("reorder") else ""
        lines.append("  {:<26} mean run {:.2f}, slab-able {:.1%}{}".format(
            "gather contiguity", s["gather_mean_run_len"],
            s.get("gather_slab_frac", 0.0), tail))
    row("reorder delta", "reorder_delta_s", "{:+.4f} s/epoch")
    row("slab delta", "slab_delta_s", "{:+.4f} s/epoch")
    row("MFU", "mfu_pct", "{:.2f} %")
    if s.get("n_faults"):
        kinds = ", ".join(f"{k}x{n}" for k, n in
                          sorted(s.get("fault_kinds", {}).items()))
        lines.append(f"  {'faults / recoveries':<26} "
                     f"{s['n_faults']} / {s.get('n_recoveries', 0)}"
                     f" ({kinds})")
        if s.get("fault_ranks"):
            by_rank = ", ".join(f"{k}x{n}" for k, n in
                                sorted(s["fault_ranks"].items()))
            lines.append(f"  {'faults by rank':<26} {by_rank}")
        if s.get("fault_source_ranks"):
            by_src = ", ".join(f"{k}x{n}" for k, n in
                               sorted(s["fault_source_ranks"].items()))
            lines.append(f"  {'consensus source ranks':<26} {by_src} "
                         f"({s.get('n_agreed_faults', 0)} agreed)")
    # ---- numerics health ----
    if s.get("first_nan_phase"):
        lines.append("  {:<26} {} (epoch {})".format(
            "!! first NaN phase", s["first_nan_phase"],
            s.get("first_nan_epoch", "?")))
    if s.get("loss_scale_skips") is not None:
        lines.append("  {:<26} {} skipped, {} backoffs, {} regrowths, "
                     "scale {}".format(
                         "loss-scale events", s["loss_scale_skips"],
                         s.get("loss_scale_backoffs", 0),
                         s.get("loss_scale_growths", 0),
                         s.get("loss_scale_last", "?")))
    elif s.get("loss_scale_growths"):
        lines.append("  {:<26} {} regrowths, scale {}".format(
            "loss-scale events", s["loss_scale_growths"],
            s.get("loss_scale_last", "?")))
    if s.get("kernel_fallbacks"):
        lines.append("  {:<26} {}".format(
            "kernel fallbacks", ", ".join(s["kernel_fallbacks"])))
    # ---- online serving (docs/SERVING.md) ----
    if s.get("n_serving_records"):
        lines.append("  {:<26} {} windows, {} queries".format(
            "serving", s["n_serving_records"],
            s.get("serving_queries", 0)))
        row("serving QPS", "serving_qps", "{:.2f} q/s")
        if s.get("serving_p50_ms") is not None:
            lines.append("  {:<26} p50 {:.2f} / p95 {:.2f} / p99 {:.2f} "
                         "ms".format("serving latency",
                                     s["serving_p50_ms"],
                                     s.get("serving_p95_ms", 0.0),
                                     s.get("serving_p99_ms", 0.0)))
        row("serving batch fill", "serving_batch_fill", "{:.1%}")
        row("serving cache hit rate", "serving_cache_hit_rate", "{:.1%}")
        row("serving staleness (max)", "serving_staleness_age_max")
        row("serving queue depth max", "serving_queue_depth_max")
        row("serving shed (total)", "serving_shed_total")
        row("serving param generation", "serving_param_generation_last")
        row("serving param staleness", "serving_param_staleness_max")
        if not s.get("serving_drained"):
            lines.append(f"  {'!! serving shutdown':<26} no final "
                         f"record — the run died without draining")
    # ---- serving fleet (docs/SERVING.md, "Fleet") ----
    if s.get("n_fleet_records"):
        ev = s.get("fleet_events") or {}
        lines.append("  {:<26} {} events ({})".format(
            "fleet", s["n_fleet_records"],
            ", ".join(f"{k}={v}" for k, v in sorted(ev.items()))
            or "none"))
        row("fleet param swap (max)", "fleet_param_swap_ms_max",
            "{:.2f} ms")
        row("fleet param generation", "fleet_param_generation_last")
        if ev.get("replica-dead", 0) > ev.get("replica-rejoin", 0):
            lines.append(f"  {'!! fleet degraded':<26} "
                         f"{ev.get('replica-dead', 0)} death(s) vs "
                         f"{ev.get('replica-rejoin', 0)} rejoin(s) — "
                         f"ended below full strength")
    # ---- elastic membership (docs/RESILIENCE.md) ----
    if s.get("n_membership_records"):
        lines.append("  {:<26} {} generations (last gen {})".format(
            "membership", s["n_membership_records"],
            s.get("membership_last_generation", "?")))
        for t in s.get("membership_timeline", []):
            lat = t.get("restart_latency_s")
            lat_s = f", relaunched in {lat:.1f}s" \
                if isinstance(lat, (int, float)) else ""
            lines.append(
                "  {:<26} gen {}: {} member(s) x {} part(s) "
                "[{}]{}".format("", t.get("generation"),
                                t.get("n_members", "?"),
                                t.get("parts_per_node", "?"),
                                t.get("trigger", "?"), lat_s))
        row("restart latency (max)", "restart_latency_max_s", "{:.2f} s")
        if s.get("membership_stopped"):
            lines.append(f"  {'!! supervisor stopped':<26} "
                         f"{s['membership_stopped']} — resume from the "
                         f"last checkpoint manually")
    # ---- streaming graph deltas (docs/STREAMING.md) ----
    if s.get("n_stream_records"):
        lines.append("  {:<26} {} delta(s): +{}/-{} edges, +{} nodes"
                     .format("stream deltas", s["n_stream_records"],
                             s.get("stream_edges_added", 0),
                             s.get("stream_edges_deleted", 0),
                             s.get("stream_nodes_added", 0)))
        if s.get("stream_patch_ms_median") is not None:
            lines.append("  {:<26} median {:.1f} / max {:.1f} ms"
                         .format("stream patch cost",
                                 s["stream_patch_ms_median"],
                                 s.get("stream_patch_ms_max", 0.0)))
        if s.get("stream_drift_max") is not None:
            lines.append("  {:<26} max {:.4f}, last {:.4f}".format(
                "stream probe drift", s["stream_drift_max"],
                s.get("stream_drift_last", 0.0)))
        row("stream tables rebuilt", "stream_tables_rebuilt")
        sl = s.get("stream_slack_remaining_last")
        if isinstance(sl, dict):
            lines.append("  {:<26} {}".format(
                "stream slack left", ", ".join(
                    f"{k}={v}" for k, v in sorted(sl.items()))))
        if s.get("stream_repads"):
            lines.append(f"  {'!! stream re-pads':<26} "
                         f"{s['stream_repads']} slack exhaustion(s) — "
                         f"recompiled; raise --stream-slack")
    # ---- forensics (docs/OBSERVABILITY.md "Postmortem") ----
    if s.get("n_blackbox_records"):
        reasons = ", ".join(f"{k}x{n}" for k, n in
                            sorted(s.get("blackbox_reasons",
                                         {}).items()))
        lines.append(f"  {'black-box dumps':<26} "
                     f"{s['n_blackbox_records']} ({reasons})")
    if s.get("diagnosis_verdict"):
        det = (" [deterministic — do not blind-restart]"
               if s.get("diagnosis_deterministic") else "")
        lines.append("  {:<26} {} (confidence {}){}".format(
            "!! postmortem verdict", s["diagnosis_verdict"],
            s.get("diagnosis_confidence", "?"), det))
        if s.get("diagnosis_remediation"):
            lines.append(f"  {'':<26} {s['diagnosis_remediation']}")
    row("best val", "best_val", "{:.4f}")
    row("best epoch", "best_epoch")
    row("test acc", "test_acc", "{:.4f}")
    row("mean eval wait", "mean_eval_s", "{:.4f} s")
    return "\n".join(lines)


def _load_target(path: str):
    """One CLI target's records: a plain JSONL file keeps the strict
    single-file contract (read_metrics raises on malformed lines); a
    run directory, a metrics stem, or a base file WITH per-generation
    siblings goes through the aggregator's tolerant deduped
    generation-ordered merge (obs/live.py). Returns (records,
    n_streams)."""
    import os

    from ..obs.live import discover_streams, merge_streams

    streams = discover_streams(path)
    if streams == [path] and os.path.isfile(path):
        return read_metrics(path), 1
    if not streams:
        raise OSError(f"no metrics streams found under {path!r}")
    return merge_streams(streams), len(streams)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pipegcn_tpu.cli.report",
        description="Summarize metrics JSONL files written with "
                    "--metrics-out (schema: pipegcn_tpu/obs/schema.py)")
    ap.add_argument("files", nargs="+",
                    help="metrics JSONL file(s), run directories, or "
                         "metrics stems: a directory or stem expands "
                         "to every stream under it ({stem}.g*.m*.jsonl "
                         "per-generation files, the supervisor ledger, "
                         "replica streams) merged generation-ordered "
                         "and deduped — the live monitor's discovery "
                         "(obs/live.py), applied post-hoc")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON summary object per file")
    args = ap.parse_args(argv)

    rc = 0
    for path in args.files:
        try:
            recs, n_streams = _load_target(path)
            s = summarize_run(recs)
            if n_streams > 1:
                s["n_streams_merged"] = n_streams
        except (OSError, ValueError) as exc:
            print(f"{path}: {exc}", file=sys.stderr)
            rc = 1
            continue
        if args.json:
            print(json.dumps({"file": path, **s}))
        else:
            print(format_summary(path, s))
    return rc


if __name__ == "__main__":
    sys.exit(main())
