"""Command-line flag surface.

Accepts the exact flag set of the reference (helper/parser.py:4-71, every
flag with both `-` and `_` spellings) so the reference's `scripts/*.sh`
run unchanged, plus TPU-specific extensions listed at the bottom.
Differences in meaning:

  --backend        'xla' (default) — the only real backend; 'gloo' is
                   accepted for script compatibility and treated as xla
                   (the reference's nccl/mpi raise NotImplementedError,
                   main.py:60-63; here they are rejected the same way).
  --master-addr/--port/--node-rank/--parts-per-node
                   map to `jax.distributed.initialize` coordinator
                   config for multi-host SPMD instead of gloo rendezvous.
"""

from __future__ import annotations

import argparse


def create_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="PipeGCN-TPU")

    parser.add_argument("--dataset", type=str, default="reddit",
                        help="the input dataset")
    parser.add_argument("--graph-name", "--graph_name", type=str, default="")

    parser.add_argument("--model", type=str, default="graphsage",
                        help="model for training")
    parser.add_argument("--dropout", type=float, default=0.5,
                        help="dropout probability")
    parser.add_argument("--lr", type=float, default=1e-2,
                        help="learning rate")
    parser.add_argument("--n-epochs", "--n_epochs", type=int, default=200,
                        help="the number of training epochs")
    parser.add_argument("--n-partitions", "--n_partitions", type=int,
                        default=2, help="the number of partitions")
    parser.add_argument("--n-hidden", "--n_hidden", type=int, default=16,
                        help="the number of hidden units")
    parser.add_argument("--n-layers", "--n_layers", type=int, default=2,
                        help="the number of GCN layers")
    parser.add_argument("--n-linear", "--n_linear", type=int, default=0,
                        help="the number of linear layers")
    parser.add_argument("--norm", choices=["layer", "batch", "none"],
                        default="layer", help="normalization method")
    parser.add_argument("--weight-decay", "--weight_decay", type=float,
                        default=0, help="weight for L2 loss")

    parser.add_argument("--n-feat", "--n_feat", type=int, default=0)
    parser.add_argument("--n-class", "--n_class", type=int, default=0)
    parser.add_argument("--n-train", "--n_train", type=int, default=0)
    parser.add_argument("--skip-partition", "--skip_partition",
                        action="store_true",
                        help="reuse the on-disk partition artifact")

    parser.add_argument("--partition-obj", "--partition_obj",
                        choices=["vol", "cut"], default="vol",
                        help="partition objective function")
    parser.add_argument("--partition-method", "--partition_method",
                        choices=["metis", "random"], default="metis",
                        help="the method for graph partition")

    parser.add_argument("--enable-pipeline", "--enable_pipeline",
                        action="store_true")
    parser.add_argument("--feat-corr", "--feat_corr", action="store_true")
    parser.add_argument("--grad-corr", "--grad_corr", action="store_true")
    parser.add_argument("--corr-momentum", "--corr_momentum", type=float,
                        default=0.95)

    parser.add_argument("--use-pp", "--use_pp", action="store_true",
                        help="whether to use precomputation")
    parser.add_argument("--inductive", action="store_true",
                        help="inductive learning setting")
    parser.add_argument("--fix-seed", "--fix_seed", action="store_true",
                        help="fix random seed")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--log-every", "--log_every", type=int, default=10)

    parser.add_argument("--backend", type=str, default="xla")
    parser.add_argument("--port", type=int, default=18118,
                        help="coordinator port for multi-host")
    parser.add_argument("--master-addr", "--master_addr", type=str,
                        default="127.0.0.1")
    parser.add_argument("--node-rank", "--node_rank", type=int, default=0)
    parser.add_argument("--parts-per-node", "--parts_per_node", type=int,
                        default=10)
    parser.add_argument("--coordinator-timeout", "--coordinator_timeout",
                        type=int, default=300,
                        help="seconds to wait for the jax.distributed "
                             "coordinator at --master-addr:--port before "
                             "failing with an actionable error instead "
                             "of hanging forever (single-host runs "
                             "never connect)")

    parser.add_argument("--eval", action="store_true",
                        help="enable evaluation")
    parser.add_argument("--no-eval", action="store_false", dest="eval",
                        help="disable evaluation")
    parser.set_defaults(eval=True)

    # ---- TPU-native extensions (not in the reference) ----
    parser.add_argument("--data-root", "--data_root", type=str, default=None,
                        help="dataset root (default $PIPEGCN_DATA or ./dataset)")
    parser.add_argument("--partition-dir", "--partition_dir", type=str,
                        default="partitions",
                        help="directory for partition artifacts")
    parser.add_argument("--model-dir", "--model_dir", type=str,
                        default="model", help="directory for saved models")
    parser.add_argument("--results-dir", "--results_dir", type=str,
                        default="results", help="directory for result logs")
    parser.add_argument("--spmm-chunk", "--spmm_chunk", type=int, default=0,
                        help="edge-chunk size bounding SpMM memory "
                             "(0 = unchunked)")
    parser.add_argument("--spmm-impl", "--spmm_impl",
                        choices=["xla", "bucket", "block", "auto"],
                        default="xla",
                        help="aggregation kernel: XLA gather+segment-sum, "
                             "the scatter-free degree-bucketed kernel, "
                             "the hybrid block-dense MXU kernel, or "
                             "auto — resolved from the artifact's "
                             "measured tuning table / a live "
                             "micro-bench (ops/tuner.py), never from "
                             "shape thresholds")
    parser.add_argument("--n-heads", "--n_heads", type=int, default=4,
                        help="attention heads for --model gat")
    parser.add_argument("--block-tile", "--block_tile", type=int,
                        default=256,
                        help="dense-tile edge length for the block-dense "
                             "kernel")
    parser.add_argument("--block-nnz", "--block_nnz", type=int, default=0,
                        help="minimum edges for a tile pair to go dense "
                             "in the block kernel (0 = read-cost "
                             "break-even)")
    parser.add_argument("--block-group", "--block_group", type=int,
                        default=1,
                        help="union-gather group: that many consecutive "
                             "dst tiles share one gathered source-tile "
                             "union in the block kernel's dense path "
                             "(1 = per-tile block lists)")
    parser.add_argument("--bucket-merge", "--bucket_merge", type=int,
                        default=0,
                        help="merge bucket-ladder rungs below this width "
                             "into one bucket (fewer kernel launches / "
                             "transients per epoch at bounded padding "
                             "cost; 0 = full ladder). Tuner-signature "
                             "relevant: changing it re-tunes")
    parser.add_argument("--tune", action="store_true", dest="tune",
                        default=True,
                        help="allow a live tuner micro-bench when "
                             "--spmm-impl auto finds no trusted "
                             "tuning.json in the partition artifact "
                             "(default on; single-process runs only)")
    parser.add_argument("--no-tune", action="store_false", dest="tune",
                        help="never micro-bench at trainer setup: a "
                             "cache miss falls back to the "
                             "deterministic default kernel with a loud "
                             "record")
    parser.add_argument("--tuner-samples", "--tuner_samples", type=int,
                        default=200_000,
                        help="edge budget of the tuner's sampled "
                             "degree-distribution slice")
    parser.add_argument("--rem-dtype", "--rem_dtype",
                        choices=["none", "bfloat16", "float8"],
                        default="none",
                        help="gather-transport dtype for the bucket "
                             "kernel / block remainder: float8 packs "
                             "256 features into one 256-byte gather "
                             "row (e4m3 activations, e5m2 cotangents, "
                             "f32 accumulation)")
    parser.add_argument("--fused-epochs", "--fused_epochs", type=int,
                        default=1,
                        help="epochs per compiled dispatch (lax.scan); "
                             "amortizes host round-trips")
    parser.add_argument("--rng-impl", "--rng_impl",
                        choices=["threefry", "rbg", "unsafe_rbg"],
                        default="threefry",
                        help="dropout PRNG: threefry (jax default), "
                             "rbg (hardware-RNG-backed, cheaper mask "
                             "generation on TPU; different but equally "
                             "valid masks at the same seed), or "
                             "unsafe_rbg (cheapest; weaker fold_in/split "
                             "guarantees — fine for dropout noise, "
                             "never for init)")
    parser.add_argument("--dropout-bits", "--dropout_bits", type=int,
                        choices=[8, 32], default=32,
                        help="dropout mask generation width: 8 draws "
                             "one random byte per element (quarter the "
                             "generated bits; keep-prob quantized to "
                             "1/256) instead of bernoulli's uniform-f32 "
                             "compare")
    parser.add_argument("--dropout-reuse", "--dropout_reuse", type=int,
                        default=0,
                        help="reuse each dropout mask for N consecutive "
                             "epochs (the per-epoch key folds "
                             "epoch//N), amortizing mask generation "
                             "N-fold inside fused blocks; 0/1 = fresh "
                             "mask every epoch")
    parser.add_argument("--halo-dtype", "--halo_dtype",
                        choices=["none", "bfloat16", "float8"],
                        default="none",
                        help="wire dtype of the halo ppermute payloads "
                             "(pipelined mode only): bfloat16 halves "
                             "ICI bytes per hop, float8 quarters them "
                             "(e4m3 features / e5m2 bgrads, amax-scaled "
                             "per distance block; decoded back to the "
                             "compute dtype on receipt)")
    parser.add_argument("--epoch-block", "--epoch_block", type=int,
                        default=0,
                        help="epochs per megastep dispatch (donated-"
                             "carry lax.scan + one batched metrics "
                             "harvest per block); overrides "
                             "--fused-epochs when set, 0 = inherit it")
    parser.add_argument("--comm-prefetch", "--comm_prefetch",
                        action="store_true",
                        help="issue the layer-0 halo collective at the "
                             "top of the step so it overlaps the "
                             "previous epoch's tail inside a fused "
                             "block (pipelined, no --use-pp; "
                             "numerically identical)")
    parser.add_argument("--local-reorder", "--local_reorder",
                        choices=["none", "cluster"], default="cluster",
                        help="local-id ordering within each partition: "
                             "'cluster' renumbers by locality clusters so "
                             "the shard adjacency forms dense tiles "
                             "(feeds --spmm-impl block); 'none' keeps "
                             "global-id order")
    from ..partition.partitioner import DEFAULT_CLUSTER_SIZE

    parser.add_argument("--cluster-size", "--cluster_size", type=int,
                        default=DEFAULT_CLUSTER_SIZE,
                        help="locality-cluster target size for "
                             "--local-reorder cluster; finer clusters "
                             "(the 1024 default) concentrate edges into "
                             "fewer, denser tiles (docs/PERF_NOTES.md)")
    parser.add_argument("--dtype", choices=["float32", "bfloat16"],
                        default="float32",
                        help="compute dtype for activations/halo exchange "
                             "(params, optimizer and statistics stay f32)")
    parser.add_argument("--checkpoint-dir", "--checkpoint_dir", type=str,
                        default="",
                        help="enable periodic checkpointing to this dir")
    parser.add_argument("--checkpoint-every", "--checkpoint_every", type=int,
                        default=100)
    parser.add_argument("--checkpoint-keep", "--checkpoint_keep", type=int,
                        default=3,
                        help="checkpoint generations retained "
                             "(keep-last-N rotation with a 'latest' "
                             "pointer and digest-verified fallback, "
                             "docs/RESILIENCE.md; 0 keeps all)")
    parser.add_argument("--checkpoint-fallback-dir",
                        "--checkpoint_fallback_dir", type=str, default="",
                        help="second directory (ideally another volume) "
                             "to save into when a periodic checkpoint "
                             "write fails with OSError; with or without "
                             "it the failed save degrades loudly and "
                             "retries at later boundaries "
                             "(docs/RESILIENCE.md 'Storage faults')")
    parser.add_argument("--resume", action="store_true",
                        help="resume from --checkpoint-dir (errors "
                             "without one; warns loudly when the dir "
                             "holds no checkpoint yet)")
    # ---- fault tolerance (docs/RESILIENCE.md) ----
    parser.add_argument("--no-sentinel", "--no_sentinel",
                        action="store_false", dest="sentinel",
                        help="disable the divergence sentinel "
                             "(non-finite/exploding loss detection with "
                             "rollback + LR backoff + bounded retries)")
    parser.set_defaults(sentinel=True)
    parser.add_argument("--sentinel-loss-factor", "--sentinel_loss_factor",
                        type=float, default=10.0,
                        help="trip when loss exceeds this multiple of "
                             "the recent healthy median (0 disables the "
                             "relative check; non-finite always trips)")
    parser.add_argument("--sentinel-grad-max", "--sentinel_grad_max",
                        type=float, default=0.0,
                        help="absolute grad-norm trip threshold "
                             "(0 disables)")
    parser.add_argument("--sentinel-max-retries", "--sentinel_max_retries",
                        type=int, default=3,
                        help="consecutive rollback retries before the "
                             "run fails with DivergenceError")
    parser.add_argument("--sentinel-lr-backoff", "--sentinel_lr_backoff",
                        type=float, default=0.5,
                        help="LR multiplier applied on every sentinel "
                             "trip (1.0 = no backoff)")
    parser.add_argument("--sentinel-snapshot-every",
                        "--sentinel_snapshot_every", type=int, default=25,
                        help="epochs between in-memory last-good "
                             "snapshots the sentinel rolls back to")
    parser.add_argument("--sentinel-no-flush", "--sentinel_no_flush",
                        action="store_false", dest="sentinel_flush",
                        help="keep the stale pipelined halo carry on "
                             "rollback instead of flushing it to zeros")
    parser.set_defaults(sentinel_flush=True)
    parser.add_argument("--fault-plan", "--fault_plan", type=str,
                        default="",
                        help="deterministic chaos injection: comma-"
                             "separated kind@epoch[:rN] entries "
                             "(nan-loss, nan-grad, sigterm, crash, "
                             "corrupt-ckpt, desync, hang, overflow, "
                             "kernel-crash, graph-delta, plus the "
                             "storage kinds enospc, torn-write, ro-dir, "
                             "slow-fs@E:<ms> — armed at the boundary of "
                             "E, disarmed at the next checkpoint "
                             "boundary), e.g. "
                             "'nan-loss@5:r1,sigterm@8,enospc@4'; each "
                             "fires once, host-side only; :rN targets "
                             "one rank (process index) in multi-host "
                             "runs")
    # ---- streaming graphs (docs/STREAMING.md) ----
    parser.add_argument("--stream-plan", "--stream_plan", type=str,
                        default="",
                        help="graph delta schedule: comma-separated "
                             "FILE@epoch[:everyN] entries — batch j of "
                             "FILE (CRC-guarded JSONL or npz, "
                             "stream/deltas.py) applies at the boundary "
                             "of epoch+j*N. Edges/nodes land in the "
                             "existing partition through reserved "
                             "headroom (--stream-slack), so compiled "
                             "shapes stay static across deltas")
    parser.add_argument("--stream-slack", "--stream_slack", type=float,
                        default=0.10,
                        help="fractional headroom reserved in every "
                             "padded dimension (rows, edges, send "
                             "slots) of the sharded build for streamed "
                             "growth; exhausting it re-pads loudly "
                             "(one recompile) instead of failing")
    parser.add_argument("--journal-dir", "--journal_dir", type=str,
                        default="",
                        help="write-ahead delta journal directory "
                             "(stream/journal.py): every applied delta "
                             "batch is made durable before it mutates "
                             "the topology, and --resume replays the "
                             "journal to the checkpoint's watermark. "
                             "Defaults to <checkpoint-dir>/journal "
                             "when streaming with --checkpoint-dir; "
                             "set explicitly to journal without "
                             "checkpoints")
    # ---- numerics guardrails (docs/RESILIENCE.md "Numerics") ----
    parser.add_argument("--loss-scale", "--loss_scale", type=str,
                        default="off",
                        help="mixed-precision loss scaling: 'auto' "
                             "(dynamic — backoff on overflow, regrow "
                             "after a clean streak), a positive number "
                             "(static scale), or 'off'. Non-'off' also "
                             "arms in-graph overflow-skip: an epoch "
                             "whose reduced gradient is non-finite "
                             "keeps params unchanged (skips counted in "
                             "the metrics JSONL as 'numerics' records)")
    parser.add_argument("--rem-amax", "--rem_amax", action="store_true",
                        help="amax-clamped fp8 transport cast: scale "
                             "each gathered tensor by a power of two "
                             "from its amax so the e4m3/e5m2 cast lands "
                             "mid-range instead of saturating or "
                             "flushing to zero (only with --rem-dtype "
                             "float8)")
    parser.add_argument("--no-numerics-tripwire", "--no_numerics_tripwire",
                        action="store_false", dest="numerics_tripwire",
                        help="drop the in-graph per-phase non-finite "
                             "tripwire from the step (fault records "
                             "then name no NaN birth phase)")
    parser.set_defaults(numerics_tripwire=True)
    # ---- cross-rank coordination (docs/RESILIENCE.md multi-host) ----
    parser.add_argument("--watchdog-timeout", "--watchdog_timeout",
                        type=float, default=60.0,
                        help="multi-host heartbeat watchdog: a peer "
                             "rank silent on the shared partition "
                             "filesystem for this many seconds raises "
                             "PeerLost -> crash checkpoint -> resumable "
                             "exit 75 instead of hanging the pod in a "
                             "collective (0 disables; single-process "
                             "runs never arm it)")
    parser.add_argument("--watchdog-dir", "--watchdog_dir", type=str,
                        default="",
                        help="shared directory for heartbeat files and "
                             "desync resync states (default: "
                             "<partition-dir>/coord-<master-addr>-"
                             "<port>, the filesystem multi-host runs "
                             "already share)")
    parser.add_argument("--desync-check-every", "--desync_check_every",
                        type=int, default=0,
                        help="epochs between cross-rank agreement "
                             "checks of per-leaf CRC32 param digests "
                             "through the consensus channel "
                             "(0 disables; mismatch emits a 'desync' "
                             "fault and aborts resumably unless "
                             "--desync-resync)")
    parser.add_argument("--integrity-check-every",
                        "--integrity_check_every", type=int, default=0,
                        help="epochs between SDC integrity checks "
                             "(resilience/integrity.py): fletcher-"
                             "digest scrub of static device tables and "
                             "Freivalds verification of the production "
                             "SpMM at this cadence, cheap params/carry "
                             "digest compares at every boundary, and "
                             "the halo wire-checksum lane in the "
                             "pipelined step; 0 disables (and keeps "
                             "the compiled step byte-identical)")
    parser.add_argument("--desync-resync", "--desync_resync",
                        action="store_true",
                        help="on a detected cross-rank desync, resync "
                             "every rank from rank 0's state (via the "
                             "shared coordination dir) instead of "
                             "aborting with the resumable exit 75")
    parser.add_argument("--no-signal-handlers", "--no_signal_handlers",
                        action="store_true",
                        help="do not install SIGTERM/SIGINT handlers "
                             "(nested launchers that own their signals; "
                             "PIPEGCN_NO_SIGNAL_HANDLERS=1 does the "
                             "same)")
    parser.add_argument("--profile-dir", "--profile_dir", type=str,
                        default="",
                        help="write a jax.profiler trace of a few epochs "
                             "to this directory (TensorBoard format); "
                             "the captured trace is folded into a "
                             "'profile' metrics record with MEASURED "
                             "per-phase device time and comm/compute "
                             "overlap (docs/OBSERVABILITY.md)")
    parser.add_argument("--profile-epochs", "--profile_epochs", type=str,
                        default="",
                        help="'A:B' — capture the device trace around "
                             "epochs [A, B) instead of the default "
                             "auto-window; requires --profile-dir")
    parser.add_argument("--staleness-probe-every",
                        "--staleness_probe_every", type=int, default=0,
                        help="every N epochs measure the per-layer "
                             "relative drift between the stale halo "
                             "features the pipelined step consumed and "
                             "the fresh ones it shipped (emits "
                             "'staleness' records; pipelined mode "
                             "only; 0 disables)")
    parser.add_argument("--anatomy", action="store_true",
                        help="emit an 'anatomy' record before training: "
                             "the compiled step's FLOPs/bytes "
                             "attributed per phase from the optimized "
                             "HLO + XLA cost analysis "
                             "(docs/OBSERVABILITY.md)")
    parser.add_argument("--metrics-out", "--metrics_out", type=str,
                        default="",
                        help="append structured JSONL telemetry (run "
                             "header + per-epoch/eval/summary records; "
                             "schema in pipegcn_tpu/obs/schema.py, see "
                             "docs/OBSERVABILITY.md) to this file; "
                             "summarize with python -m "
                             "pipegcn_tpu.cli.report")
    parser.add_argument("--no-train-traces", "--no_train_traces",
                        action="store_true",
                        help="disable the always-on training-span plane "
                             "(per-block compute/halo_exchange/"
                             "bgrad_return/grad_reduce/checkpoint/eval "
                             "spans + tracesync clock anchors in the "
                             "metrics stream; obs/trainspan.py, "
                             "docs/OBSERVABILITY.md 'Training traces'). "
                             "Spans are host-side only and inert "
                             "without --metrics-out")
    parser.add_argument("--sharded-eval", "--sharded_eval",
                        action="store_true",
                        help="evaluate through the training mesh instead "
                             "of one device (for graphs larger than a "
                             "single device's memory)")
    parser.add_argument("--sync-eval", "--sync_eval", action="store_true",
                        help="block the epoch loop on each evaluation "
                             "instead of the default async dispatch+"
                             "harvest (reference-thread analogue)")
    return parser
