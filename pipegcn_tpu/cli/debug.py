"""Postmortem CLI (docs/OBSERVABILITY.md "Postmortem & flight
recorder").

    python -m pipegcn_tpu.cli.debug explain <run-dir> [--json] \
        [--out metrics.jsonl]

Collects everything a dead run left behind — black-box flight-recorder
dumps (``blackbox-r<k>.json``), every metrics JSONL stream, child log
tails, checkpoint metadata, environment fingerprint — and runs the
evidence-citing rule engine (obs/postmortem.py) over it. Prints a
confidence-ranked verdict with remediation and a last-minutes
timeline; `--json` emits the contracted ``diagnosis`` record instead.
`--out` additionally appends that record to a metrics JSONL sink (the
supervisor and scripts/tpu_window.py use the library entry point
directly).

Exit code: 0 when a diagnosis was reached, 4 when the verdict is
``unknown`` (nothing matched — collect more and retry), 1 on usage /
IO errors."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

EXIT_UNKNOWN = 4


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pipegcn_tpu.cli.debug",
        description="Automated postmortem: diagnose why a run died "
                    "from the artifacts it left behind")
    sub = p.add_subparsers(dest="command", required=True)
    ex = sub.add_parser(
        "explain", help="diagnose a run directory and print the "
                        "verdict with evidence")
    ex.add_argument("run_dir",
                    help="run directory (checkpoint/coordination/"
                         "metrics dir — anything holding the run's "
                         "artifacts)")
    ex.add_argument("--json", action="store_true",
                    help="emit the contracted diagnosis record as "
                         "JSON instead of the human report")
    ex.add_argument("--out", default=None, metavar="METRICS.JSONL",
                    help="also append the diagnosis record to this "
                         "metrics JSONL sink")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    from ..obs import postmortem

    if not os.path.isdir(args.run_dir):
        print(f"pipegcn-debug: not a directory: {args.run_dir}",
              file=sys.stderr)
        return 1
    verdict = postmortem.diagnose_run(args.run_dir)

    if args.out:
        from ..obs.metrics import MetricsLogger

        ml = MetricsLogger(args.out)
        try:
            ml.diagnosis(
                verdict=verdict["verdict"],
                confidence=verdict["confidence"],
                evidence=verdict["evidence"],
                remediation=verdict["remediation"],
                deterministic=verdict["deterministic"],
                run_dir=verdict.get("run_dir", ""),
            )
        finally:
            ml.close()

    if args.json:
        print(json.dumps(verdict))
    else:
        print(postmortem.render(verdict), end="")
    return EXIT_UNKNOWN if verdict["verdict"] == "unknown" else 0


if __name__ == "__main__":
    sys.exit(main())
