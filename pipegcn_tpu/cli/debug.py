"""Postmortem CLI (docs/OBSERVABILITY.md "Postmortem & flight
recorder").

    python -m pipegcn_tpu.cli.debug explain <run-dir> [--json] \
        [--out metrics.jsonl]

Collects everything a dead run left behind — black-box flight-recorder
dumps (``blackbox-r<k>.json``), every metrics JSONL stream, child log
tails, checkpoint metadata, environment fingerprint — and runs the
evidence-citing rule engine (obs/postmortem.py) over it. Prints a
confidence-ranked verdict with remediation and a last-minutes
timeline; `--json` emits the contracted ``diagnosis`` record instead.
`--out` additionally appends that record to a metrics JSONL sink (the
supervisor and scripts/tpu_window.py use the library entry point
directly).

    python -m pipegcn_tpu.cli.debug scrub <run-dir> [--json]

``scrub`` is the offline arm of the integrity plane
(docs/RESILIENCE.md "Silent data corruption"): it digest-verifies
every artifact under a run directory that carries its own integrity
metadata — checkpoint generations (``state-*.npz`` digest manifests
via utils/checkpoint.verify_checkpoint), membership-ledger records
(CRC32, resilience/elastic.MembershipLedger), and kernel-tuning
sidecars (``tuning.json`` format/winner validation) — and lists any
standing rank-quarantine markers. Exit 0 when everything verifies,
2 when ANY artifact is corrupt (so cron/window sweeps can alarm on
at-rest rot before a resume trips over it).

Exit code: 0 when a diagnosis was reached / everything verified, 4
when the verdict is ``unknown`` (nothing matched — collect more and
retry), 2 when ``scrub`` found corruption, 1 on usage / IO errors."""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Optional, Sequence

EXIT_UNKNOWN = 4
EXIT_CORRUPT = 2


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pipegcn_tpu.cli.debug",
        description="Automated postmortem: diagnose why a run died "
                    "from the artifacts it left behind")
    sub = p.add_subparsers(dest="command", required=True)
    ex = sub.add_parser(
        "explain", help="diagnose a run directory and print the "
                        "verdict with evidence")
    ex.add_argument("run_dir",
                    help="run directory (checkpoint/coordination/"
                         "metrics dir — anything holding the run's "
                         "artifacts)")
    ex.add_argument("--json", action="store_true",
                    help="emit the contracted diagnosis record as "
                         "JSON instead of the human report")
    ex.add_argument("--out", default=None, metavar="METRICS.JSONL",
                    help="also append the diagnosis record to this "
                         "metrics JSONL sink")
    sc = sub.add_parser(
        "scrub", help="digest-verify every self-describing artifact "
                      "under a run directory (checkpoints, membership "
                      "ledger, tuning sidecars); exit 2 on corruption")
    sc.add_argument("run_dir",
                    help="run directory to sweep recursively")
    sc.add_argument("--json", action="store_true",
                    help="emit the scrub report as JSON instead of "
                         "the human summary")
    return p


def _scrub(run_dir: str) -> dict:
    """Sweep `run_dir` recursively and digest-verify everything that
    carries integrity metadata. Pure host-side reads — never mutates,
    never needs a device."""
    from ..ops.tuner import TUNING_FILE, load_tuning
    from ..resilience.elastic import (LEDGER_PREFIX, LedgerCorrupt,
                                      MembershipLedger)
    from ..resilience.integrity import read_quarantines
    from ..utils.checkpoint import CheckpointCorrupt, verify_checkpoint

    report: dict = {"run_dir": os.path.abspath(run_dir),
                    "checkpoints": [], "ledger": [], "tuning": [],
                    "quarantines": [], "corrupt": 0}

    for path in sorted(_glob.glob(
            os.path.join(run_dir, "**", "state-*.npz"), recursive=True)):
        rel = os.path.relpath(path, run_dir)
        try:
            epoch = verify_checkpoint(path)
            report["checkpoints"].append(
                {"path": rel, "ok": True, "epoch": epoch})
        except CheckpointCorrupt as exc:
            report["corrupt"] += 1
            report["checkpoints"].append(
                {"path": rel, "ok": False, "error": str(exc)[:300]})

    ledger_dirs = sorted({os.path.dirname(p) for p in _glob.glob(
        os.path.join(run_dir, "**", LEDGER_PREFIX + "*.json"),
        recursive=True)})
    for d in ledger_dirs:
        led = MembershipLedger(d)
        for gen in led.generations():
            rel = os.path.relpath(led.path_for(gen), run_dir)
            try:
                led.read(gen)
                report["ledger"].append(
                    {"path": rel, "ok": True, "generation": gen})
            except LedgerCorrupt as exc:
                report["corrupt"] += 1
                report["ledger"].append(
                    {"path": rel, "ok": False, "generation": gen,
                     "error": str(exc)[:300]})
        for member, info in sorted(read_quarantines(d).items()):
            report["quarantines"].append(
                {"coord_dir": os.path.relpath(d, run_dir),
                 "member": member,
                 "reason": info.get("reason", "unreadable marker")})

    for path in sorted(_glob.glob(
            os.path.join(run_dir, "**", TUNING_FILE), recursive=True)):
        cache_dir = os.path.dirname(path)
        rel = os.path.relpath(path, run_dir)
        rec, reason = load_tuning(cache_dir)
        if rec is not None:
            report["tuning"].append({"path": rel, "ok": True})
        else:
            report["corrupt"] += 1
            report["tuning"].append(
                {"path": rel, "ok": False, "error": reason})

    report["ok"] = report["corrupt"] == 0
    return report


def _render_scrub(report: dict) -> str:
    lines = [f"scrub {report['run_dir']}"]
    for section in ("checkpoints", "ledger", "tuning"):
        items = report[section]
        bad = [i for i in items if not i["ok"]]
        lines.append(f"  {section}: {len(items) - len(bad)}/"
                     f"{len(items)} verified")
        for i in bad:
            lines.append(f"    CORRUPT {i['path']}: {i['error']}")
    for q in report["quarantines"]:
        lines.append(f"  quarantined member {q['member']} "
                     f"({q['coord_dir']}): {q['reason']}")
    lines.append("verdict: " + ("clean" if report["ok"] else
                                f"{report['corrupt']} corrupt "
                                f"artifact(s)"))
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if not os.path.isdir(args.run_dir):
        print(f"pipegcn-debug: not a directory: {args.run_dir}",
              file=sys.stderr)
        return 1

    if args.command == "scrub":
        report = _scrub(args.run_dir)
        if args.json:
            print(json.dumps(report))
        else:
            print(_render_scrub(report), end="")
        return 0 if report["ok"] else EXIT_CORRUPT

    from ..obs import postmortem

    verdict = postmortem.diagnose_run(args.run_dir)

    if args.out:
        from ..obs.metrics import MetricsLogger

        ml = MetricsLogger(args.out)
        try:
            ml.diagnosis(
                verdict=verdict["verdict"],
                confidence=verdict["confidence"],
                evidence=verdict["evidence"],
                remediation=verdict["remediation"],
                deterministic=verdict["deterministic"],
                run_dir=verdict.get("run_dir", ""),
            )
        finally:
            ml.close()

    if args.json:
        print(json.dumps(verdict))
    else:
        print(postmortem.render(verdict), end="")
    return EXIT_UNKNOWN if verdict["verdict"] == "unknown" else 0


if __name__ == "__main__":
    sys.exit(main())
