"""Cross-rank timeline CLI: metrics JSONL -> Perfetto trace.json.

    python -m pipegcn_tpu.cli.timeline rank0.jsonl rank1.jsonl \
        [--out trace.json] [--ranks 0,1]

Merges one metrics JSONL stream per rank (written with --metrics-out;
schema obs/schema.py) into a single Chrome-trace file loadable in
Perfetto (ui.perfetto.dev) or chrome://tracing: ranks as processes,
epochs as slices aligned at dispatch boundaries, faults/recoveries as
instant events, loss and staleness drift as counters, profile-window
phase decompositions as sub-slices, serving windows as counter
tracks, fleet/membership/stream/soak/alert records as instants, and
sampled serving spans (--trace-sample-rate) as slices stitched into
per-query Perfetto flows (docs/OBSERVABILITY.md "Timelines"). Rank
ids come from --ranks, else from each stream's own rank-tagged
records, else from file order.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from ..obs.metrics import read_metrics
from ..obs.timeline import build_timeline, write_timeline


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pipegcn_tpu.cli.timeline",
        description="Merge per-rank metrics JSONL files into one "
                    "Perfetto/Chrome-trace trace.json")
    ap.add_argument("files", nargs="+",
                    help="metrics JSONL file(s), one per rank")
    ap.add_argument("--out", default="trace.json",
                    help="output Chrome-trace path (default trace.json)")
    ap.add_argument("--ranks", default="",
                    help="comma-separated rank ids matching the file "
                         "order (default: rank fields in the records, "
                         "else file order)")
    args = ap.parse_args(argv)

    ranks = []
    if args.ranks:
        try:
            ranks = [int(x) for x in args.ranks.split(",")]
        except ValueError:
            print(f"--ranks must be comma-separated integers, got "
                  f"{args.ranks!r}", file=sys.stderr)
            return 2
        if len(ranks) != len(args.files):
            print(f"--ranks lists {len(ranks)} ids for "
                  f"{len(args.files)} files", file=sys.stderr)
            return 2

    rank_records = []
    for i, path in enumerate(args.files):
        try:
            records = read_metrics(path)
        except (OSError, ValueError) as exc:
            print(f"{path}: {exc}", file=sys.stderr)
            return 1
        if ranks:
            rank = ranks[i]
        else:
            rank = next((r["rank"] for r in records
                         if isinstance(r.get("rank"), int)), i)
        rank_records.append((rank, records))

    obj = build_timeline(rank_records)
    write_timeline(obj, args.out)
    n_ev = sum(1 for e in obj["traceEvents"] if e.get("ph") != "M")
    print(f"wrote {args.out}: {len(rank_records)} rank(s), {n_ev} "
          f"events — open in ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
