"""Launcher + training driver.

The analogue of the reference's `main.py` (load/partition/spawn) and
`train.py run()` (per-rank epoch loop) collapsed into one entry point:
there is no process spawning — the SPMD mesh replaces it — so "launch"
means: resolve config, load + partition the graph (cached on disk like
the reference's partition JSON, helper/utils.py:137 / --skip-partition),
build the Trainer, run the epoch loop with reference-format logging, and
save the best model.

Log-line format parity (reference train.py:369-371):
  Process 000 | Epoch 00009 | Time(s) ... | Comm(s) ... | Reduce(s) ... | Loss ...
Result-file format parity (train.py:33-39, 54-60):
  Epoch 00009 | Accuracy 95.00%                  (inductive)
  Epoch 00009 | Validation Accuracy ... | Test Accuracy ...   (trans)

Multi-host: when n_partitions spans multiple hosts (ceil(n_partitions /
parts_per_node) > 1), `jax.distributed.initialize` is called with the
coordinator at --master-addr:--port and process id --node-rank, after
which jax.devices() covers all hosts and the same SPMD program runs
(ICI intra-slice, DCN across hosts).
"""

from __future__ import annotations

import math
import os
import random
import warnings


from ..graph.datasets import inductive_split, load_data
from ..models.sage import ModelConfig
from ..partition.halo import ShardedGraph
from ..partition.partitioner import locality_clusters, partition_graph
from ..utils.checkpoint import (checkpoint_exists, load_checkpoint,
                                peek_watermark, save_pytree)


def derive_graph_name(args) -> str:
    mode = "induc" if args.inductive else "trans"
    return (f"{args.dataset}-{args.n_partitions}-{args.partition_method}-"
            f"{args.partition_obj}-{mode}")


def result_file_name(args) -> str:
    suffix = ""
    if args.grad_corr and args.feat_corr:
        suffix = "_grad_feat"
    elif args.grad_corr:
        suffix = "_grad"
    elif args.feat_corr:
        suffix = "_feat"
    return os.path.join(
        args.results_dir,
        f"{args.dataset}_n{args.n_partitions}_p{int(args.enable_pipeline)}"
        f"{suffix}.txt",
    )


def _maybe_init_distributed(args) -> None:
    import jax

    n_nodes = math.ceil(args.n_partitions / args.parts_per_node)
    if n_nodes <= 1:
        return
    plat = (os.environ.get("PIPEGCN_PLATFORM")
            or os.environ.get("JAX_PLATFORMS") or "")
    if "cpu" in plat.lower():
        # cross-process collectives on the CPU backend need an explicit
        # implementation (jax >= 0.4.34 raises "Multiprocess
        # computations aren't implemented on the CPU backend" without
        # one); gloo is the bundled choice. Must be set BEFORE
        # initialize(). Harmless if this jaxlib predates the option.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # noqa: BLE001 — older jax: no such config
            pass
    addr = f"{args.master_addr}:{args.port}"
    timeout = int(getattr(args, "coordinator_timeout", 300))
    try:
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=n_nodes,
            process_id=args.node_rank,
            initialization_timeout=timeout,
        )
    except Exception as exc:
        # without this, an unreachable coordinator used to hang the
        # process forever (or die with a bare RPC error no operator
        # could act on)
        raise RuntimeError(
            f"could not join the multi-host coordination service at "
            f"{addr} as process {args.node_rank}/{n_nodes} within "
            f"{timeout}s ({exc}). Check --master-addr/--port, that the "
            f"rank-0 process is up and the port is reachable from this "
            f"host, and raise --coordinator-timeout for slow pod "
            f"bring-up.") from exc


def _local_parts(args):
    """Global partition ids this process will own under the contiguous
    block assignment (node i gets [i*k, (i+1)*k)), or None when a
    single process owns everything. Passed to ShardedGraph.load so an
    elastic relaunch with a REDISTRIBUTED assignment validates its
    per-rank artifact slices at load time (partition/halo.py), not
    mid-epoch."""
    n_nodes = math.ceil(args.n_partitions / args.parts_per_node)
    if n_nodes <= 1:
        return None
    lo = args.node_rank * args.parts_per_node
    hi = min(lo + args.parts_per_node, args.n_partitions)
    return list(range(lo, hi))


def prepare(args):
    """Load, partition (or reuse artifact), and return
    (sharded_graph, eval_graphs or None)."""
    graph_name = args.graph_name or derive_graph_name(args)
    # the local-id ordering is part of the artifact's identity: a
    # cluster-reordered layout and a plain one are both valid but not
    # interchangeable (--skip-partition must never silently reuse the
    # other kind), so the ordering choice gets its own cache key suffix
    # non-default cluster granularity changes the layout, so it gets its
    # own artifact identity (like the "-c" ordering suffix itself)
    from ..partition.partitioner import cluster_suffix

    csuf = "-c" + cluster_suffix(args.cluster_size) \
        if args.local_reorder == "cluster" else ""
    part_name = graph_name + csuf
    part_path = os.path.join(args.partition_dir, part_name)

    g = None
    eval_graphs = None
    if args.eval or not (args.skip_partition and ShardedGraph.exists(part_path)):
        g = load_data(args.dataset, args.data_root)
        if args.inductive:
            train_g, val_g, test_g = inductive_split(g)
            eval_graphs = {"val": (val_g, "val_mask"),
                           "test": (test_g, "test_mask")}
        else:
            train_g = g
            eval_graphs = {"val": (g, "val_mask"), "test": (g, "test_mask")}
        if not args.eval:
            eval_graphs = None

    if args.skip_partition and ShardedGraph.exists(part_path):
        sg = ShardedGraph.load(part_path, parts=_local_parts(args))
        if sg.num_parts != args.n_partitions:
            raise ValueError(
                f"partition artifact at {part_path} has "
                f"{sg.num_parts} parts, requested {args.n_partitions}"
            )
    else:
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            # multi-host: only process 0 partitions (the reference
            # partitions on node_rank 0 only, main.py:32-40); peers poll
            # the shared filesystem for the finished artifact so every
            # process trains on the SAME partition (the partitioner is
            # deterministic per host but not across toolchains)
            sg = _await_partition_artifact(part_path, args.n_partitions,
                                           parts=_local_parts(args))
        else:
            assert g is not None
            # inductive mode partitions the train subgraph only
            # (reference main.py:34-35)
            pg = train_g if args.inductive else g
            seed = args.seed if args.fix_seed else 0
            parts = partition_graph(
                pg, args.n_partitions, method=args.partition_method,
                obj=args.partition_obj, seed=seed,
            )
            cluster = None
            if args.local_reorder == "cluster":
                cluster = locality_clusters(
                    pg, target_size=args.cluster_size, seed=seed)
            # papers100M-class edge lists: the RAM-bounded chunked build
            # (bit-identical output) keeps the O(E) int64 scratch of the
            # plain build from crowding host memory
            build = (ShardedGraph.build_chunked
                     if pg.num_edges > 200_000_000 else ShardedGraph.build)
            sg = build(pg, parts, n_parts=args.n_partitions,
                       cluster=cluster)
            os.makedirs(args.partition_dir, exist_ok=True)
            sg.save(part_path)
            # first runs cache their derived kernel tables too
            sg.cache_dir = part_path
    return sg, eval_graphs


def _prepare_streaming(args):
    """Streaming-mode prepare (--stream-plan / graph-delta faults):
    always builds the sharded graph in memory — the patcher mutates the
    HOST graph and partition arrays in lockstep with the device state,
    which a reloaded artifact would not share — and reserves
    --stream-slack headroom in every padded dimension so scheduled
    deltas land without recompiling. Returns
    (sg, eval_graphs, host_graph, parts)."""
    if args.local_reorder != "none":
        raise ValueError(
            "--stream-plan / graph-delta faults require --local-reorder "
            "none: the patcher appends new nodes in plain local-id "
            "order, and cluster renumbering would break the "
            "patched-vs-rebuilt bit-identity contract")
    if args.use_pp:
        raise ValueError(
            "streaming deltas are incompatible with --use-pp (the "
            "layer-0 precompute bakes in the pre-delta topology)")
    if args.inductive:
        raise ValueError(
            "streaming deltas support transductive runs only (the "
            "inductive split would diverge from the patched graph)")
    if math.ceil(args.n_partitions / args.parts_per_node) > 1:
        raise ValueError(
            "streaming deltas are single-process only (the patcher "
            "owns the full host-side partition state)")
    g = load_data(args.dataset, args.data_root)
    eval_graphs = ({"val": (g, "val_mask"), "test": (g, "test_mask")}
                   if args.eval else None)
    seed = args.seed if args.fix_seed else 0
    parts = partition_graph(
        g, args.n_partitions, method=args.partition_method,
        obj=args.partition_obj, seed=seed)
    sg = ShardedGraph.build(g, parts, n_parts=args.n_partitions,
                            slack=args.stream_slack)
    return sg, eval_graphs, g, parts


def _await_partition_artifact(part_path: str, n_partitions: int,
                              timeout_s: float = 3600.0,
                              poll_s: float = 2.0,
                              max_poll_s: float = 30.0,
                              parts=None):
    """Poll the shared filesystem for process 0's finished artifact.

    Exponential backoff with jitter: a 64-host pod polling a shared
    filesystem in lockstep every 2 s is a thundering herd for the whole
    multi-hour partition build; backing off to `max_poll_s` (desynced
    by the jitter) costs at most one extra poll interval of startup
    latency. A progress line keeps long waits diagnosable from the
    rank's log."""
    import time

    start = time.monotonic()
    deadline = start + timeout_s
    poll = poll_s
    next_report = start
    while not ShardedGraph.exists(part_path):
        now = time.monotonic()
        if now > deadline:
            raise TimeoutError(
                f"timed out waiting for partition artifact at {part_path} "
                f"(is the partition dir on a shared filesystem?)"
            )
        if now >= next_report:
            print(f"waiting for partition artifact at {part_path} "
                  f"({int(now - start)}s elapsed, poll {poll:.1f}s)")
            next_report = now + 30.0
        time.sleep(min(poll + random.uniform(0, poll * 0.25),
                       max(deadline - time.monotonic(), 0.1)))
        poll = min(poll * 1.6, max_poll_s)
    sg = ShardedGraph.load(part_path, parts=parts)
    if sg.num_parts != n_partitions:
        raise ValueError(
            f"partition artifact at {part_path} has {sg.num_parts} parts, "
            f"requested {n_partitions}"
        )
    return sg


def run(args) -> dict:
    """Full training run; returns a result dict (accuracies, timings)."""
    # seed semantics: random unless --fix-seed (reference main.py:11-14)
    if not args.fix_seed:
        if args.parts_per_node < args.n_partitions:
            warnings.warn("Please enable `--fix-seed` for multi-node training.")
        args.seed = random.randint(0, 1 << 31)

    if args.model not in ("graphsage", "gcn", "gat"):
        raise ValueError(f"unknown model: {args.model}")
    if args.model in ("gcn", "gat") and args.use_pp:
        raise ValueError("--use-pp is a GraphSAGE-only optimization")
    if args.backend in ("nccl", "mpi"):
        raise NotImplementedError(
            f"backend {args.backend!r} is not supported; use 'xla'"
        )
    if args.backend not in ("xla", "gloo"):
        raise ValueError(f"unknown backend: {args.backend}")
    if args.resume and not args.checkpoint_dir:
        # fail BEFORE the partition/trainer build: a silent no-op
        # resume restarted multi-day runs from epoch 0 unnoticed
        raise ValueError(
            "--resume requires --checkpoint-dir (there is nothing to "
            "resume from)")
    # validate the loss-scale spec BEFORE the partition/trainer build
    # (a typo'd flag must not burn a multi-minute setup)
    from ..resilience.numerics import LossScaleConfig

    LossScaleConfig.parse(getattr(args, "loss_scale", "off"))
    profile_epochs = None
    if getattr(args, "profile_epochs", ""):
        # parse BEFORE the partition/trainer build: a malformed window
        # must not burn a multi-minute setup
        from ..obs.profiler import parse_profile_epochs

        profile_epochs = parse_profile_epochs(args.profile_epochs)
        if not args.profile_dir:
            raise ValueError(
                "--profile-epochs needs --profile-dir (there is "
                "nowhere to write the trace)")
    # parse the delta schedule BEFORE the partition/trainer build: a
    # missing or corrupt delta file must not burn a multi-minute setup
    # (parse() CRC-checks every batch up front)
    stream_plan = None
    streaming = bool(getattr(args, "stream_plan", "")) or \
        "graph-delta" in getattr(args, "fault_plan", "")
    if getattr(args, "stream_plan", ""):
        from ..stream import StreamPlan

        stream_plan = StreamPlan.parse(args.stream_plan)

    # deferred jax import so the parser works without initializing backends
    import jax

    # PIPEGCN_PLATFORM=cpu forces the CPU backend even where a site hook
    # pins JAX_PLATFORMS (needed for virtual-device mesh testing)
    plat = os.environ.get("PIPEGCN_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    _maybe_init_distributed(args)

    from ..parallel.trainer import TrainConfig, Trainer
    from ..resilience import CoordConfig, Coordinator

    # cross-rank coordination: inactive (pure no-ops) in single-process
    # runs, so fit() keeps one code path. Built BEFORE the partition
    # build and started immediately: heartbeats must flow while this
    # rank spends minutes partitioning / compiling, or its
    # already-training-blocked peers would mistake the silence for
    # death. The shared coordination dir (heartbeats + desync resync)
    # defaults under the partition dir — the filesystem multi-host runs
    # already share — keyed by the rendezvous endpoint so concurrent
    # runs never cross-talk. The consensus channel itself needs the
    # training mesh and is attached after the trainer build.
    coord_dir = args.watchdog_dir or os.path.join(
        args.partition_dir,
        f"coord-{args.master_addr}-{args.port}")
    # under elastic supervision (cli.elastic) the membership generation
    # keys the heartbeat filenames, so a relaunched fleet never sees a
    # previous incarnation's files (resilience/elastic.py)
    try:
        membership_gen = int(os.environ.get("PIPEGCN_MEMBERSHIP_GEN", -1))
    except ValueError:
        membership_gen = -1
    if membership_gen >= 0:
        print(f"elastic membership generation {membership_gen}")
    coord = Coordinator(
        cfg=CoordConfig(
            dir=coord_dir,
            watchdog_timeout=args.watchdog_timeout,
            desync_every=args.desync_check_every,
            desync_resync=args.desync_resync,
            generation=membership_gen,
        ),
        log=print)
    coord.start()

    # ---- flight recorder (obs/flight.py): on by default, breadcrumbs
    # from here on dump to blackbox-r<k>.json in the coordination dir
    # on fault / unhandled exception / preemption / watchdog trip, and
    # on demand via SIGQUIT (kill -QUIT <pid>) ----
    from ..obs import flight as flightrec

    flightrec.configure(rank=jax.process_index(), dump_dir=coord_dir)
    flightrec.install_signal_dump()
    flightrec.crumb("run-start", dataset=args.dataset,
                    n_partitions=args.n_partitions,
                    node_rank=args.node_rank)

    if streaming:
        # streaming needs the live host graph + parts the artifact path
        # discards, so it always builds in memory (with slack headroom)
        sg, eval_graphs, host_g, host_parts = _prepare_streaming(args)
    else:
        sg, eval_graphs = prepare(args)
    # partition-size report (reference prints each rank's node count at
    # setup, train.py:267-268)
    sizes = ", ".join(str(int(c)) for c in sg.inner_count)
    print(f"partition sizes (inner nodes per device): {sizes}")

    n_feat = args.n_feat or sg.n_feat
    n_class = args.n_class or sg.n_class
    n_train = args.n_train or sg.n_train_global
    layer_sizes = (n_feat,) + (args.n_hidden,) * (args.n_layers - 1) + (n_class,)
    cfg = ModelConfig(
        layer_sizes=layer_sizes,
        model=args.model,
        n_heads=args.n_heads,
        n_linear=args.n_linear,
        use_pp=args.use_pp,
        norm=None if args.norm == "none" else args.norm,
        dropout=args.dropout,
        train_size=n_train,
        spmm_chunk=args.spmm_chunk or None,
        spmm_impl=args.spmm_impl,
        block_tile=args.block_tile,
        block_nnz=args.block_nnz or None,
        block_group=args.block_group,
        bucket_merge=args.bucket_merge,
        tune=args.tune,
        tuner_samples=args.tuner_samples,
        rem_dtype=args.rem_dtype,  # 'none' normalized by ModelConfig
        rem_amax=args.rem_amax,
        dropout_bits=args.dropout_bits,
        dtype=args.dtype,
    )
    tcfg = TrainConfig(
        lr=args.lr,
        weight_decay=args.weight_decay,
        n_epochs=args.n_epochs,
        enable_pipeline=args.enable_pipeline,
        feat_corr=args.feat_corr,
        grad_corr=args.grad_corr,
        corr_momentum=args.corr_momentum,
        log_every=args.log_every,
        seed=args.seed,
        eval=args.eval,
        fused_epochs=args.fused_epochs,
        rng_impl=args.rng_impl,
        dropout_reuse=args.dropout_reuse,
        halo_dtype=args.halo_dtype,
        epoch_block=args.epoch_block,
        comm_prefetch=args.comm_prefetch,
        numerics_tripwire=args.numerics_tripwire,
        loss_scale=args.loss_scale,
        integrity_check_every=args.integrity_check_every,
        train_traces=not args.no_train_traces,
    )
    trainer = Trainer(sg, cfg, tcfg)

    patcher = None
    journal = None
    if streaming:
        from ..stream import DeltaJournal, GraphPatcher

        patcher = GraphPatcher(host_g, sg, host_parts,
                               slack=args.stream_slack)
        trainer.enable_stream(patcher)
        n_due = stream_plan.remaining() if stream_plan is not None else 0
        print(f"streaming enabled: {n_due} delta batch(es) scheduled, "
              f"slack={args.stream_slack:.0%}, "
              f"headroom={patcher.slack_remaining()}")
        # write-ahead delta journal: defaults under the checkpoint dir
        # so the elastic supervisor / soak harness inherit durability
        # with zero extra plumbing; --journal-dir overrides
        journal_dir = getattr(args, "journal_dir", "") or (
            os.path.join(args.checkpoint_dir, "journal")
            if args.checkpoint_dir else "")
        if journal_dir:
            journal = DeltaJournal(journal_dir)
            print(f"delta journal at {journal_dir} "
                  f"(last durable seq {journal.last_seq()})")

    graph_name = args.graph_name or derive_graph_name(args)
    os.makedirs(args.results_dir, exist_ok=True)
    rfile = result_file_name(args)

    start_epoch = 0
    replay_stats = None
    wm_seq, wm_gen = -1, 0
    if args.resume:
        if checkpoint_exists(args.checkpoint_dir):
            if journal is not None:
                # crash-consistent streaming resume: the graph below is
                # NOMINAL (checkpoints never hold topology), so replay
                # every journaled seq <= the checkpoint's watermark
                # BEFORE loading state — the params must meet the graph
                # they trained against (a replayed re-pad also restores
                # the carry shapes the checkpoint was saved with). Seqs
                # past the watermark are uncommitted: truncated here,
                # re-delivered by the plan at their scheduled epochs.
                from ..stream import replay_for_resume

                wm_seq, wm_gen = peek_watermark(args.checkpoint_dir)
                replay_stats = replay_for_resume(
                    journal, wm_seq, trainer.apply_graph_deltas,
                    plan=stream_plan)
                if stream_plan is not None:
                    stream_plan.skip_journaled(wm_seq)
                print(f"journal replay to watermark seq={wm_seq}: "
                      f"{replay_stats['replayed']} replayed, "
                      f"{replay_stats['rederived']} re-derived from "
                      f"the plan, {replay_stats['truncated']} "
                      f"uncommitted entr(ies) rolled back; "
                      f"topo_generation={trainer.topo_generation}"
                      + (f" (checkpoint says {wm_gen})"
                         if trainer.topo_generation != wm_gen else ""))
            # host_state() (not device_get): the sharded comm carry is
            # not process-addressable in multi-host runs; every process
            # resumes together, so the allgather inside is lockstep
            host_state, start_epoch = load_checkpoint(
                args.checkpoint_dir, trainer.host_state()
            )
            trainer.restore_state(host_state)
            print(f"resumed from {args.checkpoint_dir} "
                  f"at epoch {start_epoch}")
        else:
            warnings.warn(
                f"--resume: no checkpoint found in "
                f"{args.checkpoint_dir!r}; starting a FRESH run from "
                f"epoch 0 (first checkpoint will be written there)")
            print(f"WARNING: --resume found no checkpoint in "
                  f"{args.checkpoint_dir!r}; training from scratch")

    metrics = None
    if args.metrics_out:
        from ..obs import MetricsLogger, device_info, mesh_info

        metrics = MetricsLogger(args.metrics_out)
        # args-level header (richer than the trainer's fallback): the
        # exact CLI invocation that produced the numbers
        metrics.run_header(
            config=vars(args),
            device=device_info(),
            mesh={"n_parts": args.n_partitions,
                  **mesh_info(trainer.mesh)},
        )
        if replay_stats is not None:
            # the resume replay ran before the sink existed; its audit
            # records land here (soak invariant #9 + the topo-rollback
            # postmortem rule read them)
            metrics.journal(
                op="replay", seq=wm_seq,
                topo_generation=int(trainer.topo_generation),
                n_records=replay_stats["replayed"], source="resume",
                rederived=replay_stats["rederived"],
                watermark_generation=wm_gen)
            if replay_stats["truncated"]:
                metrics.journal(
                    op="truncate", seq=wm_seq,
                    topo_generation=int(trainer.topo_generation),
                    n_records=replay_stats["truncated"],
                    source="resume")

    # ---- fault tolerance (docs/RESILIENCE.md) ----
    from ..resilience import (DivergenceSentinel, FaultPlan,
                              PreemptionHandler, SentinelConfig)

    sentinel = None
    if getattr(args, "sentinel", True):
        sentinel = DivergenceSentinel(SentinelConfig(
            loss_factor=args.sentinel_loss_factor,
            grad_norm_max=args.sentinel_grad_max,
            max_retries=args.sentinel_max_retries,
            lr_backoff=args.sentinel_lr_backoff,
            snapshot_every=args.sentinel_snapshot_every,
            flush_on_trip=args.sentinel_flush,
        ))
    fault_plan = FaultPlan.parse(args.fault_plan,
                                 rank=jax.process_index()) \
        if args.fault_plan else None
    preemption = PreemptionHandler()
    # the coordinator has been heartbeating since before the partition
    # build; now that the mesh and metrics sink exist, complete it
    coord.attach_mesh(trainer.mesh)
    coord.metrics = metrics

    if getattr(args, "anatomy", False):
        # compiled-step anatomy: FLOPs/bytes per phase from the
        # optimized HLO (obs/anatomy.py). Costs one single-epoch
        # compile up front — opt-in for that reason.
        from ..obs.anatomy import step_anatomy

        rec = step_anatomy(trainer)
        frac = rec.get("attributed_flops_fraction")
        print(f"epoch anatomy: {rec['n_ops']} HLO ops, "
              f"{rec['est_flops']:.3e} est FLOPs"
              + (f", {frac:.1%} attributed to named phases"
                 if frac is not None else ""))
        if metrics is not None:
            extras = {k: v for k, v in rec.items()
                      if k not in ("phases", "est_flops", "flops",
                                   "attributed_flops_fraction")}
            metrics.anatomy(rec["phases"], rec["est_flops"],
                            rec["flops"],
                            rec["attributed_flops_fraction"], **extras)

    try:
        with preemption.installed(enabled=not args.no_signal_handlers):
            fit_res = trainer.fit(
                eval_graphs,
                start_epoch=start_epoch,
                reference_logs=True,
                result_file=rfile,
                inductive=args.inductive,
                checkpoint_dir=args.checkpoint_dir or None,
                checkpoint_every=args.checkpoint_every,
                checkpoint_keep=args.checkpoint_keep,
                checkpoint_fallback_dir=getattr(
                    args, "checkpoint_fallback_dir", "") or None,
                profile_dir=args.profile_dir or None,
                profile_epochs=profile_epochs,
                staleness_probe_every=args.staleness_probe_every,
                measure_comm_cost=True,
                sharded_eval=args.sharded_eval,
                async_eval=not args.sync_eval,
                metrics=metrics,
                sentinel=sentinel,
                preemption=preemption,
                fault_plan=fault_plan,
                stream_plan=stream_plan,
                journal=journal,
                coord=coord,
            )
            if journal is not None and args.resume:
                # prove the replayed topology: the patched tables must
                # digest-match a from-scratch rebuild of the post-delta
                # graph (the PR-13 bit-identity oracle as a runtime
                # check; soak invariant #9 reads this record)
                from ..stream import verify_against_rebuild

                v = verify_against_rebuild(patcher)
                print(f"journal verify: tables_match="
                      f"{v['tables_match']}, topo_generation="
                      f"{trainer.topo_generation}"
                      + (f", mismatched tables: {v['mismatch']}"
                         if v["mismatch"] else ""))
                if metrics is not None:
                    metrics.journal(
                        op="verify", seq=int(patcher.last_seq),
                        topo_generation=int(trainer.topo_generation),
                        n_records=0, source="resume",
                        tables_match=bool(v["tables_match"]),
                        mismatch=list(v["mismatch"]))
    finally:
        coord.stop()
        # every record is already flushed; close releases the handle
        # even when training crashes mid-run
        if metrics is not None:
            metrics.close()

    result = {
        "graph_name": graph_name,
        "epoch_time": fit_res["epoch_time"],
        "best_val": fit_res["best_val"],
        "best_epoch": fit_res["best_epoch"],
    }
    if args.metrics_out:
        result["metrics_out"] = args.metrics_out
    if args.eval and fit_res["best_params"] is not None:
        os.makedirs(args.model_dir, exist_ok=True)
        model_path = os.path.join(args.model_dir, f"{graph_name}_final.npz")
        # multi-host: identical replicated params on every process;
        # process 0 writes (save_pytree's pid-temp makes a stray
        # concurrent writer harmless, but N copies are pure waste)
        import jax

        if jax.process_index() == 0:
            save_pytree(model_path, fit_res["best_params"])
        print("model saved")
        print("Validation accuracy {:.2%}".format(fit_res["best_val"]))
        print("Test Result | Accuracy {:.2%}".format(fit_res["test_acc"]))
        result["test_acc"] = fit_res["test_acc"]
        result["model_path"] = model_path
    return result


def cli_entry() -> None:
    import sys

    from ..resilience import EXIT_PREEMPTED, PeerLost, Preempted
    from .parser import create_parser

    args = create_parser().parse_args()
    print(args)
    try:
        run(args)
    except Preempted as p:
        # distinct resumable status (EX_TEMPFAIL): a supervisor retries
        # with --resume on 75, treats anything else as a real failure
        print(f"preempted at epoch {p.epoch} ({p.reason}); resumable — "
              f"rerun with --resume --checkpoint-dir "
              f"{args.checkpoint_dir!r} [exit {EXIT_PREEMPTED}]")
        import jax

        if jax.process_count() > 1:
            # a ONE-SIDED preemption (an SDC quarantine asks only the
            # striking rank to leave) strands the peers mid-epoch: the
            # graceful exit's distributed-shutdown barrier can never
            # complete once they watchdog out, and the coordination
            # client would SIGABRT over the resumable status — same
            # reasoning as the PeerLost branch below
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(EXIT_PREEMPTED)
        sys.exit(EXIT_PREEMPTED)
    except PeerLost as p:
        # a dead peer is the platform's problem, not this state's: the
        # crash checkpoint is valid, so the supervisor reschedules the
        # whole pod and resumes — same contract as preemption. Exit via
        # os._exit: a graceful sys.exit runs jax's atexit distributed
        # shutdown, whose barrier can never complete with a dead peer —
        # the coordination client then hard-aborts the process (SIGABRT)
        # and the resumable status is lost.
        print(f"peer lost ({p}); resumable — restart the pod with "
              f"--resume --checkpoint-dir {args.checkpoint_dir!r} "
              f"[exit {EXIT_PREEMPTED}]")
        sys.stdout.flush()
        sys.stderr.flush()
        # os._exit skips atexit AND io teardown; the metrics sink was
        # closed (flushed) by run()'s finally, and fault records are
        # fsynced at write time (MetricsLogger.hard_flush), so the
        # final peer-lost record is already durable here
        os._exit(EXIT_PREEMPTED)
    except (Exception, KeyboardInterrupt) as exc:
        # unhandled exception: leave a black box beside the run before
        # the traceback propagates (skipped when the recorder was
        # never pointed at a run dir — the failure predates setup).
        # fit()'s own crash handler already dumped in-training
        # failures; re-dumping the same path with the newest crumbs is
        # idempotent.
        from ..obs import flight as flightrec

        if flightrec.get_recorder().dump_dir:
            flightrec.dump_blackbox(
                "exception",
                error=f"{type(exc).__name__}: {exc}"[:200])
        raise


if __name__ == "__main__":
    cli_entry()
