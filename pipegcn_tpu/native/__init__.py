"""Native (C++) host-runtime components, loaded via ctypes.

The reference's host-side heavy lifting is native code it links against —
METIS partitioning (C) and DGL's C++ graph/partition machinery
(SURVEY.md §2b). This package holds the framework's own native
equivalents, compiled on demand from the bundled C++ sources with the
system toolchain (g++), no third-party deps.

Loading policy: the first call to `get_lib()` compiles (if needed) and
dlopens the shared library. Failures — no compiler, read-only install —
degrade gracefully: callers check `available()` and fall back to the
pure-numpy implementations. Set PIPEGCN_NATIVE=0 to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["partitioner.cpp", "halo_builder.cpp"]
_LIB_NAME = "libpipegcn_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(lib_path: str) -> bool:
    srcs = [os.path.join(_DIR, s) for s in _SOURCES
            if os.path.exists(os.path.join(_DIR, s))]
    if not srcs:
        return False
    # compile to a unique temp name in the destination dir, then rename:
    # rename is atomic, so concurrent processes never dlopen a half-
    # written library (the per-process lock can't serialize across
    # processes)
    tmp_path = f"{lib_path}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-o", tmp_path,
           *srcs]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if res.returncode != 0:
            import sys
            print(f"pipegcn_tpu.native build failed:\n{res.stderr}",
                  file=sys.stderr)
            return False
        os.replace(tmp_path, lib_path)
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp_path):
            try:
                os.remove(tmp_path)
            except OSError:
                # genuinely-optional (storage-fault audit): orphaned
                # build temp; the caller already returned the build
                # verdict
                pass
    return True


def _lib_path() -> str:
    # prefer in-package (cached across runs); fall back to a per-user
    # cache dir if the install is read-only (never a shared temp dir —
    # a world-writable predictable path would let another local user
    # plant a library that we would dlopen)
    cand = os.path.join(_DIR, _LIB_NAME)
    if os.path.exists(cand) or os.access(_DIR, os.W_OK):
        return cand
    cache = os.environ.get("XDG_CACHE_HOME",
                           os.path.join(os.path.expanduser("~"), ".cache"))
    d = os.path.join(cache, "pipegcn_tpu")
    os.makedirs(d, mode=0o700, exist_ok=True)
    return os.path.join(d, _LIB_NAME)


def _stale(lib_path: str) -> bool:
    if not os.path.exists(lib_path):
        return True
    lib_mtime = os.path.getmtime(lib_path)
    return any(
        os.path.getmtime(os.path.join(_DIR, s)) > lib_mtime
        for s in _SOURCES if os.path.exists(os.path.join(_DIR, s))
    )


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if os.environ.get("PIPEGCN_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _lib_path()
        if _stale(path) and not _build(path):
            return None
        try:
            lib = ctypes.CDLL(path)
            _declare(lib)
        except (OSError, AttributeError):
            # AttributeError: a stale library missing newly-declared
            # symbols (e.g. built before a source was added) must degrade
            # to the numpy fallback like every other load failure
            return None
        _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _declare(lib: ctypes.CDLL) -> None:
    c_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    c_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    c_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    lib.pgt_partition.restype = ctypes.c_int
    lib.pgt_partition.argtypes = [
        ctypes.c_int64, c_i64p, c_i32p,          # n, indptr, indices
        ctypes.c_int32, ctypes.c_int,            # n_parts, objective
        ctypes.c_uint64, ctypes.c_double,        # seed, imbalance
        ctypes.c_int, c_i32p,                    # refine_iters, out
    ]
    lib.pgt_radix_argsort_u64.restype = ctypes.c_int
    lib.pgt_radix_argsort_u64.argtypes = [
        ctypes.c_int64, c_u64p, c_i64p,          # n, keys, out order
    ]


def native_partition(
    indptr: np.ndarray,
    indices: np.ndarray,
    n_parts: int,
    obj: str = "vol",
    seed: int = 0,
    imbalance: float = 1.05,
    refine_iters: int = 10,
) -> np.ndarray:
    """Multilevel k-way partition of a symmetric CSR adjacency.

    Native equivalent of the reference's METIS call (helper/utils.py:143
    with objtype passthrough). Raises RuntimeError if the native library
    is unavailable — callers should check available() first.
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = indptr.shape[0] - 1
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    out = np.empty(n, dtype=np.int32)
    rc = lib.pgt_partition(
        n, indptr, indices, np.int32(n_parts),
        1 if obj == "vol" else 0, np.uint64(seed), float(imbalance),
        int(refine_iters), out,
    )
    if rc != 0:
        raise RuntimeError(f"pgt_partition failed with code {rc}")
    return out


def stable_argsort(keys: np.ndarray) -> np.ndarray:
    """Stable argsort of non-negative integer keys: the native LSD radix
    sort when the library is available and the array is large enough to
    matter, else numpy. The shared fast path for every O(E) host sort
    (halo build, kernel table builds, eval-edge CSR ordering)."""
    if keys.size >= 1 << 20 and available():
        return radix_argsort(keys)
    return np.argsort(keys, kind="stable")


def radix_argsort(keys: np.ndarray) -> np.ndarray:
    """Stable argsort of non-negative integer keys via the native LSD
    radix sort (halo_builder.cpp) — the fast path for ShardedGraph.build's
    100M+-edge sorts. Identical permutation to
    np.argsort(keys, kind='stable'). Raises RuntimeError if the native
    library is unavailable — callers should check available() first."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    out = np.empty(keys.shape[0], dtype=np.int64)
    rc = lib.pgt_radix_argsort_u64(keys.shape[0], keys, out)
    if rc != 0:
        raise RuntimeError(f"pgt_radix_argsort_u64 failed with code {rc}")
    return out
