// Native host kernels for the halo index pipeline (partition/halo.py).
//
// ShardedGraph.build's dominant cost at Reddit scale is sorting the
// ~114M-edge list by (owner device, local destination) — a two-key
// numpy lexsort taking tens of seconds to minutes. The build fuses the
// keys into one uint64 and sorts here with a stable LSD radix sort
// (comparison-free, one 256-bucket pass per significant byte), the
// native analogue of the C++ graph machinery the reference leans on
// (DGL's partition/csr code, SURVEY.md §2b).

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Stable argsort of uint64 keys: writes the permutation (int64 indices)
// into `out`. LSD radix over 11-bit digits on (key, index) PAIRS — the
// payload travels with the key so every pass streams memory instead of
// gathering keys[idx] (the gather's cache misses dominate otherwise).
// Only digits below the maximum key's bit-width run (the common fused
// key owner*N + local_id fits in ~31 bits → 3 passes). Returns 0.
int pgt_radix_argsort_u64(int64_t n, const uint64_t* keys, int64_t* out) {
  if (n < 0 || (n > 0 && (!keys || !out))) return 1;
  if (n == 0) return 0;

  constexpr int kDigitBits = 11;
  constexpr int kBuckets = 1 << kDigitBits;
  constexpr uint64_t kMask = kBuckets - 1;

  uint64_t max_key = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (keys[i] > max_key) max_key = keys[i];
  }
  int n_passes = 0;
  while (max_key >> (kDigitBits * n_passes)) ++n_passes;
  if (n_passes == 0) n_passes = 1;

  struct Pair {
    uint64_t k;
    int64_t i;
  };
  std::vector<Pair> a(n), b(n);
  for (int64_t i = 0; i < n; ++i) {
    a[i].k = keys[i];
    a[i].i = i;
  }
  Pair* cur = a.data();
  Pair* nxt = b.data();

  std::vector<int64_t> hist(kBuckets);
  for (int p = 0; p < n_passes; ++p) {
    const int shift = kDigitBits * p;
    std::memset(hist.data(), 0, kBuckets * sizeof(int64_t));
    for (int64_t i = 0; i < n; ++i) {
      ++hist[(cur[i].k >> shift) & kMask];
    }
    int populated = 0;
    for (int d = 0; d < kBuckets && populated < 2; ++d) {
      if (hist[d]) ++populated;
    }
    if (populated < 2) continue;  // uniform digit: pass is a no-op
    int64_t run = 0;
    for (int d = 0; d < kBuckets; ++d) {
      const int64_t c = hist[d];
      hist[d] = run;
      run += c;
    }
    for (int64_t i = 0; i < n; ++i) {
      nxt[hist[(cur[i].k >> shift) & kMask]++] = cur[i];
    }
    Pair* t = cur;
    cur = nxt;
    nxt = t;
  }
  for (int64_t i = 0; i < n; ++i) out[i] = cur[i].i;
  return 0;
}

}  // extern "C"
