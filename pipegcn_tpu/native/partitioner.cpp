// Native multilevel k-way graph partitioner.
//
// TPU-native replacement for the METIS C library the reference reaches
// through its customized DGL fork (reference helper/utils.py:132-144,
// README.md:62 — the fork exists only to pass objtype='vol'|'cut' through
// to METIS). Same role, same objective surface:
//
//   objective = 0 ('cut')  minimize edges crossing partitions
//   objective = 1 ('vol')  minimize communication volume: distinct
//                          (node, foreign-partition) halo pairs — the
//                          quantity PipeGCN-style training exchanges
//                          every layer.
//
// Classic multilevel scheme (Karypis & Kumar style, independent
// implementation):
//   1. coarsen by randomized heavy-edge matching, accumulating edge and
//      node weights, until the graph is small;
//   2. initial k-way partition on the coarsest graph: BFS-grown
//      contiguous blocks balanced by node weight;
//   3. uncoarsen, at every level running boundary FM-style refinement:
//      greedy positive-gain moves under a node-weight balance cap, with
//      the gain formula matching the requested objective.
//
// Deterministic for a fixed seed. Single-threaded C++17, no deps.
//
// C API (ctypes-friendly): pgt_partition() at the bottom.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <numeric>
#include <queue>
#include <random>
#include <tuple>
#include <vector>

namespace {

// Non-owning CSR view: the finest level aliases the CALLER's arrays
// (no 12.8 GB indices copy at papers100M scale) with IMPLICIT unit
// edge/node weights (null pointers — no 25.6 GB all-ones ewgt).
// Coarse levels own int32 weights (a merged weight is bounded by the
// fine edges merged into it, far below 2^31 in practice; saturated on
// overflow in coarsen()).
struct CsrView {
  int64_t n = 0;
  const int64_t* indptr = nullptr;   // [n+1]
  const int32_t* indices = nullptr;  // [m]
  const int32_t* ewgt = nullptr;     // [m]; null => all edges weight 1
  const int32_t* nwgt = nullptr;     // [n]; null => all nodes weight 1
  int64_t m() const { return indptr[n]; }
};

inline int64_t ew(const CsrView& g, int64_t e) {
  return g.ewgt ? (int64_t)g.ewgt[e] : 1;
}
inline int64_t nw(const CsrView& g, int64_t u) {
  return g.nwgt ? (int64_t)g.nwgt[u] : 1;
}

struct Csr {
  int64_t n = 0;
  std::vector<int64_t> indptr;   // [n+1]
  std::vector<int32_t> indices;  // [m] neighbor ids
  std::vector<int32_t> ewgt;     // [m] edge weights
  std::vector<int32_t> nwgt;     // [n] node weights

  CsrView view() const {
    return {n, indptr.data(), indices.data(), ewgt.data(), nwgt.data()};
  }
};

// ---------------------------------------------------------------------
// Coarsening: randomized heavy-edge matching.

// Build the coarse graph induced by a fine->coarse map: aggregate
// parallel edges, drop (coarse) self loops. Shared by incremental
// coarsening AND the uncoarsening-time rebuild of unstored levels
// (contract(level0, composed map) reproduces level i exactly — edge
// weights aggregate additively along map composition).
Csr contract(const CsrView& g, const int32_t* map, int64_t nc) {
  const int64_t n = g.n;
  Csr c;
  c.n = nc;
  c.nwgt.assign(nc, 0);
  for (int64_t u = 0; u < n; ++u) {
    int64_t w = (int64_t)c.nwgt[map[u]] + nw(g, u);
    c.nwgt[map[u]] = (int32_t)std::min<int64_t>(w, INT32_MAX);
  }

  // count then fill, merging duplicates with a per-node scratch table
  std::vector<int64_t> scratch_w(nc, 0);
  std::vector<int32_t> scratch_nbr;
  scratch_nbr.reserve(256);

  // two passes over fine edges grouped by coarse node; build fine-node
  // lists per coarse node first
  std::vector<int64_t> cstart(nc + 1, 0);
  for (int64_t u = 0; u < n; ++u) cstart[map[u] + 1]++;
  for (int64_t i = 0; i < nc; ++i) cstart[i + 1] += cstart[i];
  std::vector<int32_t> members(n);
  {
    std::vector<int64_t> cur(cstart.begin(), cstart.end() - 1);
    for (int64_t u = 0; u < n; ++u) members[cur[map[u]]++] = (int32_t)u;
  }

  c.indptr.assign(nc + 1, 0);
  // pass 1: count distinct coarse neighbors
  for (int64_t cu = 0; cu < nc; ++cu) {
    scratch_nbr.clear();
    for (int64_t mi = cstart[cu]; mi < cstart[cu + 1]; ++mi) {
      int32_t u = members[mi];
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t cv = map[g.indices[e]];
        if (cv == cu) continue;
        if (scratch_w[cv] == 0) scratch_nbr.push_back(cv);
        scratch_w[cv] += ew(g, e);
      }
    }
    c.indptr[cu + 1] = c.indptr[cu] + (int64_t)scratch_nbr.size();
    for (int32_t cv : scratch_nbr) scratch_w[cv] = 0;
  }
  c.indices.resize(c.indptr[nc]);
  c.ewgt.resize(c.indptr[nc]);
  // pass 2: fill
  for (int64_t cu = 0; cu < nc; ++cu) {
    scratch_nbr.clear();
    for (int64_t mi = cstart[cu]; mi < cstart[cu + 1]; ++mi) {
      int32_t u = members[mi];
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t cv = map[g.indices[e]];
        if (cv == cu) continue;
        if (scratch_w[cv] == 0) scratch_nbr.push_back(cv);
        scratch_w[cv] += ew(g, e);
      }
    }
    int64_t pos = c.indptr[cu];
    for (int32_t cv : scratch_nbr) {
      c.indices[pos] = cv;
      c.ewgt[pos] =
          (int32_t)std::min<int64_t>(scratch_w[cv], INT32_MAX);
      scratch_w[cv] = 0;
      ++pos;
    }
  }
  return c;
}

// Returns coarse graph + mapping fine node -> coarse node.
Csr coarsen(const CsrView& g, std::mt19937_64& rng,
            std::vector<int32_t>& map) {
  const int64_t n = g.n;
  map.assign(n, -1);
  std::vector<int32_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::shuffle(order.begin(), order.end(), rng);

  // heavy-edge matching: visit nodes in random order, match each
  // unmatched node with its unmatched neighbor of max edge weight
  int32_t nc = 0;
  std::vector<int32_t> match(n, -1);
  for (int64_t i = 0; i < n; ++i) {
    int32_t u = order[i];
    if (match[u] != -1) continue;
    int32_t best = -1;
    int64_t best_w = -1;
    for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
      int32_t v = g.indices[e];
      if (v == u || match[v] != -1) continue;
      if (ew(g, e) > best_w) { best_w = ew(g, e); best = v; }
    }
    match[u] = (best == -1) ? u : best;
    if (best != -1) match[best] = u;
    map[u] = nc;
    if (best != -1) map[best] = nc;
    ++nc;
  }

  // Cluster pass (HEM* — what METIS does when plain HEM stalls): on
  // hub-heavy graphs most of a hub's neighbors are already matched by
  // the time the sweep reaches them, leaving singleton coarse nodes
  // and a ~0.75 shrink per level, i.e. ~2x the levels and ~2x the
  // refinement work and hierarchy RAM. Let leftover singletons join a
  // neighbor's coarse node (heaviest edge) up to 4 fine members, which
  // restores ~0.5 shrink. Renumber coarse ids densely afterwards.
  {
    std::vector<int32_t> csize(nc, 0);
    for (int64_t u = 0; u < n; ++u) csize[map[u]]++;
    for (int64_t i = 0; i < n; ++i) {
      int32_t u = order[i];
      if (match[u] != u || csize[map[u]] != 1) continue;  // not singleton
      int32_t best = -1;
      int64_t best_w = -1;
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t v = g.indices[e];
        if (v == u || map[v] == map[u] || csize[map[v]] >= 4) continue;
        if (ew(g, e) > best_w) { best_w = ew(g, e); best = v; }
      }
      if (best != -1) {
        csize[map[u]]--;
        map[u] = map[best];
        csize[map[u]]++;
      }
    }
    std::vector<int32_t> renum(nc, -1);
    int32_t dense = 0;
    for (int64_t u = 0; u < n; ++u) {
      if (renum[map[u]] == -1) renum[map[u]] = dense++;
      map[u] = renum[map[u]];
    }
    nc = dense;
  }

  return contract(g, map.data(), nc);
}

// ---------------------------------------------------------------------
// Initial partition on the coarsest graph: BFS order, contiguous blocks
// balanced by node weight.

void initial_partition(const CsrView& g, int32_t k, std::mt19937_64& rng,
                       std::vector<int32_t>& parts) {
  const int64_t n = g.n;
  parts.assign(n, 0);
  std::vector<int32_t> order;
  order.reserve(n);
  std::vector<char> visited(n, 0);
  std::vector<int32_t> restart(n);
  std::iota(restart.begin(), restart.end(), 0);
  std::shuffle(restart.begin(), restart.end(), rng);
  int64_t cursor = 0;
  std::vector<int32_t> queue;
  while ((int64_t)order.size() < n) {
    while (cursor < n && visited[restart[cursor]]) ++cursor;
    int32_t s = restart[cursor];
    visited[s] = 1;
    queue.assign(1, s);
    size_t qh = 0;
    order.push_back(s);
    while (qh < queue.size()) {
      int32_t u = queue[qh++];
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t v = g.indices[e];
        if (!visited[v]) {
          visited[v] = 1;
          queue.push_back(v);
          order.push_back(v);
        }
      }
    }
  }
  int64_t total_w = 0;
  for (int64_t u = 0; u < n; ++u) total_w += nw(g, u);
  // walk the BFS order filling part 0, then 1, ... by weight quota
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t p = (int32_t)std::min<int64_t>((acc * k) / std::max<int64_t>(total_w, 1),
                                           k - 1);
    parts[order[i]] = p;
    acc += nw(g, order[i]);
  }
}

// ---------------------------------------------------------------------
// Refinement: FM-style greedy boundary passes.
//
// For 'cut', gain(u, p) = w(u->p) - w(u->own).
// For 'vol', add the change in distinct halo pairs: moving u to p removes
// the (u, p) pair, creates a (u, own) pair if u keeps neighbors there —
// approximated (as in the Python refiner) with indicator terms
// [w(u->p) > 0] - [w(u->own) > 0]; neighbor-side pair changes are second
// order and ignored.

// One definition of the balance cap and the per-move gain, shared by
// the greedy and FM phases — two copies would let them silently
// enforce different caps/objectives in the same refinement loop.
int64_t balance_cap(const CsrView& g, int32_t k, double imbalance) {
  int64_t total_w = 0;
  for (int64_t u = 0; u < g.n; ++u) total_w += nw(g, u);
  return (int64_t)(imbalance * (double)((total_w + k - 1) / k)) + 1;
}

inline int64_t move_gain(int64_t conn_p, int64_t conn_own, int objective) {
  int64_t gain = conn_p - conn_own;
  if (objective == 1)
    gain += (conn_p > 0 ? 1 : 0) - (conn_own > 0 ? 1 : 0);
  return gain;
}

void refine(const CsrView& g, int32_t k, int objective, int iters,
            double imbalance, std::vector<int32_t>& parts,
            std::mt19937_64& rng) {
  const int64_t n = g.n;
  const int64_t cap = balance_cap(g, k, imbalance);

  std::vector<int64_t> psize(k, 0);
  for (int64_t u = 0; u < n; ++u) psize[parts[u]] += nw(g, u);

  std::vector<int64_t> conn(k, 0);  // edge weight to each part, per node
  std::vector<int32_t> touched;
  touched.reserve(64);
  std::vector<int32_t> order(n);
  std::iota(order.begin(), order.end(), 0);

  for (int it = 0; it < iters; ++it) {
    std::shuffle(order.begin(), order.end(), rng);
    int64_t moved = 0;
    for (int64_t i = 0; i < n; ++i) {
      int32_t u = order[i];
      int32_t pu = parts[u];
      if (psize[pu] - nw(g, u) <= 0) continue;  // never drain a part
      touched.clear();
      bool boundary = false;
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t pv = parts[g.indices[e]];
        if (conn[pv] == 0) touched.push_back(pv);
        conn[pv] += ew(g, e);
        if (pv != pu) boundary = true;
      }
      if (boundary) {
        int64_t own = conn[pu];
        int64_t best_gain = 0;
        int32_t best_p = -1;
        for (int32_t p : touched) {
          if (p == pu || psize[p] + nw(g, u) > cap) continue;
          int64_t gain = move_gain(conn[p], own, objective);
          if (gain > best_gain ||
              (gain == best_gain && best_p != -1 && psize[p] < psize[best_p])) {
            best_gain = gain;
            best_p = p;
          }
        }
        if (best_p != -1 && best_gain > 0) {
          psize[pu] -= nw(g, u);
          psize[best_p] += nw(g, u);
          parts[u] = best_p;
          ++moved;
        }
      }
      for (int32_t p : touched) conn[p] = 0;
    }
    if (moved == 0) break;
  }
}

// True objective value of a partition: 'cut' counts each crossing edge
// twice (symmetric CSR) — consistent for comparisons; 'vol' counts
// distinct (node, foreign-part) halo pairs.
int64_t eval_objective(const CsrView& g, int32_t k, int objective,
                       const std::vector<int32_t>& parts) {
  int64_t obj = 0;
  std::vector<char> seen(k, 0);
  std::vector<int32_t> touched;
  touched.reserve(64);
  for (int64_t u = 0; u < g.n; ++u) {
    int32_t pu = parts[u];
    for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
      int32_t pv = parts[g.indices[e]];
      if (pv == pu) continue;
      if (objective == 0) {
        obj += ew(g, e);
      } else if (!seen[pv]) {
        seen[pv] = 1;
        touched.push_back(pv);
        ++obj;
      }
    }
    for (int32_t p : touched) seen[p] = 0;
    touched.clear();
  }
  return obj;
}

// ---------------------------------------------------------------------
// FM-style hill climbing: unlike the greedy pass, moves may have
// NEGATIVE gain — the pass tracks the cumulative objective delta,
// remembers the best prefix of the move sequence, and rolls back
// everything after it. This is what lets the partition escape the
// local minima the greedy pass terminates in (the classic
// Fiduccia–Mattheyses ingredient METIS-grade refinement relies on).
// Lazy max-heap with per-node version stamps; moved nodes lock for the
// pass. Returns true if the pass improved the objective.

bool fm_pass(const CsrView& g, int32_t k, int objective, int64_t cap,
             std::vector<int64_t>& psize, std::vector<int32_t>& parts,
             bool eager) {
  const int64_t n = g.n;
  // consecutive non-improving moves tolerated before the pass stops —
  // bounds both wasted work and rollback length
  const int max_drift = 512;

  std::vector<int64_t> conn(k, 0);
  std::vector<int32_t> touched;
  touched.reserve(64);

  // best (gain, target) for u under the balance cap; target -1 if none
  auto best_move = [&](int32_t u, int64_t& gain_out) -> int32_t {
    int32_t pu = parts[u];
    if (psize[pu] - nw(g, u) <= 0) return -1;
    touched.clear();
    for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
      int32_t pv = parts[g.indices[e]];
      if (conn[pv] == 0) touched.push_back(pv);
      conn[pv] += ew(g, e);
    }
    int64_t own = conn[pu];
    int64_t best_gain = INT64_MIN;
    int32_t best_p = -1;
    for (int32_t p : touched) {
      if (p == pu || psize[p] + nw(g, u) > cap) continue;
      int64_t gain = move_gain(conn[p], own, objective);
      if (gain > best_gain) {
        best_gain = gain;
        best_p = p;
      }
    }
    for (int32_t p : touched) conn[p] = 0;
    gain_out = best_gain;
    return best_p;
  };

  // heap entries: (gain, node, target, version). Stale entries are
  // skipped on pop via the version stamp; gains are CACHED per node
  // (last_gain/last_p) so a neighbor invalidation is an O(log) push of
  // the stale value, not an O(deg) recompute — the true gain is
  // recomputed lazily only when the entry surfaces at the top.
  using Entry = std::tuple<int64_t, int32_t, int32_t, uint32_t>;
  std::priority_queue<Entry> heap;
  std::vector<uint32_t> ver(n, 0);
  std::vector<char> locked(n, 0);
  std::vector<int64_t> last_gain(n, INT64_MIN);
  std::vector<int32_t> last_p(n, -1);

  for (int64_t u = 0; u < n; ++u) {
    bool boundary = false;
    int32_t pu = parts[u];
    for (int64_t e = g.indptr[u]; e < g.indptr[u + 1] && !boundary; ++e)
      boundary = parts[g.indices[e]] != pu;
    if (!boundary) continue;
    int64_t gain;
    int32_t p = best_move((int32_t)u, gain);
    if (p != -1) {
      last_gain[u] = gain;
      last_p[u] = p;
      heap.emplace(gain, (int32_t)u, p, 0u);
    }
  }

  std::vector<std::pair<int32_t, int32_t>> moves;  // (node, from)
  int64_t cum = 0, best_cum = 0;
  size_t best_len = 0;
  int drift = 0;

  while (!heap.empty() && drift < max_drift) {
    auto [gain, u, p, stamp] = heap.top();
    heap.pop();
    if (locked[u] || stamp != ver[u]) continue;
    // entry may predate neighbor moves: recompute before trusting it
    int64_t fresh_gain;
    int32_t fresh_p = best_move(u, fresh_gain);
    if (fresh_p == -1) continue;
    if (fresh_gain != gain || fresh_p != p) {
      last_gain[u] = fresh_gain;
      last_p[u] = fresh_p;
      heap.emplace(fresh_gain, u, fresh_p, ver[u]);
      continue;
    }
    int32_t pu = parts[u];
    psize[pu] -= nw(g, u);
    psize[p] += nw(g, u);
    parts[u] = p;
    locked[u] = 1;
    moves.emplace_back(u, pu);
    cum += fresh_gain;
    if (cum > best_cum) {
      best_cum = cum;
      best_len = moves.size();
      drift = 0;
    } else {
      ++drift;
    }
    for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
      int32_t v = g.indices[e];
      if (locked[v]) continue;
      ++ver[v];
      if (eager) {
        // exact gains keep the hill-climb chains honest — measurably
        // better on mesh-like graphs, O(deg) per neighbor
        int64_t vg;
        int32_t vp = best_move(v, vg);
        if (vp != -1) {
          last_gain[v] = vg;
          last_p[v] = vp;
          heap.emplace(vg, v, vp, ver[v]);
        }
        continue;
      }
      // stale cached gain; corrected lazily on pop. A node never seen
      // on the boundary enters with its neighbor-count as an optimistic
      // upper bound so it gets examined once.
      int64_t vg = last_gain[v] != INT64_MIN
                       ? last_gain[v]
                       : g.indptr[v + 1] - g.indptr[v];
      int32_t vp = last_p[v] != -1 ? last_p[v] : parts[u];
      heap.emplace(vg, v, vp, ver[v]);
    }
  }

  // roll back everything after the best prefix
  for (size_t i = moves.size(); i > best_len; --i) {
    auto [u, from] = moves[i - 1];
    psize[parts[u]] -= nw(g, u);
    psize[from] += nw(g, u);
    parts[u] = from;
  }
  return best_cum > 0;
}

void fm_refine(const CsrView& g, int32_t k, int objective, double imbalance,
               std::vector<int32_t>& parts, int max_passes = 8) {
  // Cost/quality ladder by level size: exact (eager) neighbor gains on
  // small graphs, lazy cached gains in the mid range, and no FM at all
  // on billion-edge levels — there the greedy passes carry refinement
  // and the quality-critical decisions were already made on the
  // coarser levels (where FM did run).
  const int64_t m = g.m();
  const int64_t eager_edge_cap = 1'000'000;
  const int64_t fm_edge_cap = 200'000'000;
  if (m > fm_edge_cap) return;
  // eager neighbor updates cost O(deg^2) per move — only worth it on
  // sparse mesh-like graphs, where exact gains measurably improve the
  // hill-climb (grid probe: 1.07x vs 1.72x of the optimal bisection)
  const bool eager = m <= eager_edge_cap && m <= 16 * g.n;
  const int64_t cap = balance_cap(g, k, imbalance);
  std::vector<int64_t> psize(k, 0);
  for (int64_t u = 0; u < g.n; ++u) psize[parts[u]] += nw(g, u);
  for (int pass = 0; pass < max_passes; ++pass)
    if (!fm_pass(g, k, objective, cap, psize, parts, eager)) break;
}

void ensure_nonempty(const CsrView& g, int32_t k, std::vector<int32_t>& parts) {
  std::vector<int64_t> count(k, 0);
  for (int64_t u = 0; u < g.n; ++u) count[parts[u]]++;
  for (int32_t p = 0; p < k; ++p) {
    if (count[p] > 0) continue;
    int32_t donor =
        (int32_t)(std::max_element(count.begin(), count.end()) - count.begin());
    for (int64_t u = 0; u < g.n; ++u) {
      if (parts[u] == donor) {
        parts[u] = p;
        count[donor]--;
        count[p]++;
        break;
      }
    }
  }
}

}  // namespace

extern "C" {

// Partition a symmetric CSR graph (no self loops required; they are
// ignored) into n_parts. Writes int32 partition ids to out_parts[n].
// Returns 0 on success.
int pgt_partition(int64_t n, const int64_t* indptr, const int32_t* indices,
                  int32_t n_parts, int objective, uint64_t seed,
                  double imbalance, int refine_iters, int32_t* out_parts) {
  if (n <= 0 || n_parts <= 0) return 1;
  if (n_parts == 1) {
    std::memset(out_parts, 0, sizeof(int32_t) * (size_t)n);
    return 0;
  }
  std::mt19937_64 rng(seed);

  // the FINEST level is a zero-copy view of the caller's arrays with
  // implicit unit weights — at papers100M scale the old copy +
  // materialized all-ones int64 weights cost ~40 GB by themselves.
  const CsrView fine_view{n, indptr, indices, nullptr, nullptr};

  // The hierarchy is NOT kept in RAM wholesale: on low-locality graphs
  // coarse edge counts barely shrink for many levels (~2.6 GB/level at
  // 1/10-papers scale, 30+ GB total — the measured round-4 peak).
  // Instead, only levels at or below SPILL_EDGES are stored; a larger
  // level keeps just its composed level0->level map (n int32) and is
  // REBUILT by contract(level0, composed map) when uncoarsening
  // reaches it — exact reconstruction, O(E0) per rebuilt level.
  const int64_t SPILL_EDGES = 50'000'000;
  struct LevelInfo {
    std::vector<int32_t> map;   // level i-1 node -> level i node
    Csr graph;                  // owned iff stored
    bool stored = false;
    std::vector<int32_t> cmap;  // level 0 -> level i (iff !stored)
    int64_t n = 0;
  };
  std::vector<LevelInfo> levels;  // levels[i] describes level i+1

  const int64_t target = std::max<int64_t>((int64_t)n_parts * 16, 512);
  const bool verbose = std::getenv("PIPEGCN_PART_VERBOSE") != nullptr;
  // `current` holds the working graph ONLY while levels are unstored;
  // once a level fits SPILL_EDGES its graph moves into the hierarchy
  // (coarse edge counts are non-increasing, so every deeper level is
  // stored too and the level0->level composition can stop)
  Csr current;
  std::vector<int32_t> cur_cmap;
  while ((levels.empty() ? n : levels.back().n) > target) {
    const CsrView gv =
        levels.empty() ? fine_view
        : (levels.back().stored ? levels.back().graph.view()
                                : current.view());
    std::vector<int32_t> map;
    Csr c = coarsen(gv, rng, map);
    if (c.n > (int64_t)(0.95 * (double)gv.n)) break;  // stalled
    LevelInfo li;
    li.n = c.n;
    li.stored = c.indptr[c.n] <= SPILL_EDGES;
    if (!li.stored) {
      if (levels.empty()) {
        cur_cmap = map;
      } else {
        for (int64_t u = 0; u < n; ++u) cur_cmap[u] = map[cur_cmap[u]];
      }
      li.cmap = cur_cmap;
    } else {
      std::vector<int32_t>().swap(cur_cmap);  // composition is done
    }
    li.map = std::move(map);
    if (verbose)
      std::fprintf(stderr,
                   "# level %zu: n=%lld m=%lld (%.2f GB, %s)\n",
                   levels.size() + 1, (long long)c.n,
                   (long long)c.indptr[c.n],
                   (double)(c.indptr[c.n] * 8 + c.n * 16) / 1e9,
                   li.stored ? "stored" : "rebuilt on demand");
    if (li.stored) {
      li.graph = std::move(c);
      current = Csr();
      levels.push_back(std::move(li));
    } else {
      levels.push_back(std::move(li));
      current = std::move(c);  // frees the previous working graph
    }
  }

  // initial partition at the coarsest level: the coarse graph is tiny,
  // so run several independent BFS-seeded attempts (METIS-style
  // multi-start) and keep the best refined one by the true objective
  std::vector<int32_t> parts;
  {
    const CsrView coarsest =
        levels.empty() ? fine_view
        : (levels.back().stored ? levels.back().graph.view()
                                : current.view());
    // multi-start assumes a TINY coarsest graph; when coarsening
    // stalls early (low-locality graphs), each try still sweeps the
    // full edge set through refine — scale the tries down with size
    // so initial partitioning stays a minor phase
    const int64_t cm = coarsest.m();
    const int tries = cm > 1'000'000'000 ? 2
                      : cm > 100'000'000 ? 4 : 8;
    int64_t best_obj = INT64_MAX;
    std::vector<int32_t> cand;
    for (int t = 0; t < tries; ++t) {
      initial_partition(coarsest, n_parts, rng, cand);
      refine(coarsest, n_parts, objective, refine_iters, imbalance,
             cand, rng);
      fm_refine(coarsest, n_parts, objective, imbalance, cand);
      int64_t obj = eval_objective(coarsest, n_parts, objective, cand);
      if (obj < best_obj) {
        best_obj = obj;
        parts = cand;
      }
    }
  }
  current = Csr();  // coarsest graph is done; free before uncoarsening

  // uncoarsen with refinement at every level: greedy positive-gain
  // passes first (cheap, bulk moves), then FM hill-climbing to escape
  // the greedy local minimum. `j` is the level being refined; its
  // graph is the fine view (j==0), the stored copy, or an on-demand
  // exact rebuild — at most ONE big level is live at any moment.
  for (int64_t j = (int64_t)levels.size() - 1; j >= 0; --j) {
    {
      const std::vector<int32_t>& map = levels[j].map;
      std::vector<int32_t> fine((int64_t)map.size());
      for (int64_t u = 0; u < (int64_t)map.size(); ++u)
        fine[u] = parts[map[u]];
      parts = std::move(fine);
    }
    // everything describing level j+1 is consumed: free the
    // projection map (and its graph below) before refining the
    // bigger, finer level
    std::vector<int32_t>().swap(levels[j].map);
    Csr rebuilt;
    CsrView gv;
    if (j == 0) {
      gv = fine_view;
    } else if (levels[j - 1].stored) {
      gv = levels[j - 1].graph.view();
    } else {
      rebuilt = contract(fine_view, levels[j - 1].cmap.data(),
                         levels[j - 1].n);
      std::vector<int32_t>().swap(levels[j - 1].cmap);
      gv = rebuilt.view();
    }
    refine(gv, n_parts, objective, refine_iters, imbalance, parts, rng);
    fm_refine(gv, n_parts, objective, imbalance, parts);
    if (j > 0) levels[j - 1].graph = Csr();  // consumed
  }

  ensure_nonempty(fine_view, n_parts, parts);
  std::memcpy(out_parts, parts.data(), sizeof(int32_t) * (size_t)n);
  return 0;
}

}  // extern "C"
