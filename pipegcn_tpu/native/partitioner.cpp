// Native multilevel k-way graph partitioner.
//
// TPU-native replacement for the METIS C library the reference reaches
// through its customized DGL fork (reference helper/utils.py:132-144,
// README.md:62 — the fork exists only to pass objtype='vol'|'cut' through
// to METIS). Same role, same objective surface:
//
//   objective = 0 ('cut')  minimize edges crossing partitions
//   objective = 1 ('vol')  minimize communication volume: distinct
//                          (node, foreign-partition) halo pairs — the
//                          quantity PipeGCN-style training exchanges
//                          every layer.
//
// Classic multilevel scheme (Karypis & Kumar style, independent
// implementation):
//   1. coarsen by randomized heavy-edge matching, accumulating edge and
//      node weights, until the graph is small;
//   2. initial k-way partition on the coarsest graph: BFS-grown
//      contiguous blocks balanced by node weight;
//   3. uncoarsen, at every level running boundary FM-style refinement:
//      greedy positive-gain moves under a node-weight balance cap, with
//      the gain formula matching the requested objective.
//
// Deterministic for a fixed seed. Single-threaded C++17, no deps.
//
// C API (ctypes-friendly): pgt_partition() at the bottom.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <random>
#include <vector>

namespace {

struct Csr {
  int64_t n = 0;
  std::vector<int64_t> indptr;   // [n+1]
  std::vector<int32_t> indices;  // [m] neighbor ids
  std::vector<int64_t> ewgt;     // [m] edge weights
  std::vector<int64_t> nwgt;     // [n] node weights
};

// ---------------------------------------------------------------------
// Coarsening: randomized heavy-edge matching.

// Returns coarse graph + mapping fine node -> coarse node.
Csr coarsen(const Csr& g, std::mt19937_64& rng, std::vector<int32_t>& map) {
  const int64_t n = g.n;
  map.assign(n, -1);
  std::vector<int32_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::shuffle(order.begin(), order.end(), rng);

  // heavy-edge matching: visit nodes in random order, match each
  // unmatched node with its unmatched neighbor of max edge weight
  int32_t nc = 0;
  std::vector<int32_t> match(n, -1);
  for (int64_t i = 0; i < n; ++i) {
    int32_t u = order[i];
    if (match[u] != -1) continue;
    int32_t best = -1;
    int64_t best_w = -1;
    for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
      int32_t v = g.indices[e];
      if (v == u || match[v] != -1) continue;
      if (g.ewgt[e] > best_w) { best_w = g.ewgt[e]; best = v; }
    }
    match[u] = (best == -1) ? u : best;
    if (best != -1) match[best] = u;
    map[u] = nc;
    if (best != -1) map[best] = nc;
    ++nc;
  }

  // build coarse graph: aggregate parallel edges, drop self loops
  Csr c;
  c.n = nc;
  c.nwgt.assign(nc, 0);
  for (int64_t u = 0; u < n; ++u) c.nwgt[map[u]] += g.nwgt[u];

  // count then fill, merging duplicates with a per-node scratch table
  std::vector<int64_t> scratch_w(nc, 0);
  std::vector<int32_t> scratch_nbr;
  scratch_nbr.reserve(256);

  // two passes over fine edges grouped by coarse node; build fine-node
  // lists per coarse node first
  std::vector<int64_t> cstart(nc + 1, 0);
  for (int64_t u = 0; u < n; ++u) cstart[map[u] + 1]++;
  for (int32_t i = 0; i < nc; ++i) cstart[i + 1] += cstart[i];
  std::vector<int32_t> members(n);
  {
    std::vector<int64_t> cur(cstart.begin(), cstart.end() - 1);
    for (int64_t u = 0; u < n; ++u) members[cur[map[u]]++] = (int32_t)u;
  }

  c.indptr.assign(nc + 1, 0);
  // pass 1: count distinct coarse neighbors
  for (int32_t cu = 0; cu < nc; ++cu) {
    scratch_nbr.clear();
    for (int64_t mi = cstart[cu]; mi < cstart[cu + 1]; ++mi) {
      int32_t u = members[mi];
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t cv = map[g.indices[e]];
        if (cv == cu) continue;
        if (scratch_w[cv] == 0) scratch_nbr.push_back(cv);
        scratch_w[cv] += g.ewgt[e];
      }
    }
    c.indptr[cu + 1] = c.indptr[cu] + (int64_t)scratch_nbr.size();
    for (int32_t cv : scratch_nbr) scratch_w[cv] = 0;
  }
  c.indices.resize(c.indptr[nc]);
  c.ewgt.resize(c.indptr[nc]);
  // pass 2: fill
  for (int32_t cu = 0; cu < nc; ++cu) {
    scratch_nbr.clear();
    for (int64_t mi = cstart[cu]; mi < cstart[cu + 1]; ++mi) {
      int32_t u = members[mi];
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t cv = map[g.indices[e]];
        if (cv == cu) continue;
        if (scratch_w[cv] == 0) scratch_nbr.push_back(cv);
        scratch_w[cv] += g.ewgt[e];
      }
    }
    int64_t pos = c.indptr[cu];
    for (int32_t cv : scratch_nbr) {
      c.indices[pos] = cv;
      c.ewgt[pos] = scratch_w[cv];
      scratch_w[cv] = 0;
      ++pos;
    }
  }
  return c;
}

// ---------------------------------------------------------------------
// Initial partition on the coarsest graph: BFS order, contiguous blocks
// balanced by node weight.

void initial_partition(const Csr& g, int32_t k, std::mt19937_64& rng,
                       std::vector<int32_t>& parts) {
  const int64_t n = g.n;
  parts.assign(n, 0);
  std::vector<int32_t> order;
  order.reserve(n);
  std::vector<char> visited(n, 0);
  std::vector<int32_t> restart(n);
  std::iota(restart.begin(), restart.end(), 0);
  std::shuffle(restart.begin(), restart.end(), rng);
  int64_t cursor = 0;
  std::vector<int32_t> queue;
  while ((int64_t)order.size() < n) {
    while (cursor < n && visited[restart[cursor]]) ++cursor;
    int32_t s = restart[cursor];
    visited[s] = 1;
    queue.assign(1, s);
    size_t qh = 0;
    order.push_back(s);
    while (qh < queue.size()) {
      int32_t u = queue[qh++];
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t v = g.indices[e];
        if (!visited[v]) {
          visited[v] = 1;
          queue.push_back(v);
          order.push_back(v);
        }
      }
    }
  }
  int64_t total_w = 0;
  for (int64_t u = 0; u < n; ++u) total_w += g.nwgt[u];
  // walk the BFS order filling part 0, then 1, ... by weight quota
  int64_t acc = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t p = (int32_t)std::min<int64_t>((acc * k) / std::max<int64_t>(total_w, 1),
                                           k - 1);
    parts[order[i]] = p;
    acc += g.nwgt[order[i]];
  }
}

// ---------------------------------------------------------------------
// Refinement: FM-style greedy boundary passes.
//
// For 'cut', gain(u, p) = w(u->p) - w(u->own).
// For 'vol', add the change in distinct halo pairs: moving u to p removes
// the (u, p) pair, creates a (u, own) pair if u keeps neighbors there —
// approximated (as in the Python refiner) with indicator terms
// [w(u->p) > 0] - [w(u->own) > 0]; neighbor-side pair changes are second
// order and ignored.

void refine(const Csr& g, int32_t k, int objective, int iters,
            double imbalance, std::vector<int32_t>& parts,
            std::mt19937_64& rng) {
  const int64_t n = g.n;
  int64_t total_w = 0;
  for (int64_t u = 0; u < n; ++u) total_w += g.nwgt[u];
  const int64_t cap =
      (int64_t)(imbalance * (double)((total_w + k - 1) / k)) + 1;

  std::vector<int64_t> psize(k, 0);
  for (int64_t u = 0; u < n; ++u) psize[parts[u]] += g.nwgt[u];

  std::vector<int64_t> conn(k, 0);  // edge weight to each part, per node
  std::vector<int32_t> touched;
  touched.reserve(64);
  std::vector<int32_t> order(n);
  std::iota(order.begin(), order.end(), 0);

  for (int it = 0; it < iters; ++it) {
    std::shuffle(order.begin(), order.end(), rng);
    int64_t moved = 0;
    for (int64_t i = 0; i < n; ++i) {
      int32_t u = order[i];
      int32_t pu = parts[u];
      if (psize[pu] - g.nwgt[u] <= 0) continue;  // never drain a part
      touched.clear();
      bool boundary = false;
      for (int64_t e = g.indptr[u]; e < g.indptr[u + 1]; ++e) {
        int32_t pv = parts[g.indices[e]];
        if (conn[pv] == 0) touched.push_back(pv);
        conn[pv] += g.ewgt[e];
        if (pv != pu) boundary = true;
      }
      if (boundary) {
        int64_t own = conn[pu];
        int64_t best_gain = 0;
        int32_t best_p = -1;
        for (int32_t p : touched) {
          if (p == pu || psize[p] + g.nwgt[u] > cap) continue;
          int64_t gain = conn[p] - own;
          if (objective == 1)
            gain += (conn[p] > 0 ? 1 : 0) - (own > 0 ? 1 : 0);
          if (gain > best_gain ||
              (gain == best_gain && best_p != -1 && psize[p] < psize[best_p])) {
            best_gain = gain;
            best_p = p;
          }
        }
        if (best_p != -1 && best_gain > 0) {
          psize[pu] -= g.nwgt[u];
          psize[best_p] += g.nwgt[u];
          parts[u] = best_p;
          ++moved;
        }
      }
      for (int32_t p : touched) conn[p] = 0;
    }
    if (moved == 0) break;
  }
}

void ensure_nonempty(const Csr& g, int32_t k, std::vector<int32_t>& parts) {
  std::vector<int64_t> count(k, 0);
  for (int64_t u = 0; u < g.n; ++u) count[parts[u]]++;
  for (int32_t p = 0; p < k; ++p) {
    if (count[p] > 0) continue;
    int32_t donor =
        (int32_t)(std::max_element(count.begin(), count.end()) - count.begin());
    for (int64_t u = 0; u < g.n; ++u) {
      if (parts[u] == donor) {
        parts[u] = p;
        count[donor]--;
        count[p]++;
        break;
      }
    }
  }
}

}  // namespace

extern "C" {

// Partition a symmetric CSR graph (no self loops required; they are
// ignored) into n_parts. Writes int32 partition ids to out_parts[n].
// Returns 0 on success.
int pgt_partition(int64_t n, const int64_t* indptr, const int32_t* indices,
                  int32_t n_parts, int objective, uint64_t seed,
                  double imbalance, int refine_iters, int32_t* out_parts) {
  if (n <= 0 || n_parts <= 0) return 1;
  if (n_parts == 1) {
    std::memset(out_parts, 0, sizeof(int32_t) * (size_t)n);
    return 0;
  }
  std::mt19937_64 rng(seed);

  // levels[i] may be relocated by push_back — never hold references into it
  std::vector<Csr> levels(1);
  levels[0].n = n;
  levels[0].indptr.assign(indptr, indptr + n + 1);
  levels[0].indices.assign(indices, indices + indptr[n]);
  levels[0].ewgt.assign(indptr[n], 1);
  levels[0].nwgt.assign(n, 1);

  // coarsen until small or stalled
  std::vector<std::vector<int32_t>> maps;
  const int64_t target = std::max<int64_t>((int64_t)n_parts * 32, 2048);
  while (levels.back().n > target) {
    std::vector<int32_t> map;
    Csr c = coarsen(levels.back(), rng, map);
    if (c.n > (int64_t)(0.95 * (double)levels.back().n)) break;  // stalled
    maps.push_back(std::move(map));
    levels.push_back(std::move(c));
  }

  // initial partition at the coarsest level
  std::vector<int32_t> parts;
  initial_partition(levels.back(), n_parts, rng, parts);
  refine(levels.back(), n_parts, objective, refine_iters, imbalance, parts,
         rng);

  // uncoarsen with refinement at every level
  for (int64_t lvl = (int64_t)maps.size() - 1; lvl >= 0; --lvl) {
    const std::vector<int32_t>& map = maps[lvl];
    std::vector<int32_t> fine(levels[lvl].n);
    for (int64_t u = 0; u < levels[lvl].n; ++u) fine[u] = parts[map[u]];
    parts = std::move(fine);
    refine(levels[lvl], n_parts, objective, refine_iters, imbalance, parts,
           rng);
  }

  ensure_nonempty(levels[0], n_parts, parts);
  std::memcpy(out_parts, parts.data(), sizeof(int32_t) * (size_t)n);
  return 0;
}

}  // extern "C"
