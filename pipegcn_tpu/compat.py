"""Shims for running the newer-jax API surface on older jax releases.

The SPMD layer is written against the current public API
(`jax.shard_map` with `check_vma`, `jax.lax.pcast` for varying-type
marks). Older releases (<= 0.4.x) ship `shard_map` under
`jax.experimental.shard_map` with the `check_rep` spelling and have no
varying-manifest-axes system at all. `ensure_jax_compat()` installs
aliases so the same code runs on both:

  jax.shard_map     -> experimental shard_map; check_vma maps onto
                       check_rep (both gate the same replication check)
  jax.lax.pcast     -> identity (no vma system: every value is already
                       acceptable everywhere, so the mark is a no-op;
                       halo._ensure_varying's jax.typeof probe already
                       degrades gracefully)

Idempotent and a no-op on releases that already expose the API.
Applied by pipegcn_tpu.parallel at import, before any shard_map use.
"""

from __future__ import annotations


def shape_dtype_struct(shape, dtype, vma=None):
    """jax.ShapeDtypeStruct carrying the varying-mesh-axes declaration
    when the release supports it (newer jax, inside shard_map with
    check_vma); older releases have no vma system — their check_rep
    path never inspects output vma — so the kwarg is simply dropped."""
    import jax

    if vma is not None:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def ensure_jax_compat() -> None:
    import jax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kw):
            if check_vma is not None and "check_rep" not in kw:
                kw["check_rep"] = bool(check_vma)
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axis_name, to=None):
            del axis_name, to
            return x

        jax.lax.pcast = pcast
