"""Health rollup, SLO alert rules, and the /metrics exporter
(docs/OBSERVABILITY.md "Live monitoring").

Consumes a :class:`~.live.LiveAggregator`'s rolling state three ways:

  AlertEngine       declarative SLO rules (``--alert-rules`` JSON or
                    the built-in defaults), evaluated each monitor
                    tick. Edge-triggered and deduped: a rule instance
                    (rule, source) writes exactly one contracted
                    ``alert`` record per fire edge and one per resolve
                    edge, no matter how many ticks it stays red.
  prometheus_text   the /metrics payload — Prometheus text exposition
                    rendered straight from aggregator state, stdlib
                    only.
  MonitorServer     ``--serve-http``: a ThreadingHTTPServer with
                    /metrics (Prometheus text) and /health (JSON
                    rollup). Binds port 0 for an ephemeral port in
                    tests.

The engine's clock is injectable (fake-clock alert tests); rule
evaluation never raises on missing data — a rule without its inputs
simply does not fire.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from .live import LiveAggregator

# rule id -> parameter defaults; a rules file entry must name one of
# these and may override any default (plus "severity")
RULE_DEFAULTS: Dict[str, Dict[str, Any]] = {
    # latest epoch step time vs the rolling median of the window
    # before it: fires when latest > factor * median
    "epoch-time-regression": {"factor": 1.5, "min_points": 5,
                              "window": 16, "severity": "warn"},
    # shed rows / (served + shed) in the latest serving window
    "shed-rate": {"threshold": 0.1, "severity": "warn"},
    # staleness_age of the latest epoch or serving record
    "staleness-age": {"threshold": 8, "severity": "warn"},
    # >= threshold fault records (optionally of one kind) within the
    # trailing horizon; resolves once the horizon passes quietly
    "fault-rate": {"threshold": 1, "horizon_s": 60.0, "kind": None,
                   "severity": "page"},
    # a known source produced nothing for horizon_s (covers the
    # missing-heartbeat case: heartbeat records stop arriving)
    "silent-source": {"horizon_s": 30.0, "severity": "page"},
    # training-span straggler attribution (obs/trainspan.py): the same
    # rank arrived last at the dispatch boundary for the last `sustain`
    # attributed epochs, each time by more than factor * the rolling
    # median epoch time — a persistently slow rank, not a one-off blip
    "straggler-skew": {"factor": 0.5, "sustain": 3, "severity": "warn"},
}

DEFAULT_RULES: List[Dict[str, Any]] = [
    {"rule": "epoch-time-regression"},
    {"rule": "shed-rate"},
    {"rule": "staleness-age"},
    {"rule": "fault-rate"},
    {"rule": "silent-source"},
    {"rule": "straggler-skew"},
]


def load_rules(path: Optional[str]) -> List[Dict[str, Any]]:
    """Rules from a JSON file (a list of ``{"rule": id, ...overrides}``
    entries), or the defaults. Unknown rule ids and parameters fail
    loudly — a typo'd rules file must not silently monitor nothing."""
    if path is None:
        entries = [dict(e) for e in DEFAULT_RULES]
    else:
        with open(path, encoding="utf-8") as f:
            entries = json.load(f)
        if not isinstance(entries, list):
            raise ValueError(f"{path}: expected a JSON list of rules")
    out = []
    for e in entries:
        rid = e.get("rule")
        if rid not in RULE_DEFAULTS:
            raise ValueError(
                f"unknown alert rule {rid!r} (known: "
                f"{sorted(RULE_DEFAULTS)})")
        cfg = dict(RULE_DEFAULTS[rid])
        for k, v in e.items():
            if k != "rule" and k not in cfg:
                raise ValueError(f"rule {rid!r}: unknown parameter {k!r}")
            cfg[k] = v
        cfg["rule"] = rid
        out.append(cfg)
    return out


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class AlertEngine:
    """Edge-triggered, deduped SLO evaluation over aggregator state.

    ``evaluate(agg)`` computes every rule instance's predicate and
    emits one ``alert`` record per EDGE: rising -> state "fire",
    falling -> state "resolve" (through `ml.alert`, hard-flushed, when
    a sink is given; always appended to `self.events`). A rule that
    stays red across N ticks emits nothing after its fire edge — the
    dedup the schema promises."""

    def __init__(self, rules: Optional[List[Dict[str, Any]]] = None,
                 ml=None, clock: Callable[[], float] = time.time):
        self.rules = rules if rules is not None else load_rules(None)
        self.ml = ml
        self._clock = clock
        # (rule, source) -> the fire observation (value/threshold)
        self._firing: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # fault-rate bookkeeping: (rule idx) -> deque of (t, n_new)
        self._fault_hist: Dict[int, collections.deque] = {}
        self._fault_seen: Dict[int, int] = {}
        self.events: List[Dict[str, Any]] = []
        self.n_fired = 0
        self.n_resolved = 0

    # ---------------- predicates --------------------------------------

    def _observations(self, idx: int, cfg: Dict[str, Any],
                      agg: LiveAggregator):
        """Yield (source, red?, value, threshold, message) for every
        live instance of one rule."""
        rid = cfg["rule"]
        if rid == "epoch-time-regression":
            for src, hist in agg.epoch_times.items():
                if len(hist) < max(int(cfg["min_points"]), 2):
                    continue
                base = hist[-int(cfg["window"]) - 1:-1]
                med = _median(base)
                thr = float(cfg["factor"]) * med
                latest = hist[-1]
                yield (src, med > 0 and latest > thr, latest, thr,
                       f"epoch time {latest:.3f}s vs rolling median "
                       f"{med:.3f}s")
        elif rid == "shed-rate":
            for src, rec in agg.latest("serving").items():
                served = rec.get("queries") or 0
                shed = rec.get("shed") or 0
                total = served + shed
                if total <= 0:
                    yield (src, False, None, float(cfg["threshold"]),
                           "no traffic")
                    continue
                rate = shed / total
                yield (src, rate > float(cfg["threshold"]), rate,
                       float(cfg["threshold"]),
                       f"shed {shed}/{total} rows this window")
        elif rid == "staleness-age":
            latest = dict(agg.latest("epoch"))
            latest.update(agg.latest("serving"))
            for src, rec in latest.items():
                age = rec.get("staleness_age")
                if not isinstance(age, int):
                    continue
                yield (src, age > int(cfg["threshold"]), float(age),
                       float(cfg["threshold"]),
                       f"staleness age {age}")
        elif rid == "fault-rate":
            kind = cfg.get("kind")
            total = (agg.fault_counts.get(kind, 0) if kind
                     else sum(agg.fault_counts.values()))
            hist = self._fault_hist.setdefault(
                idx, collections.deque())
            seen = self._fault_seen.get(idx, 0)
            now = self._clock()
            if total > seen:
                hist.append((now, total - seen))
            self._fault_seen[idx] = total
            horizon = float(cfg["horizon_s"])
            while hist and now - hist[0][0] > horizon:
                hist.popleft()
            recent = sum(n for _, n in hist)
            yield ("*", recent >= int(cfg["threshold"]), float(recent),
                   float(cfg["threshold"]),
                   f"{recent} fault(s) in the last {horizon:.0f}s"
                   + (f" (kind {kind})" if kind else ""))
        elif rid == "silent-source":
            horizon = float(cfg["horizon_s"])
            for src in agg.sources():
                age = agg.silent_for(src)
                yield (src, age > horizon, age, horizon,
                       f"no records for {age:.1f}s")
        elif rid == "straggler-skew":
            ts = (agg.trainspan()
                  if hasattr(agg, "trainspan") else None)
            per_epoch = (ts or {}).get("per_epoch") or {}
            attributed = [(e, pe) for e, pe in sorted(per_epoch.items())
                          if pe.get("straggler_rank") is not None]
            if not attributed:
                return
            times = [t for h in agg.epoch_times.values() for t in h]
            med = _median(times) if times else 0.0
            thr = float(cfg["factor"]) * med
            sustain = max(int(cfg["sustain"]), 1)
            recent = attributed[-sustain:]
            recent_ranks = {pe["straggler_rank"] for _, pe in recent}
            # every ever-attributed rank gets an observation so a fired
            # instance can RESOLVE once the skew stops
            for r in sorted({pe["straggler_rank"]
                             for _, pe in attributed}):
                red = (med > 0 and len(recent) >= sustain
                       and recent_ranks == {r}
                       and min(pe.get("gap_s", 0.0)
                               for _, pe in recent) > thr)
                gap = max((pe.get("gap_s", 0.0) for _, pe in recent
                           if pe["straggler_rank"] == r), default=0.0)
                yield (f"r{r}", red, gap, thr,
                       f"rank {r} arrived {gap * 1e3:.0f} ms behind "
                       f"the median boundary (median epoch "
                       f"{med:.3f}s, sustain {len(recent)})")

    # ---------------- edges -------------------------------------------

    def _emit(self, rid: str, state: str, severity: str, source: str,
              value, threshold, message: str) -> Dict[str, Any]:
        rec = {"event": "alert", "rule": rid, "state": state,
               "severity": severity, "source": source,
               "value": None if value is None else float(value),
               "threshold": (None if threshold is None
                             else float(threshold)),
               "message": message, "time_unix": self._clock()}
        if self.ml is not None:
            self.ml.alert(rid, state, severity, source, value,
                          threshold, message, time_unix=rec["time_unix"])
        self.events.append(rec)
        return rec

    def evaluate(self, agg: LiveAggregator) -> List[Dict[str, Any]]:
        """One tick: returns the alert records EMITTED this tick (the
        edges only — an empty list on a steady-state tick)."""
        emitted = []
        for idx, cfg in enumerate(self.rules):
            rid = cfg["rule"]
            severity = str(cfg.get("severity", "warn"))
            for src, red, value, thr, msg in self._observations(
                    idx, cfg, agg):
                key = (f"{rid}#{idx}", src)
                was = key in self._firing
                if red and not was:
                    self._firing[key] = {"value": value,
                                         "threshold": thr}
                    self.n_fired += 1
                    emitted.append(self._emit(
                        rid, "fire", severity, src, value, thr, msg))
                elif not red and was:
                    del self._firing[key]
                    self.n_resolved += 1
                    emitted.append(self._emit(
                        rid, "resolve", severity, src, value, thr,
                        f"resolved: {msg}"))
        return emitted

    def firing(self) -> List[Dict[str, str]]:
        """Currently-red instances, for /health and /metrics."""
        return [{"rule": rk.split("#", 1)[0], "source": src}
                for (rk, src) in sorted(self._firing)]


# ---------------------------------------------------------------------------
# /metrics + /health rendering
# ---------------------------------------------------------------------------


def _esc(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _num(v: Any) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def prometheus_text(agg: LiveAggregator,
                    engine: Optional[AlertEngine] = None,
                    sink_stats: Optional[Dict[str, Any]] = None) -> str:
    """The /metrics payload: aggregator state as Prometheus text
    exposition (stdlib string building; no client library)."""
    lines: List[str] = []

    def gauge(name: str, value, labels: Optional[Dict] = None,
              mtype: str = "gauge"):
        v = _num(value)
        if v is None:
            return
        if not any(line.startswith(f"# TYPE {name} ") for line in lines):
            lines.append(f"# TYPE {name} {mtype}")
        lab = ""
        if labels:
            lab = "{" + ",".join(
                f'{k}="{_esc(x)}"' for k, x in sorted(labels.items())) \
                + "}"
        if v == int(v) and abs(v) < 1e15:
            lines.append(f"{name}{lab} {int(v)}")
        else:
            lines.append(f"{name}{lab} {v}")

    gauge("pipegcn_up", 1)
    gauge("pipegcn_schema_version", agg.schema_version)
    gauge("pipegcn_streams", len(agg.readers))
    gauge("pipegcn_records_total", agg.n_records, mtype="counter")
    gauge("pipegcn_invalid_records_total", agg.n_invalid,
          mtype="counter")
    gauge("pipegcn_malformed_lines_total",
          sum(r.n_malformed for r in agg.readers.values()),
          mtype="counter")
    for src in agg.sources():
        gauge("pipegcn_source_last_seen_age_seconds",
              agg.silent_for(src), {"source": src})
    for src, rec in sorted(agg.latest("epoch").items()):
        lab = {"source": src}
        gauge("pipegcn_epoch", rec.get("epoch"), lab)
        gauge("pipegcn_epoch_time_seconds", rec.get("step_time_s"), lab)
        gauge("pipegcn_loss", rec.get("loss"), lab)
        gauge("pipegcn_grad_norm", rec.get("grad_norm"), lab)
        gauge("pipegcn_halo_bytes", rec.get("halo_bytes"), lab)
        unc = _num(rec.get("halo_bytes_uncompressed"))
        hb = _num(rec.get("halo_bytes"))
        if unc and hb:
            gauge("pipegcn_halo_compression_ratio", unc / hb, lab)
        gauge("pipegcn_staleness_age", rec.get("staleness_age"), lab)
    for src, rec in sorted(agg.latest("serving").items()):
        lab = {"source": src}
        gauge("pipegcn_serving_qps", rec.get("qps"), lab)
        gauge("pipegcn_serving_p50_ms", rec.get("p50_ms"), lab)
        gauge("pipegcn_serving_p95_ms", rec.get("p95_ms"), lab)
        gauge("pipegcn_serving_p99_ms", rec.get("p99_ms"), lab)
        gauge("pipegcn_serving_queue_depth", rec.get("queue_depth"), lab)
        gauge("pipegcn_serving_shed", rec.get("shed"), lab)
        gauge("pipegcn_serving_staleness_age",
              rec.get("staleness_age"), lab)
        gauge("pipegcn_param_generation",
              rec.get("param_generation"), lab)
        gauge("pipegcn_param_staleness", rec.get("param_staleness"), lab)
        gauge("pipegcn_topo_generation", rec.get("topo_generation"), lab)
        # fleet-path extras (run_fleet_loop): replica count + per-
        # replica in-flight queue depth + degradation rung, so a
        # /metrics scrape shows the autoscale control loop acting
        gauge("pipegcn_replica_count", rec.get("replicas_up"), lab)
        gauge("pipegcn_degradation_rung", rec.get("rung"), lab)
        rqd = rec.get("replica_queue_depth")
        if isinstance(rqd, dict):
            for rep, depth in sorted(rqd.items()):
                gauge("pipegcn_replica_queue_depth", depth,
                      {"source": src, "replica": str(rep)})
    for action, n in sorted(getattr(agg, "autoscale_counts",
                                    {}).items()):
        gauge("pipegcn_autoscale_decisions_total", n,
              {"direction": action}, mtype="counter")
    for reason, rows in sorted(agg.shed_by_reason.items()):
        gauge("pipegcn_serving_shed_rows_total", rows,
              {"reason": reason}, mtype="counter")
    for kind, n in sorted(agg.fault_counts.items()):
        gauge("pipegcn_faults_total", n, {"kind": kind},
              mtype="counter")
    for kind, n in sorted(agg.recovery_counts.items()):
        gauge("pipegcn_recoveries_total", n, {"kind": kind},
              mtype="counter")
    for outcome, n in sorted(getattr(agg, "integrity_counts",
                                     {}).items()):
        gauge("pipegcn_integrity_checks_total", n,
              {"outcome": outcome}, mtype="counter")
    # a GAUGE: rises on quarantine-request, falls when a later
    # membership assignment seats the member again (operator rejoin)
    gauge("pipegcn_quarantined_ranks",
          len(getattr(agg, "quarantined", ())))
    gauge("pipegcn_io_degraded",
          int(agg.fault_counts.get("io-degraded", 0)
              > agg.recovery_counts.get("io-degraded", 0)))
    # black-box dump files present under the watched run dir (obs/
    # flight.py); a gauge, not a counter — dumps can be cleaned up
    gauge("pipegcn_blackbox_dumps_total",
          getattr(agg, "n_blackbox_dumps", 0))
    for src, rec in sorted(agg.latest("diagnosis").items()):
        gauge("pipegcn_diagnosis_confidence", rec.get("confidence"),
              {"source": src, "verdict": str(rec.get("verdict")),
               "deterministic": str(bool(rec.get(
                   "deterministic"))).lower()})
    for src, rec in sorted(agg.latest("membership").items()):
        gauge("pipegcn_membership_generation", rec.get("generation"),
              {"source": src})
    for src, rec in sorted(agg.latest("stream").items()):
        gauge("pipegcn_stream_seq", rec.get("seq"), {"source": src})
    # write-ahead delta journal (stream/journal.py): the topology
    # generation each writer last reported, and the replay lag — how
    # many journaled seqs a crash right now would have to re-apply
    # (watermark/append records carry lag_seqs; 0 = fully covered)
    for src, rec in sorted(agg.latest("journal").items()):
        lab = {"source": src}
        gauge("pipegcn_topo_generation", rec.get("topo_generation"),
              lab)
        gauge("pipegcn_journal_lag_seqs", rec.get("lag_seqs"), lab)
    for (src, kind), n in sorted(agg.counts.items()):
        if kind == "span":
            gauge("pipegcn_spans_total", n, {"source": src},
                  mtype="counter")
    # training-span verdicts (obs/trainspan.py fold over the live
    # buffer): the always-on measured overlap + rank-skew surface
    ts = agg.trainspan() if hasattr(agg, "trainspan") else None
    if ts:
        gauge("pipegcn_overlap_fraction", ts.get("overlap_spans"))
        for r, s in sorted(ts.get("comm_wait_s_by_rank",
                                  {}).items()):
            gauge("pipegcn_comm_wait_seconds", s, {"rank": str(r)})
        for r, g in sorted(ts.get("straggler_gap_s_by_rank",
                                  {}).items()):
            gauge("pipegcn_straggler_gap_seconds", g,
                  {"rank": str(r)})
    if engine is not None:
        for inst in engine.firing():
            gauge("pipegcn_alert_firing", 1, inst)
        gauge("pipegcn_alerts_fired_total", engine.n_fired,
              mtype="counter")
        gauge("pipegcn_alerts_resolved_total", engine.n_resolved,
              mtype="counter")
    if sink_stats:
        # the monitor's OWN MetricsLogger (alerts sink) health: the
        # PR-14 io-degraded ring made visible (MetricsLogger.stats())
        gauge("pipegcn_monitor_sink_records", sink_stats.get("records"))
        gauge("pipegcn_monitor_sink_ring_depth",
              sink_stats.get("ring_depth"))
        gauge("pipegcn_monitor_sink_dropped", sink_stats.get("dropped"))
        gauge("pipegcn_monitor_sink_degraded",
              int(bool(sink_stats.get("degraded"))))
    return "\n".join(lines) + "\n"


def health_json(agg: LiveAggregator,
                engine: Optional[AlertEngine] = None,
                sink_stats: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
    """The /health rollup: overall status + the aggregator snapshot.
    status: "ok" (nothing firing) | "degraded" (warn/info alerts
    firing) | "critical" (a page-severity alert is firing)."""
    snap = agg.snapshot()
    status = "ok"
    firing: List[Dict[str, str]] = []
    if engine is not None:
        firing = engine.firing()
        sevs = set()
        for key in engine._firing:
            rid = key[0].split("#", 1)[0]
            for cfg in engine.rules:
                if cfg["rule"] == rid:
                    sevs.add(str(cfg.get("severity", "warn")))
        if "page" in sevs:
            status = "critical"
        elif sevs:
            status = "degraded"
    out = {"status": status, "alerts_firing": firing, **snap}
    if engine is not None:
        out["alerts_fired"] = engine.n_fired
        out["alerts_resolved"] = engine.n_resolved
    if sink_stats:
        out["monitor_sink"] = dict(sink_stats)
    return out


# ---------------------------------------------------------------------------
# the exporter
# ---------------------------------------------------------------------------


class MonitorServer:
    """`--serve-http`: /metrics + /health over stdlib http.server.

    Handlers read aggregator state under `lock` (the monitor loop
    polls under the same lock), so a scrape never sees a half-folded
    record batch. Port 0 binds an ephemeral port (tests read
    `self.port`)."""

    def __init__(self, agg: LiveAggregator,
                 engine: Optional[AlertEngine] = None,
                 sink_stats: Optional[Callable[[], Dict]] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 lock: Optional[threading.Lock] = None):
        self.agg = agg
        self.engine = engine
        self.sink_stats = sink_stats
        self.lock = lock or threading.Lock()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    if self.path.split("?", 1)[0] == "/metrics":
                        with outer.lock:
                            body = prometheus_text(
                                outer.agg, outer.engine,
                                outer.sink_stats()
                                if outer.sink_stats else None)
                        ctype = "text/plain; version=0.0.4"
                    elif self.path.split("?", 1)[0] == "/health":
                        with outer.lock:
                            body = json.dumps(health_json(
                                outer.agg, outer.engine,
                                outer.sink_stats()
                                if outer.sink_stats else None),
                                indent=2) + "\n"
                        ctype = "application/json"
                    else:
                        self.send_error(404, "try /metrics or /health")
                        return
                except BrokenPipeError:
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # quiet: scrapes are chatty
                pass

        self.httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.httpd.daemon_threads = True
        self.port = int(self.httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MonitorServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True, name="pipegcn-monitor-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
