"""Device-trace profiling windows: measured phase time, not estimates.

The report CLI has so far *estimated* the comm/compute overlap fraction
from host-side `PhaseTimer` spans and standalone `measure_comm()`
costs. This module turns a captured ``jax.profiler.trace`` into a
measured decomposition:

  1. ``jax.profiler`` writes TensorBoard-format traces, including a
     Chrome-trace ``<host>.trace.json.gz`` whose device lines carry one
     event per executed HLO op (``args.hlo_op`` / ``args.hlo_module``).
  2. The op names alone are anonymous (``fusion.12``), but the COMPILED
     step's HLO text carries ``metadata={op_name="jit(step)/.../layer0/
     spmm/..."}`` — the `named_phase` scopes the model stack already
     emits. ``hlo_op_map`` joins the two.
  3. ``fold_trace`` buckets every device op's duration into a phase
     (spmm / dense / halo_comm / grad_reduce / optimizer / norm /
     dropout_rng / other) and measures the **overlap fraction**: the
     share of communication device-time covered by concurrently-running
     compute (interval union per trace process). Works on the CPU mesh
     (virtual devices are executor threads of one process), so the
     whole pipeline is tier-1 testable.

The result is the contracted ``profile`` record (obs/schema.py v2):
measured per-phase device seconds + overlap fraction in [0, 1], which
the report CLI prints NEXT TO the host-side estimate and flags when
the two diverge.

Everything here is stdlib-only (gzip/json/re); jax is never imported —
the trace directory and the compiled HLO text arrive as inputs, so the
parser also runs in jax-free report tooling.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

# phase vocabulary — the contract the profile/anatomy records share
PHASES = ("spmm", "dense", "halo_comm", "grad_reduce", "optimizer",
          "norm", "dropout_rng", "eval", "other")

# communication phases whose device time the overlap fraction measures
COMM_PHASES = ("halo_comm", "grad_reduce")

# HLO opcode prefixes that are communication wherever they appear,
# even outside a named scope (shard_map lowers ppermute/psum to these)
_COMM_KINDS = ("collective-permute", "all-reduce", "all-gather",
               "all-to-all", "reduce-scatter", "collective-broadcast",
               "send", "recv")


def classify_op(op_name: str, hlo_kind: str = "") -> str:
    """Bucket one HLO op into a phase by its metadata scope path (the
    `named_phase` names: layer{i}/spmm, halo_exchange, grad_reduce,
    adam_update, ...) with the opcode as a fallback for collectives.
    Backward ops keep the forward scope inside jax's transpose(...)
    wrapper, so substring matching covers both directions."""
    s = op_name.lower()
    k = hlo_kind.lower()
    if "halo_exchange" in s or "bgrad_return" in s:
        return "halo_comm"
    if "grad_reduce" in s:
        return "grad_reduce"
    if any(k.startswith(c) for c in _COMM_KINDS):
        # an unscoped collective: the gradient psum is scoped, so bare
        # collectives are halo traffic (stale-concat exchange blocks)
        return "halo_comm"
    if "adam_update" in s:
        return "optimizer"
    if "/spmm" in s or "spmm" in s:
        return "spmm"
    if "dropout" in s:
        return "dropout_rng"
    if "/norm" in s or "layer_norm" in s or "batch_norm" in s:
        return "norm"
    if "/dense" in s:
        return "dense"
    if "eval" in s:
        return "eval"
    return "other"


# one optimized-HLO instruction: "%name = type opcode(...), ...,
# metadata={op_name="..."}". Tuple-typed outputs and missing metadata
# both occur; keep the regex tolerant and skip what it cannot read.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>\([^=]*?\)|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?)\s*"
    r"(?P<kind>[\w\-]+)\(")
_OPNAME_RE = re.compile(r'metadata=\{[^}]*op_name="(?P<op>[^"]*)"')


def hlo_op_map(compiled_text: str) -> Dict[str, Tuple[str, str]]:
    """{hlo op name -> (scope op_name, opcode)} from a compiled
    module's text (``jitted.lower(...).compile().as_text()``). The op
    names here are what the trace events' ``args.hlo_op`` carries, so
    this is the join key between the anonymous timeline and the named
    phases."""
    out: Dict[str, Tuple[str, str]] = {}
    for line in compiled_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        om = _OPNAME_RE.search(line)
        out[m.group("name")] = (om.group("op") if om else "",
                                m.group("kind"))
    return out


def module_name(compiled_text: str) -> str:
    """The HloModule name (trace events carry it as args.hlo_module)."""
    m = re.match(r"HloModule\s+([\w.\-]+)", compiled_text)
    return m.group(1) if m else ""


# ---------------- trace loading ---------------------------------------


def find_trace_files(profile_dir: str) -> List[str]:
    """All ``*.trace.json(.gz)`` files of the NEWEST capture session
    under a ``jax.profiler`` output dir (layout:
    ``<dir>/plugins/profile/<timestamp>/<host>.trace.json.gz``)."""
    sessions = sorted(glob.glob(
        os.path.join(profile_dir, "plugins", "profile", "*")))
    if not sessions:
        return []
    latest = sessions[-1]
    return sorted(glob.glob(os.path.join(latest, "*.trace.json.gz"))
                  + glob.glob(os.path.join(latest, "*.trace.json")))


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """The ``traceEvents`` list of one Chrome-trace file (.gz or
    plain)."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt", encoding="utf-8") as f:
        data = json.load(f)
    evs = data.get("traceEvents", [])
    return [e for e in evs if isinstance(e, dict) and e]


def _union_intervals(iv: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for a, b in iv[1:]:
        if a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _overlap_with_union(iv: Tuple[float, float],
                        union: Sequence[Tuple[float, float]]) -> float:
    a, b = iv
    tot = 0.0
    for ua, ub in union:
        if ub <= a:
            continue
        if ua >= b:
            break
        tot += min(b, ub) - max(a, ua)
    return tot


def fold_trace(events: Sequence[Dict[str, Any]],
               op_map: Dict[str, Tuple[str, str]],
               module: str = "") -> Dict[str, Any]:
    """Fold device-op trace events into per-phase device seconds and a
    measured comm/compute overlap fraction.

    Only events carrying ``args.hlo_op`` participate (those are the
    device-side op executions); when `module` or `op_map` is given,
    events are further restricted to the train step's module so a
    concurrently-dispatched eval program cannot masquerade as overlap.

    Overlap: per trace process (pid), the compute intervals form a
    union; each comm event's duration is split into covered/exposed
    against it. fraction = covered_comm / total_comm (0.0 when the
    capture saw no comm at all — P=1 runs)."""
    phase_us: Dict[str, float] = {}
    n_matched = n_dev = 0
    comm_by_pid: Dict[Any, List[Tuple[float, float]]] = {}
    comp_by_pid: Dict[Any, List[Tuple[float, float]]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        hop = args.get("hlo_op")
        if not hop:
            continue
        n_dev += 1
        if module and args.get("hlo_module") not in ("", None, module):
            continue
        op_name, kind = op_map.get(hop, ("", ""))
        if op_map and hop not in op_map and module == "":
            # an op from some other compiled program (eval, comm
            # microbench): keep it out of the step decomposition
            continue
        if hop in op_map:
            n_matched += 1
        phase = classify_op(op_name or e.get("name", ""), kind
                            or str(e.get("name", "")))
        dur = float(e.get("dur", 0.0))
        ts = float(e.get("ts", 0.0))
        phase_us[phase] = phase_us.get(phase, 0.0) + dur
        pid = e.get("pid")
        tgt = comm_by_pid if phase in COMM_PHASES else comp_by_pid
        tgt.setdefault(pid, []).append((ts, ts + dur))

    comm_us = sum(phase_us.get(p, 0.0) for p in COMM_PHASES)
    compute_us = sum(v for k, v in phase_us.items()
                     if k not in COMM_PHASES)
    covered_us = 0.0
    for pid, comm in comm_by_pid.items():
        union = _union_intervals(comp_by_pid.get(pid, []))
        for iv in comm:
            covered_us += _overlap_with_union(iv, union)
    frac = (min(max(covered_us / comm_us, 0.0), 1.0)
            if comm_us > 0 else 0.0)
    return {
        "phases": {k: round(v / 1e6, 9)
                   for k, v in sorted(phase_us.items())},
        "comm_s": round(comm_us / 1e6, 9),
        "compute_s": round(compute_us / 1e6, 9),
        "overlap_fraction": round(frac, 6),
        "n_device_events": n_dev,
        "n_matched_events": n_matched,
    }


def analyze_trace_dir(profile_dir: str, compiled_text: str
                      ) -> Optional[Dict[str, Any]]:
    """Parse the newest capture session under `profile_dir` against the
    train step's compiled HLO; returns the body of a ``profile`` record
    (event/epoch fields added by the caller) or None when the session
    left no parsable trace."""
    files = find_trace_files(profile_dir)
    if not files:
        return None
    events: List[Dict[str, Any]] = []
    for f in files:
        try:
            events.extend(load_trace_events(f))
        except (OSError, ValueError):
            continue
    if not events:
        return None
    op_map = hlo_op_map(compiled_text)
    folded = fold_trace(events, op_map, module=module_name(compiled_text))
    if folded["n_device_events"] == 0:
        return None
    folded["trace_files"] = [os.path.relpath(f, profile_dir)
                             for f in files]
    return folded


# ---------------- CLI flag parsing ------------------------------------


def parse_profile_epochs(spec: str) -> Tuple[int, int]:
    """'A:B' -> (A, B): capture a device trace around the dispatched
    blocks of epochs [A, B). Raises ValueError on malformed or empty
    windows so the CLI fails before burning a run."""
    m = re.fullmatch(r"(\d+):(\d+)", spec.strip())
    if not m:
        raise ValueError(
            f"--profile-epochs expects 'A:B' (epoch window [A, B)), "
            f"got {spec!r}")
    a, b = int(m.group(1)), int(m.group(2))
    if b <= a:
        raise ValueError(
            f"--profile-epochs window [{a}, {b}) is empty")
    return a, b
