"""Automated postmortem diagnosis: from artifacts to a root cause.

`collect_bundle(run_dir)` gathers everything a dead (or finished) run
left behind — black-box flight-recorder dumps (obs/flight.py), every
metrics JSONL stream (reusing `obs/live.discover_streams`, so
per-generation files, the membership ledger metrics and window.jsonl
all fold in), child-process log tails, checkpoint metadata, and an
environment fingerprint — into one JSON-able bundle. `diagnose(bundle)`
then runs an ORDERED, evidence-citing rule set and returns a
confidence-ranked verdict:

  wedged-collective  a rank blocked in a dead collective (watchdog
                     dumps, peer-lost hard-deadline faults, open
                     dispatch/collective spans)
  oom                RESOURCE_EXHAUSTED / out-of-memory text anywhere
  fallback-exhausted the kernel fallback ladder ran out of rungs
  corrupt-artifact   digest/CRC-verification failures killed the run
  config-error       a setup-phase ValueError/argument error
  desync             cross-rank parameter desync without a resync
  storage-fault      durable writes degraded and never recovered
  recompile-storm    repeated recompiles dominated the run
  divergence         sentinel retries exhausted / NaN death
  preemption         a requested, checkpointed, resumable exit
  topo-rollback      a resume rolled the delta journal back past the
                     checkpoint watermark (journal op="truncate"
                     records with dropped entries): topology deltas
                     applied after the last durable checkpoint were
                     un-committed and re-delivered by the stream plan
  crash              an uncaught exception not matching the above
  clean-exit         the run completed after the last recorded trouble
  unknown            nothing matched (pipegcn-debug exits 4)

Three classes are DETERMINISTIC — relaunching reproduces the failure,
so the elastic supervisor fails fast on them instead of burning
``--max-restarts``: corrupt-artifact, config-error,
fallback-exhausted. Everything else keeps the restart/backoff policy
(docs/RESILIENCE.md "Fail fast vs restart").

The verdict dict validates as the schema-v11 ``diagnosis`` record
kind. `pipegcn_tpu.cli.debug` is the CLI (`pipegcn-debug explain
<run-dir>`); the elastic supervisor and scripts/tpu_window.py call
:func:`diagnose_run` directly.
"""

from __future__ import annotations

import glob
import json
import os
import platform
import re
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .live import discover_streams, merge_streams

# classes where a relaunch deterministically reproduces the failure:
# the supervisor fails fast instead of retrying (docs/RESILIENCE.md)
DETERMINISTIC_CLASSES = ("corrupt-artifact", "config-error",
                         "fallback-exhausted")

_MAX_LOG_TAIL = 4000        # chars kept per log file
_MAX_LOG_FILES = 24
_MAX_BLACKBOXES = 16
_TIMELINE_EVENTS = 40


# ---------------------------------------------------------------------
# bundle collection
# ---------------------------------------------------------------------


def _rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(path, root)
    except ValueError:
        return path


def collect_bundle(run_dir: str) -> Dict[str, Any]:
    """Everything the run left behind, as one JSON-able dict. Tolerant
    by construction: unreadable/corrupt files become entries with an
    ``error`` key, never exceptions — a postmortem must work on
    exactly the broken artifacts a crash leaves."""
    run_dir = os.path.abspath(os.fspath(run_dir))
    bundle: Dict[str, Any] = {"run_dir": run_dir,
                              "collected_unix": time.time()}

    # black-box dumps (obs/flight.py), anywhere under the run dir
    boxes: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(
            run_dir, "**", "blackbox-r*.json"), recursive=True)):
        entry: Dict[str, Any] = {"path": _rel(path, run_dir)}
        try:
            with open(path, encoding="utf-8") as fh:
                entry["data"] = json.load(fh)
        except (OSError, ValueError) as exc:
            entry["error"] = repr(exc)
        boxes.append(entry)
        if len(boxes) >= _MAX_BLACKBOXES:
            break
    bundle["blackboxes"] = boxes

    # every metrics stream the live plane would discover
    paths = discover_streams(run_dir)
    bundle["streams"] = [_rel(p, run_dir) for p in paths]
    bundle["records"] = merge_streams(paths)

    # child / rank log tails (elastic supervisor children, window runs)
    tails: Dict[str, str] = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "**", "*.log"),
                                 recursive=True))[:_MAX_LOG_FILES]:
        try:
            with open(path, "rb") as fh:
                fh.seek(max(0, os.path.getsize(path) - _MAX_LOG_TAIL))
                tails[_rel(path, run_dir)] = fh.read().decode(
                    "utf-8", "replace")
        except OSError as exc:
            tails[_rel(path, run_dir)] = f"<unreadable: {exc!r}>"
    bundle["log_tails"] = tails

    # checkpoint metadata (never the payloads)
    cks = []
    for path in sorted(glob.glob(os.path.join(
            run_dir, "**", "state-*.npz"), recursive=True)):
        try:
            st = os.stat(path)
            cks.append({"path": _rel(path, run_dir),
                        "bytes": st.st_size, "mtime_unix": st.st_mtime})
        except OSError:
            continue
    bundle["checkpoints"] = cks

    # environment / config fingerprint
    fp: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
    }
    try:
        from importlib.metadata import version

        fp["jax"] = version("jax")
    except Exception:  # noqa: BLE001 — fingerprint is best-effort
        pass
    try:
        from .schema import SCHEMA_VERSION

        fp["schema_version"] = SCHEMA_VERSION
    except Exception:  # noqa: BLE001
        pass
    run_hdr = next((r for r in bundle["records"]
                    if r.get("event") == "run"), None)
    if run_hdr is not None:
        cfg = run_hdr.get("config") or {}
        fp["config"] = {k: cfg[k] for k in sorted(cfg)
                        if isinstance(cfg[k], (str, int, float, bool,
                                               type(None)))}
    bundle["fingerprint"] = fp
    return bundle


# ---------------------------------------------------------------------
# rule helpers
# ---------------------------------------------------------------------


def _faults(bundle: Dict[str, Any], kind: str) -> List[Dict[str, Any]]:
    return [r for r in bundle.get("records", ())
            if r.get("event") == "fault" and r.get("kind") == kind]


def _recoveries(bundle: Dict[str, Any], kind: str) -> List[Dict]:
    return [r for r in bundle.get("records", ())
            if r.get("event") == "recovery" and r.get("kind") == kind]


def _boxes(bundle: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [b for b in bundle.get("blackboxes", ()) if "data" in b]


def _corpus(bundle: Dict[str, Any]) -> List[Tuple[str, str]]:
    """(source, text) pairs to grep for error signatures: log tails,
    black-box error fields and stack captures."""
    out: List[Tuple[str, str]] = list(bundle.get("log_tails",
                                                 {}).items())
    for b in _boxes(bundle):
        d = b["data"]
        for key in ("error", "stacks"):
            if d.get(key):
                out.append((f"{b['path']}:{key}", str(d[key])))
    return out


def _grep(bundle: Dict[str, Any], pattern: str,
          max_hits: int = 4) -> List[str]:
    """Evidence strings ``source: matched line`` for a regex."""
    rx = re.compile(pattern)
    hits: List[str] = []
    for source, text in _corpus(bundle):
        for line in text.splitlines():
            if rx.search(line):
                hits.append(f"{source}: {line.strip()[:160]}")
                break  # one citation per source is plenty
        if len(hits) >= max_hits:
            break
    return hits


def _last_summary_time(bundle: Dict[str, Any]) -> Optional[float]:
    ts = [r.get("time_unix") for r in bundle.get("records", ())
          if r.get("event") == "summary"
          and isinstance(r.get("time_unix"), (int, float))]
    has_summary = any(r.get("event") == "summary"
                      for r in bundle.get("records", ()))
    if not has_summary:
        return None
    return max([t for t in ts if t is not None], default=0.0)


def _newest_box_time(bundle: Dict[str, Any]) -> Optional[float]:
    # stall dumps are NON-terminal by design (the stall detector
    # captures stacks and the process keeps running), so they must not
    # make a completed run look like it died after its summary
    ts = [b["data"].get("time_unix") for b in _boxes(bundle)
          if isinstance(b["data"].get("time_unix"), (int, float))
          and b["data"].get("reason") != "stall"]
    return max(ts) if ts else None


# ---------------------------------------------------------------------
# the rule set (ordered: ties in confidence resolve to the earlier
# rule — the ordering IS part of the contract, pinned by tests)
# ---------------------------------------------------------------------


def _rule_clean_exit(b: Dict) -> Optional[Dict]:
    t_sum = _last_summary_time(b)
    if t_sum is None:
        return None
    t_box = _newest_box_time(b)
    if t_box is not None and t_box > t_sum:
        return None  # something died AFTER the last completed run
    ev = ["summary record present: the run (or its clean resume) "
          "completed"]
    n_f = sum(1 for r in b.get("records", ())
              if r.get("event") == "fault")
    if n_f:
        ev.append(f"{n_f} fault record(s) all predate the final "
                  f"summary (recovered in-run)")
    return {"confidence": 0.9, "evidence": ev,
            "remediation": "nothing to do — the run completed; any "
                           "faults along the way were recovered"}


def _rule_wedged(b: Dict) -> Optional[Dict]:
    ev: List[str] = []
    for box in _boxes(b):
        d = box["data"]
        if d.get("reason") == "watchdog":
            ann = d.get("annotation") or {}
            ctx = ", ".join(f"{k}={ann[k]}" for k in sorted(ann)
                            if k not in ("t", "seq", "kind"))
            ev.append(f"{box['path']}: watchdog-trip dump (rank "
                      f"{d.get('rank')}"
                      + (f", {ctx}" if ctx else "") + ")")
            if d.get("stacks"):
                ev.append(f"{box['path']}: all-thread stacks captured "
                          f"while wedged")
    for r in _faults(b, "peer-lost"):
        ev.append(f"fault record: peer-lost at epoch {r.get('epoch')} "
                  f"(peer rank {r.get('peer_rank')}"
                  + (", hard deadline" if r.get("hard_deadline")
                     else "") + ")")
    for box in _boxes(b):
        for sp in box["data"].get("open_spans") or ():
            if sp.get("kind") in ("dispatch-enter", "collective-enter"):
                ev.append(f"{box['path']}: span {sp.get('kind')} "
                          f"(epoch {sp.get('epoch')}"
                          + (f", phase {sp['phase']}"
                             if sp.get("phase") else "")
                          + ") never exited")
    if not ev:
        return None
    strong = any("watchdog" in e or "peer-lost" in e for e in ev)
    return {"confidence": 0.9 if strong and len(ev) >= 2 else 0.6,
            "evidence": ev,
            "remediation": "a rank stopped making progress inside a "
                           "collective; restart the pod from the "
                           "emergency checkpoint (--resume) and check "
                           "the dead peer's host/network"}


def _rule_oom(b: Dict) -> Optional[Dict]:
    ev = _grep(b, r"RESOURCE_EXHAUSTED|Out of memory|bad_alloc"
                  r"|MemoryError|OOM[ :-]|oom-kill")
    if not ev:
        return None
    return {"confidence": 0.85, "evidence": ev,
            "remediation": "the device or host ran out of memory: "
                           "shrink --spmm-chunk / --n-hidden, raise "
                           "--n-partitions, or move to a larger "
                           "topology"}


def _rule_fallback_exhausted(b: Dict) -> Optional[Dict]:
    ev = _grep(b, r"KernelFallbackError|fallback ladder|every rung")
    fbs = [r for r in b.get("records", ())
           if r.get("event") == "fallback"]
    for r in fbs[:3]:
        ev.append(f"fallback record: {r.get('from_impl')} -> "
                  f"{r.get('to_impl')} at epoch {r.get('epoch')}")
    if not _grep(b, r"KernelFallbackError|fallback ladder|every rung"):
        return None
    return {"confidence": 0.85, "evidence": ev,
            "remediation": "every aggregation-kernel rung failed — "
                           "this reproduces on relaunch; pin "
                           "--spmm-impl xla and file the kernel crash"}


def _rule_corrupt_artifact(b: Dict) -> Optional[Dict]:
    ev = _grep(b, r"CheckpointCorrupt|LedgerCorrupt|digest mismatch"
                  r"|CRC mismatch|every generation corrupt"
                  r"|partition artifact .* corrupt")
    if not ev:
        return None
    return {"confidence": 0.85, "evidence": ev,
            "remediation": "a persisted artifact fails verification — "
                           "relaunching reproduces this; delete or "
                           "restore the corrupt generation/artifact "
                           "before restarting"}


def _rule_config_error(b: Dict) -> Optional[Dict]:
    ev: List[str] = []
    for box in _boxes(b):
        err = str(box["data"].get("error") or "")
        if re.match(r"(ValueError|NotImplementedError|TypeError"
                    r"|KeyError|ArgumentError)", err):
            ev.append(f"{box['path']}: setup/config exception: "
                      f"{err[:160]}")
    ev += _grep(b, r"error: (unrecognized|invalid|argument)"
                   r"|usage: pipegcn")
    if not ev:
        return None
    return {"confidence": 0.8, "evidence": ev,
            "remediation": "the configuration itself is rejected — "
                           "relaunching reproduces this; fix the "
                           "flag/config named above"}


def _rule_desync(b: Dict) -> Optional[Dict]:
    fs = _faults(b, "desync")
    if not fs:
        return None
    rec = _recoveries(b, "desync")
    ev = [f"fault record: cross-rank desync at epoch "
          f"{r.get('epoch')} (source rank {r.get('source_rank')})"
          for r in fs[:3]]
    if rec:
        ev.append(f"{len(rec)} desync recovery record(s): resync "
                  f"adopted rank 0's state")
        conf = 0.5  # recovered; only relevant if nothing else matched
    else:
        ev += _grep(b, r"cross-rank parameter desync", max_hits=2)
        conf = 0.8
    return {"confidence": conf, "evidence": ev,
            "remediation": "replicated params drifted across ranks; "
                           "resume from the crash checkpoint and "
                           "enable --desync-resync (or investigate "
                           "nondeterministic kernels)"}


def _rule_sdc(b: Dict) -> Optional[Dict]:
    """Silent data corruption (resilience/integrity.py): sdc fault
    records, integrity mismatch records, quarantine requests. A
    quarantine (or an unrecovered detection) is high-confidence — the
    run named a defective member; a recovered one-off detection is
    background context only."""
    fs = _faults(b, "sdc")
    mism = [r for r in b.get("records", ())
            if r.get("event") == "integrity"
            and r.get("outcome") == "mismatch"]
    if not fs and not mism:
        return None
    ev = [f"fault record: sdc ({r.get('target')}) at epoch "
          f"{r.get('epoch')} (rank {r.get('rank')}, "
          f"{r.get('strikes', 1)} strike(s))" for r in fs[:3]]
    ev += [f"integrity record: {r.get('check')} mismatch on "
           f"{r.get('target')} at epoch {r.get('epoch')} "
           f"({str(r.get('detail', ''))[:60]})" for r in mism[:3]]
    quarantined = (_faults(b, "quarantine-request")
                   or _grep(b, r"quarantine requested for member",
                            max_hits=2))
    if quarantined:
        ev += [f"fault record: quarantine-request (member "
               f"{r.get('member')}, {r.get('strikes')} strikes)"
               for r in _faults(b, "quarantine-request")[:2]]
        ev += [h for h in quarantined if isinstance(h, str)]
        conf = 0.9
    else:
        recovered = bool(_recoveries(b, "sdc"))
        conf = 0.5 if recovered else 0.8
        if recovered:
            ev.append("sdc recovery record present: rollback/flush/"
                      "rebuild completed")
    return {"confidence": conf, "evidence": ev,
            "remediation": "silent data corruption detected; if one "
                           "rank keeps tripping (quarantined), pull "
                           "that host for screening — rejoin only via "
                           "an explicit rejoin request after clearing "
                           "its quarantine marker"}


def _rule_storage_fault(b: Dict) -> Optional[Dict]:
    fs = _faults(b, "io-degraded")
    ev = [f"fault record: io-degraded at epoch {r.get('epoch')} "
          f"({str(r.get('component', r.get('reason', '')))[:80]})"
          for r in fs[:3]]
    ev += _grep(b, r"ENOSPC|EROFS|No space left|Read-only file system"
                   r"|CHECKPOINT SAVE FAILED", max_hits=3)
    if not ev:
        return None
    recovered = bool(_recoveries(b, "io-degraded"))
    if recovered:
        ev.append("io-degraded recovery record present: the writer "
                  "caught back up")
    return {"confidence": 0.45 if recovered else 0.8, "evidence": ev,
            "remediation": "durable writes degraded (disk full / "
                           "read-only / torn); free space or fix the "
                           "mount, then --resume — the previous "
                           "checkpoint generation is authoritative"}


def _rule_recompile_storm(b: Dict) -> Optional[Dict]:
    repads = [r for r in b.get("records", ())
              if r.get("event") == "stream" and r.get("repadded")]
    hits = _grep(b, r"re-padded: recompile|recompil", max_hits=3)
    ev = [f"stream record: delta seq {r.get('seq')} re-padded "
          f"(recompile) at epoch {r.get('epoch')}" for r in repads[:4]]
    ev += hits
    if len(ev) < 3:
        return None
    return {"confidence": 0.7, "evidence": ev,
            "remediation": "shape changes forced repeated recompiles; "
                           "raise --stream-slack (or pre-pad) so "
                           "deltas land without growing shapes"}


def _rule_divergence(b: Dict) -> Optional[Dict]:
    fs = _faults(b, "divergence")
    if not fs:
        return None
    exhausted = _grep(b, r"retries were exhausted|DivergenceError",
                      max_hits=2)
    ev = [f"fault record: divergence at epoch {r.get('epoch')} "
          f"(retry {r.get('retry')}, reason "
          f"{str(r.get('reason', ''))[:60]})" for r in fs[:3]]
    ev += exhausted
    recovered = bool(_recoveries(b, "divergence"))
    if recovered and not exhausted:
        ev.append("divergence recovery record present: rollback + "
                  "retry succeeded")
    return {"confidence": 0.85 if exhausted
            else (0.45 if recovered else 0.7),
            "evidence": ev,
            "remediation": "training diverged; lower --lr, raise "
                           "--sentinel-loss-factor, or enable "
                           "--loss-scale dynamic before resuming"}


def _rule_preemption(b: Dict) -> Optional[Dict]:
    ev: List[str] = []
    for box in _boxes(b):
        if box["data"].get("reason") == "preemption":
            ev.append(f"{box['path']}: preemption dump (epoch "
                      f"{box['data'].get('epoch')})")
    ev += [f"fault record: preemption at epoch {r.get('epoch')} "
           f"({str(r.get('reason', ''))[:60]})"
           for r in _faults(b, "preemption")[:3]]
    ev += _grep(b, r"resumable — rerun with --resume|\[exit 75\]",
                max_hits=2)
    if not ev:
        return None
    return {"confidence": 0.75, "evidence": ev,
            "remediation": "a requested, checkpointed stop — rerun "
                           "with --resume --checkpoint-dir; no "
                           "investigation needed"}


def _rule_crash(b: Dict) -> Optional[Dict]:
    ev: List[str] = []
    for box in _boxes(b):
        d = box["data"]
        if d.get("reason") in ("exception", "fault"):
            ev.append(f"{box['path']}: {d.get('reason')} dump "
                      f"({str(d.get('error', ''))[:120]})")
    ev += _grep(b, r"Traceback \(most recent call last\)", max_hits=2)
    if not ev:
        return None
    return {"confidence": 0.65, "evidence": ev,
            "remediation": "an uncaught exception killed the run; the "
                           "crash checkpoint (if any) is resumable — "
                           "read the cited error before retrying"}


def _rule_topo_rollback(b: Dict) -> Optional[Dict]:
    """Crash-consistent streaming (stream/journal.py): a resume found
    journal entries PAST the checkpoint watermark — deltas applied
    after the last durable checkpoint — and rolled them back
    (op="truncate" with dropped records) for re-delivery by the
    stream plan. Moderate confidence: the rollback itself is the
    designed recovery, so a completed run's clean-exit outranks it;
    it becomes the verdict only when the run died around the
    rollback."""
    truncs = [r for r in b.get("records", ())
              if r.get("event") == "journal"
              and r.get("op") == "truncate"
              and int(r.get("n_records", 0)) > 0]
    if not truncs:
        return None
    ev = []
    for r in truncs[:3]:
        ev.append(f"journal record: {int(r.get('n_records', 0))} "
                  f"entr{'y' if int(r.get('n_records', 0)) == 1 else 'ies'} "
                  f"past watermark seq {r.get('seq')} rolled back "
                  f"(journal at generation {r.get('topo_generation')})")
    replays = [r for r in b.get("records", ())
               if r.get("event") == "journal"
               and r.get("op") == "replay"]
    for r in replays[:2]:
        ev.append(f"journal record: replay of "
                  f"{int(r.get('n_records', 0))} entr"
                  f"{'y' if int(r.get('n_records', 0)) == 1 else 'ies'}"
                  f" (+{int(r.get('rederived', 0))} re-derived from "
                  f"the plan) up to watermark seq {r.get('seq')}")
    return {
        "confidence": 0.6, "evidence": ev,
        "remediation": "topology deltas newer than the checkpoint "
                       "watermark were un-committed on resume and "
                       "re-delivered at their scheduled epochs — "
                       "verify the run's journal op=\"verify\" record "
                       "shows tables_match; checkpoint more often "
                       "(or fsync the journal) to shrink the "
                       "watermark gap"}


# (name, matcher) in priority order; confidence breaks ties the other
# way, so the order only matters between equal-confidence matches
_RULES: List[Tuple[str, Callable[[Dict], Optional[Dict]]]] = [
    ("clean-exit", _rule_clean_exit),
    ("wedged-collective", _rule_wedged),
    ("oom", _rule_oom),
    ("fallback-exhausted", _rule_fallback_exhausted),
    ("corrupt-artifact", _rule_corrupt_artifact),
    ("config-error", _rule_config_error),
    ("desync", _rule_desync),
    ("sdc", _rule_sdc),
    ("storage-fault", _rule_storage_fault),
    ("recompile-storm", _rule_recompile_storm),
    ("divergence", _rule_divergence),
    ("preemption", _rule_preemption),
    ("topo-rollback", _rule_topo_rollback),
    ("crash", _rule_crash),
]


# ---------------------------------------------------------------------
# diagnosis
# ---------------------------------------------------------------------


def _timeline(bundle: Dict[str, Any]) -> List[str]:
    """The last minutes of the run, rendered: contracted records and
    black-box breadcrumbs merged on their timestamps."""
    events: List[Tuple[float, str]] = []
    for r in bundle.get("records", ()):
        t = r.get("time_unix")
        if not isinstance(t, (int, float)):
            continue
        ev = r.get("event")
        if ev == "epoch":
            desc = f"epoch {r.get('epoch')} loss={r.get('loss')}"
        elif ev in ("fault", "recovery", "numerics", "fleet"):
            desc = f"{ev}:{r.get('kind')} epoch={r.get('epoch', '?')}"
        elif ev == "membership":
            desc = (f"membership gen {r.get('generation')} "
                    f"({r.get('trigger')})")
        elif ev in ("run", "summary", "alert", "stream", "fallback",
                    "blackbox", "diagnosis", "soak"):
            desc = ev
        else:
            continue
        events.append((float(t), desc))
    for box in _boxes(bundle):
        d = box["data"]
        for c in d.get("crumbs") or ():
            t = c.get("t")
            if isinstance(t, (int, float)):
                keys = ", ".join(
                    f"{k}={c[k]}" for k in sorted(c)
                    if k not in ("t", "seq", "kind"))
                events.append((float(t),
                               f"r{d.get('rank', '?')} crumb "
                               f"{c.get('kind')}"
                               + (f" ({keys[:80]})" if keys else "")))
        t = d.get("time_unix")
        if isinstance(t, (int, float)):
            events.append((float(t),
                           f"BLACKBOX DUMP r{d.get('rank', '?')} "
                           f"reason={d.get('reason')}"))
    events.sort(key=lambda e: e[0])
    events = events[-_TIMELINE_EVENTS:]
    if not events:
        return []
    t0 = events[-1][0]
    return [f"t-{t0 - t:7.1f}s  {desc}" for t, desc in events]


def diagnose(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """Run the rule set over a collected bundle; returns the verdict
    dict (validates as a schema ``diagnosis`` record), including the
    full ranked candidate list."""
    matches: List[Dict[str, Any]] = []
    for i, (name, fn) in enumerate(_RULES):
        try:
            m = fn(bundle)
        except Exception as exc:  # noqa: BLE001 — a broken rule must
            #                       not kill the whole postmortem
            m = {"confidence": 0.0, "evidence": [f"rule error: {exc!r}"],
                 "remediation": ""}
        if m is not None:
            matches.append({"verdict": name, "order": i, **m})
    matches.sort(key=lambda m: (-m["confidence"], m["order"]))
    if matches:
        top = matches[0]
        verdict, confidence = top["verdict"], float(top["confidence"])
        evidence, remediation = top["evidence"], top["remediation"]
    else:
        verdict, confidence = "unknown", 0.0
        n_box = len(_boxes(bundle))
        evidence = [("no rule matched despite "
                     f"{n_box} black-box dump(s) — inspect them "
                     "directly (the timeline below folds them in)")
                    if n_box else
                    ("no rule matched: no dumps, no fault records, no "
                     "recognizable error text")]
        remediation = ("collect more: enable --metrics-out, keep the "
                       "coordination dir, and rerun with PIPEGCN_"
                       "STALL_S set for stall forensics")
    return {
        "event": "diagnosis",
        "verdict": verdict,
        "confidence": confidence,
        "evidence": list(evidence),
        "remediation": remediation,
        "deterministic": verdict in DETERMINISTIC_CLASSES,
        "candidates": [{"verdict": m["verdict"],
                        "confidence": float(m["confidence"])}
                       for m in matches],
        "run_dir": bundle.get("run_dir", ""),
        "n_blackboxes": len(_boxes(bundle)),
        "timeline": _timeline(bundle),
    }


def diagnose_run(run_dir: str) -> Dict[str, Any]:
    """collect_bundle + diagnose in one call (supervisor / tooling
    entry point)."""
    return diagnose(collect_bundle(run_dir))


def render(verdict: Dict[str, Any]) -> str:
    """Human-readable report: verdict, evidence, remediation, and the
    last-minutes timeline."""
    lines = [
        f"verdict: {verdict['verdict']} "
        f"(confidence {verdict['confidence']:.2f}"
        + (", deterministic — do not blind-restart"
           if verdict.get("deterministic") else "") + ")",
        f"run dir: {verdict.get('run_dir', '?')}",
        "",
        "evidence:",
    ]
    for e in verdict.get("evidence", ()):
        lines.append(f"  - {e}")
    others = [c for c in verdict.get("candidates", ())[1:3]]
    if others:
        lines.append("also considered: " + ", ".join(
            f"{c['verdict']} ({c['confidence']:.2f})" for c in others))
    lines += ["", f"remediation: {verdict.get('remediation', '')}"]
    tl = verdict.get("timeline") or []
    if tl:
        lines += ["", "last-minutes timeline:"]
        lines += [f"  {ln}" for ln in tl]
    return "\n".join(lines) + "\n"
