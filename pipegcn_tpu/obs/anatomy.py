"""Epoch anatomy: attribute the compiled step's FLOPs/bytes to phases.

VERDICT round 5's top open item is a measurement problem: the ~0.5 s
non-SpMM epoch floor is known only as a residual. This module answers
it structurally: walk the compiled train step's optimized HLO, estimate
each instruction's FLOPs and bytes from its shapes, and attribute them
to the same phase vocabulary the profiler uses (obs/profiler.py:
spmm / dense / halo_comm / grad_reduce / optimizer / norm /
dropout_rng / other) via the `named_phase` scope metadata. Combined
with XLA's own ``.cost_analysis()`` total and ``memory_analysis()``,
that yields the contracted ``anatomy`` record (obs/schema.py v2).

The FLOP model is deliberately simple — dots dominate a GNN step:

  dot          2 * prod(output shape) * prod(contracted dims)
  elementwise/
  fusion/etc   prod(output shape)  (one op per output element)
  data movement (copy/transpose/broadcast/slice/gather/tuple plumbing)
               0 FLOPs, but bytes = out + operand bytes

``attributed_flops_fraction`` is the share of the estimated total that
landed in a NAMED phase (anything but "other") — the acceptance gate
is >= 90%, i.e. the scope annotations cover the compiled program.

``time_config`` is the on-chip ablation timer promoted out of
scripts/epoch_anatomy.py (which is now a thin wrapper): time the SAME
production config with one ingredient removed at a time; the deltas
attribute the wall-clock floor the way the HLO walk attributes FLOPs.
"""

from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Tuple

from .profiler import PHASES, _INSTR_RE, _OPNAME_RE, classify_op

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

# opcodes that move or rename data without arithmetic
_ZERO_FLOP = {
    "parameter", "constant", "copy", "copy-start", "copy-done",
    "bitcast", "bitcast-convert", "transpose", "reshape", "broadcast",
    "slice", "dynamic-slice", "dynamic-update-slice", "concatenate",
    "gather", "tuple", "get-tuple-element", "pad", "reverse", "iota",
    "after-all", "partition-id", "replica-id", "domain", "custom-call",
    "collective-permute", "all-gather", "send", "recv", "send-done",
    "recv-done", "infeed", "outfeed", "rng-bit-generator", "optimization-barrier",
}


def _parse_shapes(type_str: str) -> List[Tuple[str, List[int]]]:
    """Every dtype[dims] occurrence in an HLO type string (tuple types
    yield several)."""
    out = []
    for m in _SHAPE_RE.finditer(type_str):
        dims = [int(d) for d in m.group(2).split(",") if d]
        out.append((m.group(1), dims))
    return out


def _numel(dims: List[int]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def _shape_bytes(shapes: List[Tuple[str, List[int]]]) -> int:
    return sum(_DTYPE_BYTES.get(dt, 4) * _numel(dims)
               for dt, dims in shapes)


_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_OPERANDS_RE = re.compile(
    r"\(((?:[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?\s*%?[\w.\-]+(?:,\s*)?)+)\)")


def _instr_flops(kind: str, line: str,
                 out_shapes: List[Tuple[str, List[int]]]) -> float:
    out_elems = sum(_numel(dims) for _, dims in out_shapes)
    if kind == "dot":
        m = _OPERANDS_RE.search(line)
        cm = _CONTRACT_RE.search(line)
        if m and cm:
            ops = _parse_shapes(m.group(1))
            if ops:
                lhs_dims = ops[0][1]
                cdims = [int(d) for d in cm.group(1).split(",") if d]
                k = 1
                for d in cdims:
                    if d < len(lhs_dims):
                        k *= lhs_dims[d]
                return 2.0 * out_elems * k
        return 2.0 * out_elems
    if kind in _ZERO_FLOP:
        return 0.0
    if kind == "all-reduce" or kind == "reduce-scatter":
        return float(out_elems)  # one add per element per hop-combine
    # fusions, elementwise, reduce, scatter, compare, select, rng, ...
    return float(out_elems)


def hlo_anatomy(compiled_text: str) -> Dict[str, Any]:
    """Walk an optimized HLO module's text; returns per-phase estimated
    {flops, bytes} plus totals and the attributed-flops fraction."""
    phases: Dict[str, Dict[str, float]] = {}
    total_flops = 0.0
    total_bytes = 0.0
    n_ops = 0
    in_entry = False
    for line in compiled_text.splitlines():
        # count the ENTRY computation only: fusion/reduce bodies would
        # double-count their calling op's output elements
        if line.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            in_entry = False
            continue
        if not in_entry:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        kind = m.group("kind")
        out_shapes = _parse_shapes(m.group("type"))
        om = _OPNAME_RE.search(line)
        op_name = om.group("op") if om else ""
        phase = classify_op(op_name, kind)
        fl = _instr_flops(kind, line, out_shapes)
        by = float(_shape_bytes(out_shapes))
        mo = _OPERANDS_RE.search(line)
        if mo:
            by += float(_shape_bytes(_parse_shapes(mo.group(1))))
        slot = phases.setdefault(phase, {"flops": 0.0, "bytes": 0.0,
                                         "n_ops": 0})
        slot["flops"] += fl
        slot["bytes"] += by
        slot["n_ops"] += 1
        total_flops += fl
        total_bytes += by
        n_ops += 1
    named = sum(v["flops"] for k, v in phases.items() if k != "other")
    return {
        "phases": phases,
        "est_flops": total_flops,
        "est_bytes": total_bytes,
        "n_ops": n_ops,
        "attributed_flops_fraction": (
            named / total_flops if total_flops > 0 else None),
    }


def step_anatomy(trainer) -> Dict[str, Any]:
    """The full ``anatomy`` record body for a Trainer's single-epoch
    compiled step: the HLO walk above + XLA's own cost analysis and
    (where the backend exposes one) memory analysis. Costs one compile
    of the single-epoch program when the trainer has only run fused
    blocks so far; cached otherwise."""
    import jax

    import jax.numpy as jnp

    rng = jax.random.fold_in(trainer._epoch_rng_base(), 0)
    compiled = trainer._step.lower(
        trainer.state, trainer.data, rng,
        jnp.float32(trainer.loss_scaler.scale)).compile()
    rec = hlo_anatomy(compiled.as_text())
    try:
        ca = trainer.step_cost_analysis()
    except Exception:  # noqa: BLE001 — backend without analysis
        ca = {}
    rec["flops"] = float(ca["flops"]) if ca.get("flops") else None
    rec["bytes_accessed"] = (float(ca["bytes accessed"])
                             if ca.get("bytes accessed") else None)
    try:
        ma = compiled.memory_analysis()
        rec["memory"] = {
            k: int(getattr(ma, k))
            for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                      "output_size_in_bytes", "generated_code_size_in_bytes")
            if hasattr(ma, k)
        } or None
    except Exception:  # noqa: BLE001
        rec["memory"] = None
    return rec


# ---------------- on-chip ablation timing -----------------------------


def time_config(sg, cfg, tcfg, reps: int, blk: int,
                trainer_cls=None) -> Tuple[float, float, float]:
    """Median per-epoch seconds of (sg, cfg, tcfg) over `reps` fused
    blocks of `blk` epochs, excluding setup and both compiles. The
    scripts/epoch_anatomy.py ablation clock, importable so window
    tooling and tests share one implementation."""
    import numpy as np

    if trainer_cls is None:
        from ..parallel.trainer import Trainer as trainer_cls

    t0 = time.perf_counter()
    tr = trainer_cls(sg, cfg, tcfg)
    setup = time.perf_counter() - t0
    t0 = time.perf_counter()
    tr.train_epochs(0, 1)
    compile_s = time.perf_counter() - t0
    if blk > 1:
        tr.train_epochs(1, blk)  # fused-program compile, off the clock
    times = []
    e = 1 + blk
    for _ in range(reps):
        t0 = time.perf_counter()
        tr.train_epochs(e, blk)
        times.append((time.perf_counter() - t0) / blk)
        e += blk
    del tr
    return float(np.median(times)), setup, compile_s


def time_variants(sg, base_cfg, base_tcfg, variants, reps: int = 3
                  ) -> Dict[str, float]:
    """Time a list of (name, cfg, tcfg) ablation variants; returns
    {name: median s/epoch}. The caller builds the variants (pp on/off,
    fused on/off, dropout/norm ablations) — this is the loop."""
    out: Dict[str, float] = {}
    for name, cfg, tc in variants:
        blk = max(1, int(getattr(tc, "fused_epochs", 1)))
        s, _, _ = time_config(sg, cfg, tc, reps, blk)
        out[name] = round(s, 6)
    return out


__all__ = ["PHASES", "hlo_anatomy", "step_anatomy", "time_config",
           "time_variants"]
