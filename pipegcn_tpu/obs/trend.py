"""Bench trend tracking over the repo's measurement artifacts
(docs/OBSERVABILITY.md "Live monitoring", scripts/bench_trend.py).

Every real-chip window leaves ``BENCH_r<N>.json`` (the bench headline,
or a failure tail when the round died) and ``MULTICHIP_r<N>.json`` /
``MULTICHIP_40part.json`` behind. This module folds that series into a
per-lever delta history — epoch time, fused-candidate epoch time,
pipeline speedup, MFU, vs-baseline ratio — flags any lever whose
latest value regressed past tolerance from its best-known headline,
and renders the table ``scripts/tpu_window.py`` auto-publishes as a
trend verdict when the queued window finally runs.

Pure stdlib + filesystem reads; no jax.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

# lever key -> direction ("down" = lower is better)
LEVERS: Dict[str, str] = {
    "value": "down",                    # headline metric (s/epoch)
    "candidate_epoch_s": "down",
    "candidate_fused_epoch_s": "down",
    "default_epoch_s": "down",
    "default_vanilla_epoch_s": "down",
    "default_pipeline_speedup": "up",
    "vs_baseline": "up",
    "mfu_pct": "up",
}


def _round_of(path: str) -> int:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else -1


def _headline_from_tail(tail: str) -> Optional[Dict[str, Any]]:
    """The bench headline is echoed as a JSON line in the captured
    tail; failed rounds (r01's backend traceback) have none."""
    best = None
    for line in tail.splitlines():
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and "metric" in d:
            best = d  # last one wins: the final headline of the round
    return best


def load_bench_round(path: str) -> Dict[str, Any]:
    """One ``BENCH_r<N>.json``: {round, ok, headline?} — `headline`
    comes from the pre-parsed field when present, else from scanning
    the tail (r01-style rounds parsed nothing), else None."""
    with open(path, encoding="utf-8") as f:
        d = json.load(f)
    headline = d.get("parsed") or _headline_from_tail(d.get("tail", ""))
    return {"round": _round_of(path) if _round_of(path) >= 0
            else int(d.get("n", -1)),
            "path": os.path.basename(path),
            "ok": d.get("rc", 1) == 0,
            "headline": headline if isinstance(headline, dict) else None}


def load_series(root: str = ".") -> Dict[str, Any]:
    """The whole measurement series under `root`: bench rounds,
    multichip rounds, and the 40-part sweep when present."""
    bench = [load_bench_round(p) for p in sorted(
        glob.glob(os.path.join(root, "BENCH_*.json")), key=_round_of)]
    bench = [b for b in bench if b["round"] >= 0]
    multi = []
    for p in sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")),
                    key=_round_of):
        try:
            with open(p, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        multi.append({"round": _round_of(p),
                      "ok": bool(d.get("ok")),
                      "skipped": bool(d.get("skipped")),
                      "n_devices": d.get("n_devices")})
    sweep = None
    p40 = os.path.join(root, "MULTICHIP_40part.json")
    if os.path.isfile(p40):
        try:
            with open(p40, encoding="utf-8") as f:
                sweep = json.load(f)
        except (OSError, ValueError):
            sweep = None
    return {"bench": bench, "multichip": multi, "sweep": sweep}


def _num(v: Any) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


# headline fields that make two rounds comparable: when the bench
# harness moves to a new shape/config (r2-r4 measured
# small_epoch_time, r5 reddit_scale_epoch_time), best-known resets —
# comparing epoch seconds across different graphs is not a regression
_CONFIG_KEYS = ("metric", "unit", "n_parts", "pipeline", "spmm_impl",
                "dtype", "headline_config")


def _config_of(h: Dict[str, Any]) -> str:
    return "|".join(str(h.get(k)) for k in _CONFIG_KEYS)


def trend(series: Dict[str, Any], tol: float = 0.05) -> Dict[str, Any]:
    """Per-lever delta history + regression verdict.

    For each lever with >= 1 data point: the (round, value) history,
    the consecutive deltas, the best-known value and its round, and a
    `regressed` flag — latest worse than best by more than `tol`
    (fractional). Best-known is scoped to rounds sharing the latest
    round's config fingerprint (_CONFIG_KEYS): a harness that moved
    to a bigger graph starts a fresh comparison segment instead of
    flagging the shape change as a regression. The top-level verdict
    regresses iff any lever does, or the latest bench round itself
    failed after a previous success."""
    bench = series.get("bench", [])
    levers: Dict[str, Any] = {}
    for key, direction in LEVERS.items():
        hist = []
        for b in bench:
            h = b.get("headline")
            if not h:
                continue
            v = _num(h.get(key))
            if v is not None:
                hist.append({"round": b["round"], "value": v,
                             "config": _config_of(h)})
        if not hist:
            continue
        latest = hist[-1]
        cmp_hist = [h for h in hist if h["config"] == latest["config"]]
        vals = [h["value"] for h in cmp_hist]
        if direction == "down":
            best = min(vals)
        else:
            best = max(vals)
        best_round = cmp_hist[vals.index(best)]["round"]
        deltas = [round(b2["value"] - b1["value"], 6)
                  for b1, b2 in zip(cmp_hist, cmp_hist[1:])]
        if best == 0:
            rel = 0.0
        elif direction == "down":
            rel = (latest["value"] - best) / abs(best)
        else:
            rel = (best - latest["value"]) / abs(best)
        levers[key] = {
            "direction": direction,
            "history": [{"round": h["round"], "value": h["value"]}
                        for h in hist],
            "deltas": deltas,
            "n_comparable": len(cmp_hist),
            "best": best,
            "best_round": best_round,
            "latest": latest["value"],
            "latest_round": latest["round"],
            "vs_best_pct": round(100.0 * rel, 2),
            "regressed": rel > tol,
        }
    ok_rounds = [b["round"] for b in bench if b["ok"]]
    failed_rounds = [b["round"] for b in bench if not b["ok"]]
    latest_failed_after_ok = bool(
        bench and not bench[-1]["ok"] and ok_rounds)
    flags = sorted(k for k, v in levers.items() if v["regressed"])
    if latest_failed_after_ok:
        flags.append("latest-round-failed")
    multi = series.get("multichip", [])
    multi_not_ok = [m["round"] for m in multi
                    if not m["ok"] and not m["skipped"]]
    if multi_not_ok:
        flags.append("multichip-round-failed")
    return {
        "n_rounds": len(bench),
        "ok_rounds": ok_rounds,
        "failed_rounds": failed_rounds,
        "levers": levers,
        "multichip_rounds": len(multi),
        "multichip_failed": multi_not_ok,
        "flags": flags,
        "regressed": bool(flags),
        "tol": tol,
    }


def format_trend(t: Dict[str, Any]) -> str:
    """The human table: one row per lever with its delta history."""
    lines = [f"bench trend over {t['n_rounds']} round(s) "
             f"(ok: {t['ok_rounds']}, failed: {t['failed_rounds']}, "
             f"tol {t['tol'] * 100:.0f}%)"]
    if not t["levers"]:
        lines.append("  no headline data (every round failed?)")
    w = max((len(k) for k in t["levers"]), default=0)
    for key, v in sorted(t["levers"].items()):
        hist = " -> ".join(f"r{h['round']}:{h['value']:.4g}"
                           for h in v["history"])
        flag = " REGRESSED" if v["regressed"] else ""
        arrow = "v" if v["direction"] == "down" else "^"
        lines.append(
            f"  {key:<{w}} [{arrow}] {hist}  "
            f"best r{v['best_round']}:{v['best']:.4g}  "
            f"latest {v['vs_best_pct']:+.1f}% vs best{flag}")
    if t["multichip_rounds"]:
        lines.append(
            f"  multichip: {t['multichip_rounds']} round(s), "
            f"failed: {t['multichip_failed'] or 'none'}")
    lines.append("verdict: "
                 + ("REGRESSED " + ", ".join(t["flags"])
                    if t["regressed"] else "clean"))
    return "\n".join(lines)
