"""XLA trace annotations + the PhaseTimer.

`named_phase` labels TRACED code: it is `jax.named_scope`, whose names
flow into XLA op metadata, so a --profile-dir trace shows
"layer0/halo_exchange"-style phases instead of anonymous fusions.
`trace_span` labels HOST spans (`jax.profiler.TraceAnnotation`): a
no-op unless a trace is being captured, so it is safe on every
dispatch.

PhaseTimer is the host-side phase clock the epoch loop runs on —
the generalization of the reference-parity CommTimer
(helper/timer/comm_timer.py semantics, now a shim in utils/timer.py):

  - exception-safe: a span that raises still records its duration
    (try/finally around the yield), so a crashed epoch's partial
    timing reaches the crash telemetry;
  - re-entrant keys: repeated spans ACCUMULATE (durations) and count
    (counts) instead of raising — per-epoch keys no longer force a
    clear() discipline;
  - nesting: phases may nest freely; each records its own wall-clock;
  - optional trace annotation: phase(key, annotate=True) also opens a
    TraceAnnotation so profiler timelines show the same phase names
    the JSONL records use.

Both jax imports are lazy: PhaseTimer itself must work in jax-free
host processes.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict


def named_phase(name: str):
    """Name a traced-code region (forward/backward layers, halo
    exchange, gradient reduce): `with named_phase("layer0"): ...`."""
    import jax

    return jax.named_scope(name)


def trace_span(name: str):
    """Name a host-side span in the profiler timeline (step dispatch,
    eval harvest). No-op when no trace is active."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class PhaseTimer:
    def __init__(self):
        self._durs: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}

    @contextmanager
    def phase(self, key: str, annotate: bool = False):
        span = trace_span(key) if annotate else None
        t0 = time.perf_counter()
        if span is not None:
            span.__enter__()
        try:
            yield
        finally:
            if span is not None:
                span.__exit__(None, None, None)
            self._durs[key] = (self._durs.get(key, 0.0)
                               + time.perf_counter() - t0)
            self._counts[key] = self._counts.get(key, 0) + 1

    def durations(self) -> Dict[str, float]:
        """Accumulated seconds per key."""
        return dict(self._durs)

    def counts(self) -> Dict[str, int]:
        """Completed span count per key (mean = durations/counts)."""
        return dict(self._counts)

    def tot_time(self) -> float:
        return sum(self._durs.values())

    def clear(self) -> None:
        self._durs.clear()
        self._counts.clear()
