"""Always-on training-path span plane (schema v14 spans + tracesync).

The serving path got per-query distributed tracing in the fleet PR
(serve/tracing.py); this module gives the TRAINER the same treatment
so every multi-chip (or multi-process CPU-mesh) run self-measures
pipeline overlap, comm cost, and rank skew without a profiler capture
window.  Everything is host-side bookkeeping: no jax imports, no
effect on the compiled programs — the zero-recompile pins in
tests/test_trainspan.py hold with spans hot.

Span model (docs/OBSERVABILITY.md "Training traces"):

* One ``compute`` span per dispatched block — the REAL dispatch→
  harvest wall window, tagged (rank, generation, epoch, epochs).
* Once the trainer's one-shot standalone collective measurement
  (``Trainer.measure_comm``) lands, every block additionally gets a
  comm tail: one ``halo_exchange`` span per graph layer (standalone
  halo cost apportioned by wire bytes, tagged with bytes + dtype),
  one ``bgrad_return`` and one ``grad_reduce`` span — placed
  back-to-back ENDING at the harvest barrier, ``grad_reduce`` last.
  Blocks before the measurement gate carry compute spans only.
* ``checkpoint`` / ``eval`` spans bracket those host phases.

All spans for epoch E share the deterministic trace id ``train-e<E>``
— identical on every rank with zero coordination, so ``cli.timeline``
stitches cross-rank flows exactly as it does for serving spans.

Clock alignment: every rank's ``grad_reduce`` for epoch E ends at the
same collective barrier (the jit program cannot complete on any rank
until the reduce has), so each block also emits a contracted
``tracesync`` record anchoring that barrier in the rank's wall clock.
:func:`estimate_offsets` recovers per-rank clock offsets from those
anchors (median over epochs of each rank's deviation from the
cross-rank median) and :func:`fold_spans` uses the aligned clock for
straggler attribution.

Derived verdicts (:func:`fold_spans`, surfaced by obs/live.py,
obs/health.py and ``pipegcn-report``):

* ``overlap_spans`` — per-epoch MEASURED overlap fraction: the
  interval-union of comm spans covered by compute spans, the same
  math as ``obs/profiler.fold_trace`` but from always-on spans (the
  fraction of the measured comm cost the measured wall window
  absorbs; comm-bound epochs spill past the window start and read
  exposed).
* ``comm_wait_share_by_rank`` — exposed comm seconds / wall seconds.
* straggler attribution — which rank's compute window STARTED last at
  each dispatch boundary on the aligned clock, and by how much.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..serve.tracing import SpanWriter
from .profiler import _overlap_with_union, _union_intervals

#: comm-phase span ops (the trainer-side mirror of profiler.COMM_PHASES)
COMM_OPS = ("halo_exchange", "bgrad_return", "grad_reduce")
#: every op the training-span plane emits
TRAIN_OPS = ("compute",) + COMM_OPS + ("checkpoint", "eval")
_TRACE_PREFIX = "train-e"


def trace_id(epoch: int) -> str:
    """Deterministic cross-rank trace id for epoch `epoch` — the same
    string on every rank with zero coordination, which is what lets the
    timeline stitch flows across processes."""
    return f"{_TRACE_PREFIX}{int(epoch)}"


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class TrainSpanPlane:
    """Per-rank training-span emitter over the contracted span sink.

    Reuses the serving path's :class:`SpanWriter` (injectable clocks,
    thread-safe ids, wall-aligned t_start) with ``source`` set to the
    rank tag ``r<k>``. Span volume is a handful per dispatched block —
    always-on by design; ``--no-train-traces`` disables construction
    entirely."""

    def __init__(self, ml, rank: int = 0, generation: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 now: Callable[[], float] = time.time):
        self._ml = ml
        self.rank = int(rank)
        self.generation = int(generation)
        self._clock = clock
        self._now = now
        self.writer = SpanWriter(ml, clock=clock,
                                 source=f"r{int(rank)}", now=now)
        self.counts: Dict[str, int] = {}
        self.blocks = 0           # dispatched blocks span-covered
        self._costs = None        # standalone per-epoch medians, or None
        self._layer_bytes: Tuple[Tuple[int, int], ...] = ()
        self._dtype = "none"

    def clock(self) -> float:
        """The plane's monotonic clock — the trainer brackets its
        dispatch window with this so fake-clock tests stay exact."""
        return self._clock()

    # ---------------- comm arming -------------------------------------

    def set_comm(self, costs: Dict[str, float],
                 layer_bytes: Iterable[Tuple[int, int]],
                 dtype: str) -> None:
        """Arm the comm tail once ``Trainer.measure_comm()`` lands:
        `costs` holds the standalone per-epoch medians ({"comm",
        "reduce", "bgrad"} seconds), `layer_bytes` the per-graph-layer
        halo wire bytes used to apportion the halo cost, `dtype` the
        wire dtype tag. Until this is called blocks emit compute spans
        only (documented: the measurement gate fires a few epochs in)."""
        self._costs = {k: max(float(costs.get(k, 0.0)), 0.0)
                       for k in ("comm", "reduce", "bgrad")}
        self._layer_bytes = tuple((int(li), max(int(b), 0))
                                  for li, b in layer_bytes)
        self._dtype = str(dtype)

    @property
    def comm_armed(self) -> bool:
        return self._costs is not None

    # ---------------- emission ----------------------------------------

    def _emit(self, tid: str, op: str, t0: float, t1: float,
              status: str = "ok", **extra) -> None:
        extra.setdefault("rank", self.rank)
        extra.setdefault("generation", self.generation)
        self.counts[op] = self.counts.get(op, 0) + 1
        self.writer.emit(tid, op, t0, t1, status, **extra)

    def block(self, epoch: int, chunk: int, dur_s: float,
              t_end: Optional[float] = None) -> None:
        """Spans for one dispatched block of `chunk` epochs starting at
        epoch `epoch`, whose dispatch→harvest wall window measured
        `dur_s` seconds and ended at plane-clock `t_end` (defaults to
        now — call right after harvest). Also lands the block's
        ``tracesync`` barrier anchor."""
        if t_end is None:
            t_end = self._clock()
        tid = trace_id(epoch)
        dur_s = max(float(dur_s), 0.0)
        chunk = max(int(chunk), 1)
        comm_total = (sum(self._costs.values()) * chunk
                      if self._costs is not None else 0.0)
        # exposed comm: the slice of the standalone comm cost the wall
        # window could not have absorbed even at perfect overlap
        wait = max(comm_total - dur_s, 0.0)
        self._emit(tid, "compute", t_end - dur_s, t_end, epoch=epoch,
                   epochs=chunk, comm_wait_s=round(wait, 6))
        self.blocks += 1
        if self._ml is not None:
            # wall-clock barrier anchor (same clock->unix offset rule
            # as SpanWriter.emit, captured per record)
            self._ml.tracesync(self.rank, epoch,
                               t_end + (self._now() - self._clock()),
                               self.generation)
        if self._costs is None:
            return
        # comm tail, back-to-back ENDING at the harvest barrier:
        # halo layers in layer order, bgrad_return, grad_reduce last —
        # so grad_reduce's end IS the cross-rank alignment anchor
        cur = t_end
        d = self._costs["reduce"] * chunk
        self._emit(tid, "grad_reduce", cur - d, cur, epoch=epoch)
        cur -= d
        d = self._costs["bgrad"] * chunk
        self._emit(tid, "bgrad_return", cur - d, cur, epoch=epoch)
        cur -= d
        halo = self._costs["comm"] * chunk
        total_b = sum(b for _, b in self._layer_bytes)
        for li, b in reversed(self._layer_bytes):
            d = (halo * b / total_b if total_b > 0
                 else halo / max(len(self._layer_bytes), 1))
            self._emit(tid, "halo_exchange", cur - d, cur, epoch=epoch,
                       layer=li, wire_bytes=b * chunk,
                       dtype=self._dtype)
            cur -= d

    def eval_span(self, epoch: int, wait_s: float,
                  t_end: Optional[float] = None) -> None:
        """The eval harvest wait for epoch `epoch` (`wait_s` seconds
        ending at `t_end`, default now)."""
        if t_end is None:
            t_end = self._clock()
        self._emit(trace_id(epoch), "eval",
                   t_end - max(float(wait_s), 0.0), t_end, epoch=epoch)

    def checkpoint_span(self, epoch: int, dur_s: float,
                        t_end: Optional[float] = None,
                        status: str = "ok") -> None:
        """One checkpoint save window (epoch tag = the boundary's
        completed-epoch label)."""
        if t_end is None:
            t_end = self._clock()
        self._emit(trace_id(epoch), "checkpoint",
                   t_end - max(float(dur_s), 0.0), t_end,
                   status=status, epoch=epoch)

    def flush(self) -> None:
        """Hard-flush the sink: called from fault paths so the spans
        already emitted survive a crash or watchdog ``_hard_exit``
        (which also hard-flushes the shared sink in its own finally)."""
        if self._ml is not None:
            self._ml.hard_flush()


# ---------------- folding: records -> verdicts ------------------------


def train_spans(records: Iterable[dict]) -> List[dict]:
    """The training-path span records in `records` (merged streams ok)."""
    return [r for r in records
            if r.get("event") == "span" and r.get("op") in TRAIN_OPS
            and str(r.get("trace_id", "")).startswith(_TRACE_PREFIX)]


def _rank_of(rec: dict) -> int:
    r = rec.get("rank")
    if r is not None:
        return int(r)
    src = str(rec.get("source", ""))
    if src.startswith("r") and src[1:].isdigit():
        return int(src[1:])
    return 0


def _epoch_of(rec: dict) -> Optional[int]:
    e = rec.get("epoch")
    if e is not None:
        return int(e)
    tid = str(rec.get("trace_id", ""))
    if tid.startswith(_TRACE_PREFIX) and tid[len(_TRACE_PREFIX):].isdigit():
        return int(tid[len(_TRACE_PREFIX):])
    return None


def _interval(rec: dict) -> Tuple[float, float]:
    t0 = float(rec["t_start"])
    return (t0, t0 + float(rec["dur_ms"]) / 1e3)


def estimate_offsets(records: Iterable[dict]) -> Dict[int, float]:
    """Per-rank clock offsets from collective-boundary alignment.

    Every rank's epoch-E barrier anchor (``tracesync`` record, falling
    back to the ``grad_reduce`` span end) marks the same physical
    instant; a rank's offset is the median over shared epochs of its
    deviation from the cross-rank median anchor. Subtracting the
    offset aligns that rank's timestamps (``t_aligned = t - offset``).
    Ranks with no shared epoch (or a single-rank run) get offset 0."""
    anchors: Dict[int, Dict[int, float]] = {}  # epoch -> rank -> t
    for rec in records:
        if rec.get("event") == "tracesync":
            e, r = int(rec["epoch"]), int(rec["rank"])
            anchors.setdefault(e, {})[r] = float(rec["t_anchor"])
    if not anchors:  # fallback: reduce-span ends are the same barrier
        for rec in train_spans(records):
            if rec.get("op") != "grad_reduce":
                continue
            e = _epoch_of(rec)
            if e is None:
                continue
            anchors.setdefault(e, {})[_rank_of(rec)] = _interval(rec)[1]
    deltas: Dict[int, List[float]] = {}
    for e, by_rank in anchors.items():
        if len(by_rank) < 2:
            continue
        med = _median(list(by_rank.values()))
        for r, t in by_rank.items():
            deltas.setdefault(r, []).append(t - med)
    return {r: _median(ds) for r, ds in deltas.items()}


def fold_spans(records: Iterable[dict],
               offsets: Optional[Dict[int, float]] = None) -> dict:
    """Fold training spans (+ tracesync anchors) into the derived
    verdicts: measured overlap fraction, per-rank comm-wait share, and
    straggler attribution — the always-on counterpart of
    ``obs/profiler.fold_trace`` (same interval-union overlap math).

    Returns a plain dict (all keys present, Nones when undecidable):
    ``overlap_spans`` (comm-weighted mean fraction), ``per_epoch``
    ({epoch: {overlap, straggler_rank, gap_s}}), ``comm_wait_share_by_
    rank``, ``straggler_gap_s_by_rank``, ``straggler_max_gap_s``,
    ``straggler_rank``, ``counts``, ``offsets``."""
    records = list(records)
    spans = train_spans(records)
    if offsets is None:
        offsets = estimate_offsets(records)
    counts: Dict[str, int] = {}
    # (rank, epoch) -> op-partitioned intervals
    comp: Dict[Tuple[int, int], List[Tuple[float, float]]] = {}
    comm: Dict[Tuple[int, int], List[Tuple[float, float]]] = {}
    wall: Dict[int, float] = {}
    for rec in spans:
        op = rec["op"]
        counts[op] = counts.get(op, 0) + 1
        e = _epoch_of(rec)
        if e is None:
            continue
        key = (_rank_of(rec), e)
        iv = _interval(rec)
        if op == "compute":
            comp.setdefault(key, []).append(iv)
            wall[key[0]] = wall.get(key[0], 0.0) + (iv[1] - iv[0])
        elif op in COMM_OPS:
            comm.setdefault(key, []).append(iv)

    covered_total = comm_total = 0.0
    exposed: Dict[int, float] = {}
    per_epoch: Dict[int, dict] = {}
    for key, comm_iv in comm.items():
        union = _union_intervals(comp.get(key, []))
        cov = sum(_overlap_with_union(iv, union) for iv in comm_iv)
        tot = sum(b - a for a, b in comm_iv)
        covered_total += cov
        comm_total += tot
        exposed[key[0]] = exposed.get(key[0], 0.0) + max(tot - cov, 0.0)
        if tot > 0:
            pe = per_epoch.setdefault(key[1], {})
            frac = min(max(cov / tot, 0.0), 1.0)
            # per-epoch overlap: mean across the ranks seen so far
            n = pe.get("_n", 0)
            pe["overlap"] = ((pe.get("overlap", 0.0) * n + frac)
                             / (n + 1))
            pe["_n"] = n + 1

    # straggler attribution: aligned compute-window STARTs per epoch
    gaps: Dict[int, float] = {}
    for e in {k[1] for k in comp}:
        starts = {r: min(iv[0] for iv in comp[(r, e)])
                  - offsets.get(r, 0.0)
                  for r, ee in comp if ee == e}
        if len(starts) < 2:
            continue
        med = _median(list(starts.values()))
        worst, gap = max(((r, t - med) for r, t in starts.items()),
                         key=lambda x: x[1])
        pe = per_epoch.setdefault(e, {})
        pe["straggler_rank"] = worst
        pe["gap_s"] = round(gap, 6)
        for r, t in starts.items():
            gaps[r] = max(gaps.get(r, 0.0), t - med)
    for pe in per_epoch.values():
        pe.pop("_n", None)

    max_rank, max_gap = None, 0.0
    for r, g in gaps.items():
        if g > max_gap:
            max_rank, max_gap = r, g
    return {
        "overlap_spans": (min(max(covered_total / comm_total, 0.0), 1.0)
                          if comm_total > 0 else None),
        "per_epoch": {e: per_epoch[e] for e in sorted(per_epoch)},
        "comm_wait_share_by_rank": {
            r: min(max(exposed.get(r, 0.0) / w, 0.0), 1.0)
            for r, w in sorted(wall.items()) if w > 0},
        "comm_wait_s_by_rank": {r: round(s, 6)
                                for r, s in sorted(exposed.items())},
        "straggler_gap_s_by_rank": {r: round(max(g, 0.0), 6)
                                    for r, g in sorted(gaps.items())},
        "straggler_max_gap_s": (round(max_gap, 6)
                                if max_rank is not None else None),
        "straggler_rank": max_rank,
        "counts": counts,
        "offsets": {r: round(o, 6) for r, o in sorted(offsets.items())},
    }
