"""Canonical log-line formatters.

The reference-format lines are a byte-level compatibility surface:
tooling built against the reference's log discipline parses them, so
the exact format strings live HERE, in one place, and
tests/test_obs.py pins their output byte-for-byte. The trainer and
the CLI call these instead of scattering f-strings.

  reference_train_line   train.py:369-371 (Process/Epoch/Time/Comm/
                         Reduce/Loss)
  reference_eval_line    train.py:33-39 (inductive) / :54-60 (trans)
  epoch_line             this framework's own (non-reference) epoch
                         progress line
"""

from __future__ import annotations

from typing import Optional


def reference_train_line(rank: int, epoch: int, time_s: float,
                         comm_s: float, reduce_s: float,
                         loss: float) -> str:
    return ("Process {:03d} | Epoch {:05d} | Time(s) {:.4f} | "
            "Comm(s) {:.4f} | Reduce(s) {:.4f} | Loss {:.4f}"
            .format(rank, epoch, time_s, comm_s, reduce_s, loss))


def reference_eval_line(epoch: int, val_acc: float,
                        test_acc: Optional[float] = None) -> str:
    if test_acc is None:
        # reference evaluate_induc format (:33-39)
        return "Epoch {:05d} | Accuracy {:.2%}".format(epoch, val_acc)
    # reference evaluate_trans format (:54-60)
    return ("Epoch {:05d} | Validation Accuracy {:.2%} | "
            "Test Accuracy {:.2%}".format(epoch, val_acc, test_acc))


def epoch_line(epoch: int, time_s: float, loss: float,
               val_acc: Optional[float] = None) -> str:
    """The framework's own progress line (1-based epoch, like the
    pre-refactor f-strings in fit())."""
    s = f"Epoch {epoch:05d} | Time(s) {time_s:.4f} | Loss {loss:.4f}"
    if val_acc is not None:
        s += f" | Val {val_acc:.4f}"
    return s
