"""Black-box flight recorder: in-process breadcrumbs + crash dumps.

A bounded ring of host-side breadcrumbs (dispatch-boundary enter/exit,
collective phase enter/exit, checkpoint/ledger IO, RPC dispatch,
fallback/sentinel/scaler events, last harvested metrics) that costs one
amortized O(1) deque append per event and touches NOTHING inside traced
programs — `trace_counts()` is pinned unchanged by
tests/test_postmortem.py. When the process dies (fault, unhandled
exception, preemption, watchdog trip) or is asked via signal, the ring
is dumped atomically to ``blackbox-r<k>.json`` through the same
`write_text_atomic` / `FAULTY_IO` seams every other durable writer
uses, so chaos runs exercise the dump path too.

Three cooperating pieces:

  FlightRecorder  the ring itself + the dump; a process-wide singleton
                  (`get_recorder()` / `configure()`), on by default
                  (`PIPEGCN_FLIGHT=0` disables)
  capture_stacks  `faulthandler`-based all-thread stack capture,
                  annotated with the last-entered breadcrumb — the
                  watchdog deadline and SIGQUIT paths use it so a rank
                  blocked in a dead collective dies naming the wedged
                  phase/epoch instead of dying mute
  StallDetector   a daemon thread that watches breadcrumb progress and
                  dumps (once per stall episode, with stacks) when the
                  loop goes quiet for longer than its threshold WITHOUT
                  killing the process — the sub-watchdog forensics the
                  ``hang@E[:rN]:<ms>`` fault exercises

The postmortem engine (obs/postmortem.py, `pipegcn-debug explain`)
collects these dumps together with the metrics streams into a
root-cause verdict. Dump records validate as the schema-v11
``blackbox`` kind (obs/schema.py).
"""

from __future__ import annotations

import faulthandler
import json
import os
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 512

# dump reasons (free-form extras may refine them)
REASONS = ("watchdog", "exception", "preemption", "signal", "stall",
           "fault", "manual")


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


class FlightRecorder:
    """Bounded breadcrumb ring + atomic black-box dump.

    Thread-safe; append is O(1) on a ``deque(maxlen=capacity)`` so the
    steady-state cost is a lock acquire + dict build per breadcrumb —
    never a disk write, never a device op.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, rank: int = 0,
                 dump_dir: Optional[str] = None,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("PIPEGCN_FLIGHT", "1") != "0"
        self.capacity = int(capacity)
        self.rank = int(rank)
        self.dump_dir = dump_dir
        self.enabled = bool(enabled)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._open: List[Dict[str, Any]] = []   # enter/exit span stack
        self._last: Optional[Dict[str, Any]] = None
        self._progress_t = time.monotonic()
        self.dumps: List[str] = []              # paths written this process
        self._dump_failures = 0

    # ---- recording ----

    def crumb(self, kind: str, _progress: bool = True,
              **fields) -> Optional[Dict[str, Any]]:
        """Append one breadcrumb. Returns the record (None when the
        recorder is disabled). ``_progress=False`` records without
        resetting the stall clock — for the detector's own bookkeeping
        crumbs, which must not look like forward progress."""
        if not self.enabled:
            return None
        rec = {"kind": str(kind), "t": time.time()}
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            self._last = rec
            if _progress:
                self._progress_t = time.monotonic()
        return rec

    def enter(self, span: str, **fields) -> Optional[Dict[str, Any]]:
        """Breadcrumb ``<span>-enter`` + push onto the open-span stack
        (the stack is what annotates a hang: the innermost entry names
        the phase the process never exited)."""
        rec = self.crumb(span + "-enter", **fields)
        if rec is not None:
            with self._lock:
                self._open.append(rec)
        return rec

    def exit(self, span: str, **fields) -> Optional[Dict[str, Any]]:
        """Breadcrumb ``<span>-exit`` + pop the matching open span."""
        rec = self.crumb(span + "-exit", **fields)
        if rec is not None:
            with self._lock:
                for i in range(len(self._open) - 1, -1, -1):
                    if self._open[i]["kind"] == span + "-enter":
                        del self._open[i]
                        break
        return rec

    @contextmanager
    def span(self, name: str, **fields):
        """``with rec.span("collective", phase=...):`` enter/exit pair
        that survives exceptions (the exit crumb records them)."""
        self.enter(name, **fields)
        try:
            yield
        except BaseException as exc:
            self.exit(name, error=f"{type(exc).__name__}: {exc}"[:200])
            raise
        else:
            self.exit(name)

    # ---- inspection ----

    def crumbs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def last_crumb(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._last) if self._last is not None else None

    def open_spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._open]

    def annotation(self) -> Dict[str, Any]:
        """Compact hang context: the innermost open span (or the last
        crumb when nothing is open) — phase, epoch, ring distance, peer
        rank, whatever the instrumentation attached."""
        with self._lock:
            src = self._open[-1] if self._open else self._last
            return dict(src) if src is not None else {}

    def seconds_since_progress(self) -> float:
        with self._lock:
            return time.monotonic() - self._progress_t

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "capacity": self.capacity,
                "ring_depth": len(self._ring),
                "n_crumbs_total": self._seq,
                "dumps": len(self.dumps),
                "dump_failures": self._dump_failures,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._open.clear()
            self._last = None

    # ---- dumping ----

    def dump_path(self, directory: Optional[str] = None) -> str:
        d = directory or self.dump_dir or "."
        return os.path.join(d, f"blackbox-r{self.rank}.json")

    def dump(self, reason: str, directory: Optional[str] = None,
             stacks: Optional[str] = None,
             **extra) -> Optional[str]:
        """Write ``blackbox-r<k>.json`` atomically; returns the path,
        or None when the write failed (the failure NEVER propagates —
        a dump must not mask the fault it documents). ``stacks`` is a
        pre-captured all-thread stack text (see :func:`capture_stacks`);
        pass ``stacks=capture_stacks(self)`` on hang paths."""
        if not self.enabled:
            return None
        payload: Dict[str, Any] = {
            "event": "blackbox",
            "schema_version": _schema_version(),
            "rank": self.rank,
            "reason": str(reason),
            "time_unix": time.time(),
            "pid": os.getpid(),
            "crumbs": self.crumbs(),
            "last_crumb": self.last_crumb(),
            "open_spans": self.open_spans(),
            "annotation": self.annotation(),
            "stacks": stacks,
            "n_crumbs_total": self._seq,
        }
        for k, v in extra.items():
            payload.setdefault(k, _jsonable(v))
        path = self.dump_path(directory)
        try:
            from ..resilience.storage import write_text_atomic

            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            write_text_atomic(path, json.dumps(payload) + "\n",
                              fsync=True)
        except BaseException:  # noqa: BLE001 — never mask the fault
            self._dump_failures += 1
            return None
        self.dumps.append(path)
        return path


def _schema_version() -> int:
    try:
        from .schema import SCHEMA_VERSION
        return SCHEMA_VERSION
    except Exception:  # noqa: BLE001
        return -1


def capture_stacks(recorder: Optional[FlightRecorder] = None) -> str:
    """All-thread stack text via ``faulthandler.dump_traceback``
    (C-level: it works even while other threads hold locks or sit in
    blocked native calls), annotated with the recorder's last-entered
    breadcrumb so a wedged collective names its phase/epoch."""
    header = ""
    if recorder is not None:
        ann = recorder.annotation()
        if ann:
            ctx = ", ".join(f"{k}={ann[k]}" for k in sorted(ann)
                            if k not in ("t", "seq"))
            header = f"# last breadcrumb: {ctx}\n"
    fd, tmp = tempfile.mkstemp(prefix="pipegcn-stacks-", suffix=".txt")
    try:
        faulthandler.dump_traceback(file=fd, all_threads=True)
        os.lseek(fd, 0, os.SEEK_SET)
        chunks = []
        while True:
            b = os.read(fd, 65536)
            if not b:
                break
            chunks.append(b)
        text = b"".join(chunks).decode("utf-8", "replace")
    finally:
        os.close(fd)
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return header + text


class StallDetector:
    """Daemon thread: when no breadcrumb lands for ``threshold_s``,
    capture all-thread stacks and dump (reason="stall") ONCE per stall
    episode — the process keeps running, so a sub-watchdog stall (the
    ``hang@E:<ms>`` fault) leaves forensics without dying. A fresh
    breadcrumb re-arms the detector."""

    def __init__(self, recorder: FlightRecorder, threshold_s: float,
                 poll_s: Optional[float] = None,
                 directory: Optional[str] = None):
        self.recorder = recorder
        self.threshold_s = float(threshold_s)
        self.poll_s = float(poll_s) if poll_s else max(
            0.05, self.threshold_s / 4.0)
        self.directory = directory
        self.stalls = 0
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "StallDetector":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="pipegcn-stall-detector",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            age = self.recorder.seconds_since_progress()
            if age >= self.threshold_s:
                if not self._fired:
                    self._fired = True
                    self.stalls += 1
                    try:
                        stacks = capture_stacks(self.recorder)
                    except Exception:  # noqa: BLE001
                        stacks = None
                    self.recorder.crumb("stall-detected",
                                        _progress=False,
                                        stall_age_s=round(age, 3))
                    self.recorder.dump("stall", directory=self.directory,
                                       stacks=stacks,
                                       stall_age_s=round(age, 3))
            else:
                self._fired = False


# ---- process-wide singleton ----

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The process-wide recorder (created on first use, on by
    default)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def configure(rank: Optional[int] = None,
              dump_dir: Optional[str] = None,
              capacity: Optional[int] = None,
              enabled: Optional[bool] = None) -> FlightRecorder:
    """(Re)configure the singleton in place — instrumentation sites
    hold references through :func:`get_recorder`, so identity must
    survive configuration. A capacity change re-bounds the ring,
    keeping the newest crumbs."""
    rec = get_recorder()
    with rec._lock:
        if rank is not None:
            rec.rank = int(rank)
        if dump_dir is not None:
            rec.dump_dir = dump_dir
        if enabled is not None:
            rec.enabled = bool(enabled)
        if capacity is not None and int(capacity) != rec.capacity:
            rec.capacity = int(capacity)
            rec._ring = deque(rec._ring, maxlen=rec.capacity)
    return rec


def crumb(kind: str, **fields) -> Optional[Dict[str, Any]]:
    return get_recorder().crumb(kind, **fields)


def install_signal_dump(signum: int = signal.SIGQUIT) -> bool:
    """On-demand dump: ``kill -QUIT <pid>`` writes the black box (with
    stacks) and the process keeps running. Returns False when the
    handler could not be installed (non-main thread — e.g. under a
    test runner's worker — or an unsupported platform); callers treat
    that as a soft miss."""
    def _handler(_sig, _frm):
        rec = get_recorder()
        try:
            stacks = capture_stacks(rec)
        except Exception:  # noqa: BLE001
            stacks = None
        rec.crumb("signal-dump", signum=int(_sig))
        rec.dump("signal", stacks=stacks, signum=int(_sig))

    try:
        signal.signal(signum, _handler)
        return True
    except (ValueError, OSError, AttributeError):
        return False


def dump_blackbox(reason: str, directory: Optional[str] = None,
                  with_stacks: bool = False, **extra) -> Optional[str]:
    """Module-level convenience used by the crash paths (coord hard
    deadline, unhandled CLI exception, preemption): dump the singleton,
    optionally with all-thread stacks. Never raises."""
    rec = get_recorder()
    stacks = None
    if with_stacks:
        try:
            stacks = capture_stacks(rec)
        except Exception:  # noqa: BLE001
            stacks = None
    return rec.dump(reason, directory=directory, stacks=stacks, **extra)
