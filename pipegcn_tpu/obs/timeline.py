"""Cross-rank Perfetto/Chrome-trace timelines from metrics JSONL.

Multi-host runs leave one metrics JSONL stream per rank (PR 3's
fault/recovery records are rank-attributed for exactly this reason).
Reading N streams side by side in a text editor is how desync bugs
hide; this module merges them into ONE ``trace.json`` readable in
Perfetto (ui.perfetto.dev) or chrome://tracing:

  - each rank is a trace *process* (pid = rank), its epochs a track of
    ``X`` (complete) slices; loss and grad-norm ride as ``C`` counter
    tracks per rank;
  - epochs are aligned at dispatch boundaries: when every epoch record
    carries the ``time_unix`` extra (the MetricsLogger stamps it), real
    wall-clock alignment is used; otherwise epoch e of every rank is
    aligned at max-over-ranks of the rank-local cumulative step time —
    the lockstep boundary the SPMD program enforces;
  - fault / recovery / preemption records appear as instant events on
    the owning rank's track, so a chaos drill's kill -> detect ->
    checkpoint -> resume sequence reads as a single picture;
  - ``profile`` records (obs/profiler.py) contribute per-phase span
    estimates inside their capture window;
  - ``staleness`` records ride a counter track (max relative drift);
  - ``serving`` windows ride counter tracks (qps / p50 / queue depth /
    shed), and fleet / membership / stream / soak / alert records are
    instant events on an "events" track — all aligned on their
    ``time_unix`` stamps;
  - ``span`` records (the --trace-sample-rate serving path,
    docs/SERVING.md) become ``X`` slices on a "spans" track, and every
    trace id shared across streams is stitched into a Perfetto *flow*
    (``s``/``t``/``f`` events) so one query reads as an arrow chain
    router -> replica -> engine across processes;
  - training-path spans (``train-e<E>`` trace ids, obs/trainspan.py)
    ride a dedicated per-rank "train" track on the tracesync-ALIGNED
    clock (per-rank offsets from the grad_reduce barrier anchors), and
    each epoch's matching collective spans (grad_reduce /
    bgrad_return / per-layer halo_exchange) are stitched into
    cross-rank flows — the rank-skew picture the straggler
    attribution quantifies.

Chrome-trace JSON contract kept deliberately strict (the timeline test
pins it): object with "traceEvents" (list) + "displayTimeUnit"; every
non-metadata event has numeric ts >= 0 (microseconds) and X events a
numeric dur >= 0; events are emitted sorted by ts.
"""

from __future__ import annotations

import json
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .trainspan import COMM_OPS, TRAIN_OPS, estimate_offsets

# wall-clock-stamped record kinds rendered beyond the training tracks
_WALL_KINDS = ("serving", "fleet", "membership", "stream", "soak",
               "alert")
_TRAIN_TRACE = "train-e"


def _scalar_args(r: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in r.items() if k != "event"
            and isinstance(v, (int, float, str, bool))}


def _rank_of(records: Sequence[Dict[str, Any]], fallback: int) -> int:
    for r in records:
        if isinstance(r.get("rank"), int):
            return r["rank"]
    return fallback


def _epoch_starts(epochs: List[Dict[str, Any]]
                  ) -> Tuple[Dict[int, float], bool]:
    """{epoch -> start seconds} for one rank + whether real wall-clock
    timestamps were available. Records are written at dispatch END, so
    start = time_unix - step_time_s when stamped; the fallback is the
    rank-local cumulative sum of step times."""
    stamped = all(isinstance(r.get("time_unix"), (int, float))
                  for r in epochs) and bool(epochs)
    starts: Dict[int, float] = {}
    if stamped:
        for r in epochs:
            starts[r["epoch"]] = (float(r["time_unix"])
                                  - float(r.get("step_time_s", 0.0)))
        return starts, True
    t = 0.0
    for r in sorted(epochs, key=lambda x: x.get("epoch", 0)):
        starts[r["epoch"]] = t
        t += float(r.get("step_time_s", 0.0))
    return starts, False


def build_timeline(rank_records: Sequence[Tuple[int, Sequence[Dict[str, Any]]]]
                   ) -> Dict[str, Any]:
    """Merge per-rank metrics records into one Chrome-trace object.

    `rank_records`: [(rank, records), ...] — rank ids need not be
    contiguous; duplicate ranks are kept apart by their input order."""
    events: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []

    # training-span clock alignment (obs/trainspan.py): per-rank
    # offsets estimated from the tracesync / grad_reduce barrier
    # anchors; every train span renders (and stitches) on the aligned
    # clock t - offset
    train_off = estimate_offsets(
        [r for _, records in rank_records for r in records])

    def _train_aligned(rec: Dict[str, Any], rank: int,
                       t: float) -> float:
        r = rec.get("rank")
        return t - train_off.get(r if isinstance(r, int) else rank, 0.0)

    # pass 1: per-rank epoch start maps; establish the global alignment
    per_rank = []
    any_unstamped = False
    wall_min: Optional[float] = None
    for order, (rank, records) in enumerate(rank_records):
        records = list(records)
        epochs = [r for r in records if r.get("event") == "epoch"
                  and isinstance(r.get("epoch"), int)]
        starts, stamped = _epoch_starts(epochs)
        any_unstamped |= not stamped
        per_rank.append((order, rank, records, epochs, starts, stamped))
        for r in records:
            t = (r.get("t_start") if r.get("event") == "span"
                 else r.get("time_unix")
                 if r.get("event") in _WALL_KINDS else None)
            if isinstance(t, (int, float)):
                if r.get("event") == "span" and str(
                        r.get("trace_id", "")).startswith(_TRAIN_TRACE):
                    t = _train_aligned(r, rank, float(t))
                wall_min = t if wall_min is None else min(wall_min, t)

    if any_unstamped:
        # lockstep alignment: every rank's epoch e starts at the max of
        # the rank-local cumulative starts (the dispatch boundary the
        # slowest rank sets); re-map every rank onto that shared axis
        all_epochs = sorted({e for _, _, _, eps, st, _ in per_rank
                             for e in st})
        shared: Dict[int, float] = {}
        t = 0.0
        for e in all_epochs:
            t = max([t] + [st[e] for _, _, _, _, st, _ in per_rank
                           if e in st])
            shared[e] = t
            durs = [float(r.get("step_time_s", 0.0))
                    for _, _, _, eps, _, _ in per_rank
                    for r in eps if r.get("epoch") == e]
            t += max(durs, default=0.0)
        per_rank = [(o, rk, recs, eps, {e: shared[e] for e in st}, False)
                    for o, rk, recs, eps, st, _ in per_rank]
        t0 = 0.0
        # the shared lockstep axis is synthetic; wall-stamped kinds
        # (serving/fleet/span/...) get their own zero so a mixed file
        # still renders with small timestamps on both axes
        wall_ref = wall_min if wall_min is not None else 0.0
    else:
        t0 = min((min(st.values()) for _, _, _, _, st, _ in per_rank
                  if st), default=0.0)
        if wall_min is not None:
            # wall-stamped kinds may precede the first epoch dispatch
            t0 = min(t0, wall_min)
        wall_ref = t0

    def us(t: float) -> float:
        return round(max(t - t0, 0.0) * 1e6, 3)

    def wus(t: float) -> float:
        return round(max(t - wall_ref, 0.0) * 1e6, 3)

    span_sites: Dict[str, List[Tuple[float, int, int]]] = {}

    for order, rank, records, epochs, starts, stamped in per_rank:
        pid = rank if rank >= 0 else order
        meta.append({"ph": "M", "pid": pid, "name": "process_name",
                     "args": {"name": f"rank {rank}"}})
        meta.append({"ph": "M", "pid": pid, "name": "process_sort_index",
                     "args": {"sort_index": pid}})
        for tid, tname in ((0, "epochs"), (1, "faults"), (2, "profile")):
            meta.append({"ph": "M", "pid": pid, "tid": tid,
                         "name": "thread_name", "args": {"name": tname}})
            meta.append({"ph": "M", "pid": pid, "tid": tid,
                         "name": "thread_sort_index",
                         "args": {"sort_index": tid}})

        for r in epochs:
            e = r["epoch"]
            ts = us(starts[e])
            dur = round(float(r.get("step_time_s", 0.0)) * 1e6, 3)
            events.append({
                "ph": "X", "pid": pid, "tid": 0, "ts": ts, "dur": dur,
                "name": f"epoch {e}",
                "args": {k: r[k] for k in
                         ("loss", "grad_norm", "staleness_age",
                          "halo_bytes") if k in r},
            })
            if isinstance(r.get("loss"), (int, float)):
                events.append({"ph": "C", "pid": pid, "tid": 0,
                               "ts": ts + dur, "name": "loss",
                               "args": {"loss": float(r["loss"])}})

        def _epoch_ts(ep: Optional[Any], end: bool = False) -> float:
            """Best-effort ts for a record anchored to an epoch index."""
            if isinstance(ep, int) and ep in starts:
                base = starts[ep]
                if end:
                    rec = next((x for x in epochs if x["epoch"] == ep),
                               None)
                    base += float(rec.get("step_time_s", 0.0)) if rec \
                        else 0.0
                return us(base)
            if isinstance(ep, int) and starts:
                lo, hi = min(starts), max(starts)
                if ep <= lo:
                    return us(starts[lo])
                last = next(x for x in epochs if x["epoch"] == hi)
                return us(starts[hi]
                          + float(last.get("step_time_s", 0.0)))
            return 0.0

        extra_tids: set = set()

        def _wall_ts(r: Dict[str, Any]) -> float:
            t = r.get("time_unix")
            return wus(float(t)) if isinstance(t, (int, float)) else 0.0

        for r in records:
            ev = r.get("event")
            if ev in ("fault", "recovery"):
                ts = r.get("time_unix")
                ts = (us(float(ts)) if stamped
                      and isinstance(ts, (int, float))
                      else _epoch_ts(r.get("epoch"), end=True))
                events.append({
                    "ph": "i", "pid": pid, "tid": 1, "ts": ts, "s": "t",
                    "name": f"{ev}:{r.get('kind', '?')}",
                    "args": {k: v for k, v in r.items()
                             if k not in ("event",)
                             and isinstance(v, (int, float, str, bool))},
                })
            elif ev == "staleness":
                md = r.get("max_rel_drift")
                if isinstance(md, (int, float)):
                    events.append({
                        "ph": "C", "pid": pid, "tid": 1,
                        "ts": _epoch_ts(r.get("epoch"), end=True),
                        "name": "staleness_rel_drift",
                        "args": {"max_rel_drift": float(md)}})
            elif ev == "serving":
                ts = _wall_ts(r)
                for key in ("qps", "p50_ms", "p99_ms", "queue_depth",
                            "shed"):
                    v = r.get(key)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        extra_tids.add(3)
                        events.append({
                            "ph": "C", "pid": pid, "tid": 3, "ts": ts,
                            "name": f"serving_{key}",
                            "args": {key: float(v)}})
            elif ev in ("fleet", "membership", "stream", "soak",
                        "alert"):
                if ev == "fleet":
                    name = f"fleet:{r.get('kind', '?')}"
                elif ev == "membership":
                    name = f"membership:g{r.get('generation', '?')}" \
                           f" ({r.get('trigger', '?')})"
                elif ev == "stream":
                    name = f"stream:seq{r.get('seq', '?')}"
                elif ev == "soak":
                    name = f"soak:ep{r.get('episode', '?')}:" \
                           f"{r.get('verdict', '?')}"
                else:
                    name = f"alert:{r.get('state', '?')}:" \
                           f"{r.get('rule', '?')}"
                extra_tids.add(4)
                events.append({
                    "ph": "i", "pid": pid, "tid": 4, "ts": _wall_ts(r),
                    "s": "t", "name": name, "args": _scalar_args(r)})
            elif ev == "span":
                tid_ = r.get("trace_id")
                t_start = r.get("t_start")
                dur_ms = r.get("dur_ms")
                if not (isinstance(tid_, str)
                        and isinstance(t_start, (int, float))
                        and isinstance(dur_ms, (int, float))):
                    continue
                is_train = (tid_.startswith(_TRAIN_TRACE)
                            and r.get("op") in TRAIN_OPS)
                if is_train:
                    # dedicated per-rank "train" track on the ALIGNED
                    # clock; flows stitch each epoch's MATCHING
                    # collective spans across ranks (per op, per halo
                    # layer), not every span of the epoch
                    ts = wus(_train_aligned(r, rank, float(t_start)))
                    track = 6
                    if r.get("op") in COMM_OPS:
                        fkey = f"{tid_}|{r['op']}"
                        if isinstance(r.get("layer"), int):
                            fkey += f"|L{r['layer']}"
                        span_sites.setdefault(fkey, []).append(
                            (ts, pid, track))
                else:
                    ts = wus(float(t_start))
                    track = 5
                    span_sites.setdefault(tid_, []).append(
                        (ts, pid, track))
                extra_tids.add(track)
                events.append({
                    "ph": "X", "pid": pid, "tid": track, "ts": ts,
                    "dur": round(max(float(dur_ms), 0.0) * 1e3, 3),
                    "name": str(r.get("op", "span")),
                    "args": _scalar_args(r)})
            elif ev == "profile":
                a = r.get("epoch_start")
                b = r.get("epoch_end")
                ts = _epoch_ts(a if isinstance(a, int) else None)
                te = _epoch_ts(b - 1 if isinstance(b, int) else None,
                               end=True)
                phases = r.get("phases") or {}
                cursor = ts
                span = max(te - ts, 0.0)
                tot = sum(v for v in phases.values()
                          if isinstance(v, (int, float))) or 1.0
                for name, sec in sorted(phases.items()):
                    if not isinstance(sec, (int, float)) or sec <= 0:
                        continue
                    dur = round(span * sec / tot, 3) if span else \
                        round(sec * 1e6, 3)
                    events.append({"ph": "X", "pid": pid, "tid": 2,
                                   "ts": round(cursor, 3), "dur": dur,
                                   "name": name,
                                   "args": {"device_s": sec}})
                    cursor += dur

        for tid, tname in ((3, "serving"), (4, "events"), (5, "spans"),
                           (6, "train")):
            if tid in extra_tids:
                meta.append({"ph": "M", "pid": pid, "tid": tid,
                             "name": "thread_name",
                             "args": {"name": tname}})
                meta.append({"ph": "M", "pid": pid, "tid": tid,
                             "name": "thread_sort_index",
                             "args": {"sort_index": tid}})

    # flow stitching: every trace id seen in >1 span slice becomes a
    # Perfetto flow (s -> t... -> f) binding the slices it rode —
    # submit -> rpc -> replica -> engine reads as one arrow chain
    for trace_id, sites in span_sites.items():
        if len(sites) < 2:
            continue
        sites.sort()
        fid = zlib.crc32(trace_id.encode("utf-8"))
        # train-collective flow keys carry "|" (trace|op[|layer]);
        # serving flows stay the plain per-query chain
        cat = "collective" if "|" in trace_id else "query"
        for i, (ts, pid, tid) in enumerate(sites):
            ph = "s" if i == 0 else ("f" if i == len(sites) - 1
                                     else "t")
            fe = {"ph": ph, "pid": pid, "tid": tid, "ts": ts,
                  "cat": cat, "name": cat, "id": fid}
            if ph == "f":
                fe["bp"] = "e"
            events.append(fe)

    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0),
                               e.get("tid", 0)))
    return {"displayTimeUnit": "ms", "traceEvents": meta + events}


def write_timeline(obj: Dict[str, Any], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f)


__all__ = ["build_timeline", "write_timeline"]
