"""Live telemetry aggregation (docs/OBSERVABILITY.md "Live
monitoring").

Everything PRs 1–14 built writes JSONL streams that were only readable
post-hoc: training metrics, elastic per-(generation, member) files
(``{stem}.g<G>.m<M>.jsonl``), the supervisor's membership ledger,
per-replica fleet streams, the soak harness, the queued TPU window.
This module watches them ALL while they are still being written:

  discover_streams(target)   run directory / stem / file -> the
                             generation-ordered stream list, re-globbed
                             on every poll so files appearing mid-run
                             (a new generation, a relaunched replica
                             incarnation) join the tail set live
  TailReader                 one stream's incremental reader: consumes
                             only newline-terminated lines, so a torn
                             final line (a writer killed mid-write —
                             the PR-14 tolerance) is simply not yet
                             visible; truncation rewinds
  LiveAggregator             folds every stream's records into rolling
                             in-memory state keyed by (source, kind) —
                             the thing /metrics, /health, the alert
                             engine (obs/health.py) and --follow read
  merge_streams(paths)       one-shot deduped generation-ordered merge
                             of finished streams — shared with the
                             report CLI's run-directory mode

Host-side and jax-free, like the MetricsLogger it watches.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from .schema import validate_record

# elastic per-(generation, member) metrics files (resilience/elastic.
# _member_metrics_path): {stem}.g<G>.m<M>.jsonl
_GEN_RE = re.compile(r"\.g(\d+)\.m(\d+)\.jsonl$")
# fleet replica streams (cli/fleet._replica_main):
# replica-m<rid>-i<incarnation>-metrics.jsonl
_REPLICA_RE = re.compile(r"replica-m(\d+)-i(\d+)-metrics\.jsonl$")


def stream_sort_key(path: str) -> Tuple[int, int, str]:
    """Generation-ordered: whole-run streams (no .g<G>.m<M> suffix)
    first, then per-generation files by (generation, member); replica
    streams order by (incarnation, replica). Name breaks ties so the
    merge is deterministic."""
    base = os.path.basename(path)
    m = _GEN_RE.search(base)
    if m:
        return (int(m.group(1)), int(m.group(2)), base)
    m = _REPLICA_RE.search(base)
    if m:
        return (int(m.group(2)), int(m.group(1)), base)
    return (-1, -1, base)


def source_name(path: str, root: Optional[str] = None) -> str:
    """Short stable stream key for state/labels: the path relative to
    the watched root (or the basename), without the .jsonl suffix."""
    if root and os.path.isdir(root):
        try:
            rel = os.path.relpath(path, root)
        except ValueError:
            rel = os.path.basename(path)
        if not rel.startswith(".."):
            path = rel
        else:
            path = os.path.basename(path)
    else:
        path = os.path.basename(path)
    return path[:-6] if path.endswith(".jsonl") else path


def discover_streams(target: str) -> List[str]:
    """Every metrics JSONL stream a target names, generation-ordered.

    `target` may be a run DIRECTORY (all ``*.jsonl`` under it,
    recursively — per-generation files, membership ledger, replica
    streams, window.jsonl as they appear), a single FILE, or a metrics
    STEM (``foo`` or ``foo.jsonl`` matching ``foo.jsonl`` +
    ``foo.g*.m*.jsonl`` + a membership ledger beside it)."""
    target = os.fspath(target)
    if os.path.isdir(target):
        paths = glob.glob(os.path.join(target, "**", "*.jsonl"),
                          recursive=True)
    elif os.path.isfile(target) and not _stem_siblings(target):
        paths = [target]
    else:
        stem = target[:-6] if target.endswith(".jsonl") else target
        paths = []
        if os.path.isfile(stem + ".jsonl"):
            paths.append(stem + ".jsonl")
        paths += _stem_siblings(stem + ".jsonl")
        if paths:
            # the elastic supervisor's ledger lives in its coord dir
            # next to the run: pick up membership.jsonl one level
            # around the stem (only for stems that matched something —
            # a typo'd path must not adopt an unrelated ledger)
            d = os.path.dirname(os.path.abspath(stem)) or "."
            paths += glob.glob(os.path.join(d, "membership.jsonl"))
            paths += glob.glob(os.path.join(d, "*", "membership.jsonl"))
    return sorted(set(paths), key=stream_sort_key)


def _stem_siblings(path: str) -> List[str]:
    """Per-generation files belonging to a base metrics path."""
    if not path.endswith(".jsonl"):
        return []
    return glob.glob(glob.escape(path[:-6]) + ".g*.m*.jsonl")


class TailReader:
    """Incremental reader of one JSONL stream.

    Only newline-terminated lines are consumed: a torn final line (the
    writer died mid-write, or we raced its flush) stays unread until
    its newline lands — the live-follow version of the PR-14 torn-line
    tolerance. A malformed line that IS newline-terminated is counted
    (`n_malformed`) and skipped, never fatal. A shrink of the file
    (rotation/truncation) rewinds to offset 0."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.n_records = 0
        self.n_malformed = 0

    def poll(self, final: bool = False) -> List[Dict[str, Any]]:
        """New complete records since the last poll. With
        ``final=True`` (one-shot reads of finished files) a parseable
        unterminated tail is included too."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self.offset:  # truncated/rotated underneath us
            self.offset = 0
        if size == self.offset:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                buf = f.read(size - self.offset)
        except OSError:
            return []
        end = buf.rfind(b"\n")
        if end < 0:
            if not final:
                return []  # only a torn tail so far
            chunk = buf
            self.offset += len(buf)
        else:
            chunk = buf if final else buf[:end + 1]
            self.offset += len(buf) if final else end + 1
        recs = []
        for raw in chunk.split(b"\n"):
            raw = raw.strip()
            if not raw:
                continue
            try:
                recs.append(json.loads(raw.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                self.n_malformed += 1
        self.n_records += len(recs)
        return recs


def read_stream(path: str) -> List[Dict[str, Any]]:
    """Tolerant one-shot read: complete lines plus a parseable tail;
    malformed lines are skipped (contrast read_metrics, which raises —
    the strict contract for single finished files)."""
    return TailReader(path).poll(final=True)


def merge_streams(paths) -> List[Dict[str, Any]]:
    """Deduped, generation-ordered merge of whole streams (the report
    CLI's run-directory/stem mode shares this with the aggregator).
    Order: streams by :func:`stream_sort_key`, records in file order
    within each. Dedup is by exact record content — the same record
    reachable through two discovered paths (symlinked dirs, a ledger
    copied into the run dir) folds to one."""
    out: List[Dict[str, Any]] = []
    seen = set()
    for p in sorted(set(os.fspath(p) for p in paths),
                    key=stream_sort_key):
        for rec in read_stream(p):
            key = json.dumps(rec, sort_keys=True)
            if key in seen:
                continue
            seen.add(key)
            out.append(rec)
    return out


class LiveAggregator:
    """Rolling state over every stream of a live run.

    ``poll()`` re-discovers streams, tail-reads each, schema-validates
    every record (invalid ones are counted, kept out of state, never
    fatal) and folds them into:

      state[(source, kind)]   latest record of that kind per stream
      counts[(source, kind)]  how many arrived
      fault_counts[kind] / recovery_counts[kind]   run-wide
      shed_by_reason[reason]  run-wide shed row totals
      autoscale_counts[action]  run-wide scale-decision totals
      integrity_counts[outcome]  run-wide integrity-check totals
      trainspan()            span-derived training verdicts (measured
                             overlap fraction, per-rank comm-wait,
                             straggler attribution) folded from the
                             bounded train-span/tracesync buffers
                             (obs/trainspan.py fold_spans)
      quarantined            members with a standing SDC quarantine
      last_seen[source]       clock time a record last ARRIVED — the
                              silent-source alert's input
      epoch_times[source]     recent step_time_s history (regression
                              rule input, bounded window)

    The clock is injectable so alert-horizon tests run on a fake."""

    HISTORY = 64  # epoch-time history per source (regression window)
    # bounded train-span/tracesync buffer: at ~5 spans + 1 anchor per
    # rank per dispatched block this covers hundreds of recent epochs,
    # and the overlap/straggler verdicts are about the RECENT run
    # anyway (the report CLI folds whole streams post-hoc)
    SPAN_HISTORY = 4096

    def __init__(self, target: str, validate: bool = True,
                 clock=time.time):
        self.target = os.fspath(target)
        self._validate = validate
        self._clock = clock
        self.readers: Dict[str, TailReader] = {}
        self.state: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.counts: Dict[Tuple[str, str], int] = {}
        self.fault_counts: Dict[str, int] = {}
        self.recovery_counts: Dict[str, int] = {}
        self.shed_by_reason: Dict[str, int] = {}
        # autoscale decision counts keyed by action (scale-up |
        # scale-down | refuse) — the exporter's
        # pipegcn_autoscale_decisions_total{direction} input
        self.autoscale_counts: Dict[str, int] = {}
        # integrity-plane check outcomes (ok | mismatch) — the
        # exporter's pipegcn_integrity_checks_total{outcome} input
        self.integrity_counts: Dict[str, int] = {}
        # members with a standing SDC quarantine: added on a
        # quarantine-request fault record, removed when a later
        # membership assignment seats the member again (the operator's
        # explicit rejoin cleared the marker)
        self.quarantined: set = set()
        self.last_seen: Dict[str, float] = {}
        self.epoch_times: Dict[str, List[float]] = {}
        # training-path span plane (obs/trainspan.py): bounded raw
        # buffers folded on demand by trainspan()
        self._train_spans: List[Dict[str, Any]] = []
        self._tracesync: List[Dict[str, Any]] = []
        self.n_records = 0
        self.n_invalid = 0
        self.schema_version: Optional[int] = None
        # black-box flight-recorder dumps (obs/flight.py) are JSON
        # FILES, not stream records — counted by glob each poll so the
        # exporter's pipegcn_blackbox_dumps_total moves the moment a
        # rank dumps, even if no metrics stream mirrors it
        self.n_blackbox_dumps = 0

    # ---------------- ingestion ---------------------------------------

    def poll(self) -> int:
        """One aggregation step; returns how many records arrived."""
        n = 0
        root = self.target if os.path.isdir(self.target) else None
        for path in discover_streams(self.target):
            r = self.readers.get(path)
            if r is None:
                r = self.readers[path] = TailReader(path)
            src = source_name(path, root)
            for rec in r.poll():
                self._fold(src, rec)
                n += 1
        if root is not None:
            self.n_blackbox_dumps = len(glob.glob(
                os.path.join(root, "**", "blackbox-r*.json"),
                recursive=True))
        return n

    def _fold(self, source: str, rec: Dict[str, Any]) -> None:
        self.n_records += 1
        self.last_seen[source] = self._clock()
        if self._validate:
            try:
                validate_record(rec)
            except ValueError:
                self.n_invalid += 1
                return
        kind = rec.get("event")
        if not isinstance(kind, str):
            self.n_invalid += 1
            return
        key = (source, kind)
        self.state[key] = rec
        self.counts[key] = self.counts.get(key, 0) + 1
        if kind == "run":
            sv = rec.get("schema_version")
            if isinstance(sv, int):
                self.schema_version = sv
        elif kind == "epoch":
            hist = self.epoch_times.setdefault(source, [])
            st = rec.get("step_time_s")
            if isinstance(st, (int, float)):
                hist.append(float(st))
                del hist[:-self.HISTORY]
        elif kind == "fault":
            k = str(rec.get("kind"))
            self.fault_counts[k] = self.fault_counts.get(k, 0) + 1
            if k == "quarantine-request" and isinstance(
                    rec.get("member"), int):
                self.quarantined.add(rec["member"])
        elif kind == "integrity":
            o = str(rec.get("outcome"))
            self.integrity_counts[o] = (
                self.integrity_counts.get(o, 0) + 1)
        elif kind == "membership":
            asg = rec.get("assignment")
            if isinstance(asg, dict):
                seated = {m for m in asg.values()
                          if isinstance(m, int)}
                self.quarantined -= seated
        elif kind == "recovery":
            k = str(rec.get("kind"))
            self.recovery_counts[k] = self.recovery_counts.get(k, 0) + 1
        elif kind == "autoscale":
            a = str(rec.get("action"))
            self.autoscale_counts[a] = self.autoscale_counts.get(a, 0) + 1
        elif kind == "span":
            tid = rec.get("trace_id")
            if isinstance(tid, str) and tid.startswith("train-e"):
                self._train_spans.append(rec)
                del self._train_spans[:-self.SPAN_HISTORY]
        elif kind == "tracesync":
            self._tracesync.append(rec)
            del self._tracesync[:-self.SPAN_HISTORY]
        elif kind == "serving":
            by = rec.get("shed_by_reason")
            if isinstance(by, dict):
                for reason, rows in by.items():
                    if isinstance(rows, int):
                        self.shed_by_reason[reason] = (
                            self.shed_by_reason.get(reason, 0) + rows)

    # ---------------- views -------------------------------------------

    def sources(self) -> List[str]:
        return sorted(self.last_seen)

    def latest(self, kind: str) -> Dict[str, Dict[str, Any]]:
        """{source: latest record} for one record kind."""
        return {s: r for (s, k), r in self.state.items() if k == kind}

    def silent_for(self, source: str) -> float:
        """Seconds since `source` last produced a record."""
        return max(self._clock() - self.last_seen.get(source, 0.0), 0.0)

    def trainspan(self) -> Optional[Dict[str, Any]]:
        """Span-derived training verdicts over the recent buffer
        (obs/trainspan.fold_spans): measured overlap fraction, per-rank
        comm-wait, straggler attribution on the aligned clock. None
        until any train span has arrived."""
        if not self._train_spans:
            return None
        from .trainspan import fold_spans
        return fold_spans(self._train_spans + self._tracesync)

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict rollup for /health and --follow: per-source ages
        and per-kind latest highlights."""
        now = self._clock()
        per_source = {}
        for src in self.sources():
            kinds = {k: self.counts[(s, k)]
                     for (s, k) in self.counts if s == src}
            per_source[src] = {
                "age_s": round(now - self.last_seen[src], 3),
                "records": sum(kinds.values()),
                "kinds": kinds,
            }
        epochs = self.latest("epoch")
        serving = self.latest("serving")
        membership = self.latest("membership")
        diagnosis = self.latest("diagnosis")
        snap: Dict[str, Any] = {
            "target": self.target,
            "n_streams": len(self.readers),
            "n_records": self.n_records,
            "n_invalid": self.n_invalid,
            "n_malformed": sum(r.n_malformed
                               for r in self.readers.values()),
            "schema_version": self.schema_version,
            "n_blackbox_dumps": self.n_blackbox_dumps,
            "sources": per_source,
            "fault_counts": dict(self.fault_counts),
            "recovery_counts": dict(self.recovery_counts),
            "shed_by_reason": dict(self.shed_by_reason),
            "autoscale_counts": dict(self.autoscale_counts),
            "integrity_counts": dict(self.integrity_counts),
            "quarantined_members": sorted(self.quarantined),
        }
        if diagnosis:
            # the latest postmortem verdict per stream (obs/
            # postmortem.py) — what `monitor --once` surfaces
            snap["diagnosis"] = {
                s: {"verdict": r.get("verdict"),
                    "confidence": r.get("confidence"),
                    "deterministic": r.get("deterministic")}
                for s, r in diagnosis.items()}
        if epochs:
            snap["train"] = {
                s: {k: r.get(k) for k in
                    ("epoch", "step_time_s", "loss", "grad_norm",
                     "halo_bytes", "staleness_age")}
                for s, r in epochs.items()}
        if serving:
            snap["serving"] = {
                s: {k: r.get(k) for k in
                    ("qps", "p50_ms", "p95_ms", "p99_ms", "queue_depth",
                     "shed", "staleness_age", "param_generation",
                     "param_staleness")}
                for s, r in serving.items()}
        ts = self.trainspan()
        if ts is not None:
            # the live pipeline-overlap verdict + straggler attribution
            # (docs/OBSERVABILITY.md "Training traces")
            snap["trainspan"] = {
                "overlap_spans": ts["overlap_spans"],
                "comm_wait_share_by_rank": ts["comm_wait_share_by_rank"],
                "straggler_gap_s_by_rank": ts["straggler_gap_s_by_rank"],
                "straggler_max_gap_s": ts["straggler_max_gap_s"],
                "straggler_rank": ts["straggler_rank"],
                "clock_offsets": ts["offsets"],
            }
        if membership:
            snap["membership"] = {
                s: {"generation": r.get("generation"),
                    "trigger": r.get("trigger")}
                for s, r in membership.items()}
        return snap
