"""Versioned metrics schema.

Every JSONL record carries an ``event`` discriminator; the required
fields (and their JSON types) per event kind are listed below. Records
may carry EXTRA fields freely — consumers must ignore unknown keys —
but a required field may never be removed or retyped without bumping
``SCHEMA_VERSION`` (tests/test_obs.py pins the v1 field list; the
drift check fails any PR that breaks the contract silently).

Type tags are JSON types: "string" | "integer" | "number" | "object"
| "array" | "boolean". "integer" excludes booleans; "number" accepts
ints and floats. A required field may be null only when its tag ends
with "?" (e.g. the memory probe returns nulls off-accelerator).
"""

from __future__ import annotations

from typing import Dict, Mapping

SCHEMA_VERSION = 15  # v15: journal record kind (write-ahead delta
#                      journal lifecycle: append / watermark / replay /
#                      truncate / verify / degraded / recovered / skew
#                      — stream/journal.py, docs/STREAMING.md
#                      "Durability & replay")
#                 v14: tracesync record kind (per-rank training
#                      clock anchors at collective barriers —
#                      obs/trainspan.py, docs/OBSERVABILITY.md
#                      "Training traces")
#                 v13: integrity record kind (SDC detector
#                      outcomes: digest scrub, Freivalds compute
#                      verification, halo wire checksum —
#                      resilience/integrity.py)
#                 v12: autoscale record kind (closed-loop scale
#                          decisions with triggering evidence)
#                 v11: blackbox record kind (flight-recorder crash
#                          dumps, obs/flight.py) + diagnosis record kind
#                          (postmortem verdicts, obs/postmortem.py +
#                          pipegcn-debug) — docs/OBSERVABILITY.md
#                          "Postmortem & flight recorder"

# one run header per file/run: what produced the numbers
RUN_FIELDS: Dict[str, str] = {
    "event": "string",           # "run"
    "schema_version": "integer",
    "time_unix": "number",
    "config": "object",          # model/train/CLI config snapshot
    "device": "object",          # platform / device_kind / counts
    "mesh": "object",            # n_parts, axis names/shape
}

# one record per training epoch
EPOCH_FIELDS: Dict[str, str] = {
    "event": "string",           # "epoch"
    "epoch": "integer",          # 0-based global epoch index
    "step_time_s": "number",     # wall-clock of this epoch's dispatch
    "loss": "number",            # global mean train loss
    "grad_norm": "number",       # l2 norm of the reduced gradient
    "halo_bytes": "integer",     # est. halo wire bytes this epoch
    "staleness_age": "integer",  # age (epochs) of consumed boundary data
    "memory": "object?",         # bytes_in_use / peak_bytes_in_use
}

# one record per harvested evaluation
EVAL_FIELDS: Dict[str, str] = {
    "event": "string",           # "eval"
    "epoch": "integer",          # epoch the evaluated params belong to
    "eval_time_s": "number",     # exposed harvest wait (async) / full
    "val_acc": "number",
}

# one summary per completed run
SUMMARY_FIELDS: Dict[str, str] = {
    "event": "string",           # "summary"
    "n_epochs": "integer",
    "epoch_time_s": "number?",   # warmup-excluded mean (fit() semantics)
    "best_val": "number",
}

# one record per detected fault (divergence trip, preemption request,
# injected fault, corrupt checkpoint generation, cross-rank desync,
# lost peer, or — v9 — an ``io-degraded`` durable-write failure: the
# disk rejected a checkpoint / ledger / metrics write and the writer
# fell back to its degradation policy, resilience/storage.py);
# extras carry the kind-specific detail (reason, retry
# count, trip values). Multi-host extras the MetricsLogger always adds
# (optional in the contract so v1 files stay valid):
#   rank         integer — process that wrote the record
#   source_rank  integer — rank that raised a consensus-propagated
#                fault (-1 when several raised at once)
#   agreed       boolean — the action executed in cross-rank lockstep
#   peer_rank    integer — the silent peer of a peer-lost fault
FAULT_FIELDS: Dict[str, str] = {
    "event": "string",           # "fault"
    "kind": "string",            # divergence | preemption | injected
    #                              | desync | peer-lost | ...
    "epoch": "integer",          # epoch the fault surfaced at
}

# one record per completed recovery (training progressed past the
# faulted epoch after rollback/backoff, a resume restored state, or a
# desync resync adopted rank 0's state); carries the same optional
# rank/agreement extras as fault records
RECOVERY_FIELDS: Dict[str, str] = {
    "event": "string",           # "recovery"
    "kind": "string",            # matches the fault it recovers from
    "epoch": "integer",          # epoch training had reached on recovery
}

# one record per captured profiling window (obs/profiler.py): MEASURED
# per-phase device seconds folded from a jax.profiler trace, plus the
# measured comm/compute overlap fraction — the report CLI prints it
# next to (and flags divergence from) the host-side estimate. Extras:
# epoch_start/epoch_end (the --profile-epochs window), trace_files,
# n_device_events/n_matched_events (parser coverage).
PROFILE_FIELDS: Dict[str, str] = {
    "event": "string",             # "profile"
    "phases": "object",            # {spmm|dense|halo_comm|...: seconds}
    "comm_s": "number",            # device seconds in comm phases
    "compute_s": "number",         # device seconds in everything else
    "overlap_fraction": "number",  # measured, in [0, 1]
}

# one record per compiled-step anatomy (obs/anatomy.py): estimated
# FLOPs/bytes per phase from the optimized HLO walk + XLA's own cost /
# memory analysis. flops/bytes_accessed are XLA's totals (null when the
# backend exposes no analysis); attributed_flops_fraction is the share
# of the estimate landing in a named (non-"other") phase.
ANATOMY_FIELDS: Dict[str, str] = {
    "event": "string",             # "anatomy"
    "phases": "object",            # {phase: {flops, bytes, n_ops}}
    "est_flops": "number",         # this parser's own total estimate
    "flops": "number?",            # XLA cost_analysis total
    "attributed_flops_fraction": "number?",
}

# one record per staleness probe epoch (--staleness-probe-every):
# per-layer relative drift between the stale boundary features the
# pipelined step consumed and the fresh ones it shipped —
# ||h_stale - h_fresh|| / ||h_fresh|| — the approximation the pipeline
# actually pays, measured for the first time.
STALENESS_FIELDS: Dict[str, str] = {
    "event": "string",             # "staleness"
    "epoch": "integer",            # probe epoch
    "layers": "object",            # {layer: {rel_drift, fresh_norm}}
    "max_rel_drift": "number",     # max over layers
}

# one record per numerics-guardrail event (resilience/numerics.py):
#   kind "overflow"  — a loss-scale overflow epoch: the in-graph select
#                      skipped the update; extras: scale, skipped,
#                      new_scale (when auto mode backed off)
#   kind "growth"    — the dynamic scale regrew after a clean streak;
#                      extras: scale
#   kind "tripwire"  — a sentinel trip's NaN provenance; extras: phase
#                      (resilience/numerics.PHASES), counts (per-phase
#                      non-finite element counts of the tripped epoch)
NUMERICS_FIELDS: Dict[str, str] = {
    "event": "string",             # "numerics"
    "kind": "string",              # overflow | growth | tripwire
    "epoch": "integer",
}

# one record per kernel-fallback-ladder downgrade (resilience/numerics
# + Trainer._dispatch): a compile-or-dispatch crash of the aggregation
# kernel was absorbed by rebuilding one rung down (block -> bucket ->
# sorted-XLA) instead of killing the run. Extras: reason (the absorbed
# error, truncated).
FALLBACK_FIELDS: Dict[str, str] = {
    "event": "string",             # "fallback"
    "epoch": "integer",            # epoch the downgrade happened at
    "from_impl": "string",         # kernel that failed
    "to_impl": "string",           # kernel the step rebuilt on
}

# one record per run with spmm_impl='auto' (ops/tuner.py +
# Trainer._resolve_auto): WHY this kernel dispatches. winner carries
# {name, impl, rem_dtype, rem_amax, block_group}; costs is the full
# measured per-candidate micro-bench table (empty for the
# no-measurement default); source says where the decision came from:
#   "artifact" — trusted persisted tuning.json in the partition artifact
#   "live"     — micro-bench ran at trainer setup (cache miss); extras
#                carry stale_reason (why the persisted table, if any,
#                was rejected — the LOUD part of the stale-table path)
#   "default"  — no table and no live tune allowed (multi-process or
#                --no-tune): the tuner's fixed deterministic default
TUNING_FIELDS: Dict[str, str] = {
    "event": "string",             # "tuning"
    "winner": "object",            # the dispatched kernel config
    "source": "string",            # artifact | live | default
    "costs": "array",              # measured per-candidate cost table
}

# one record per serving report window (serve/loadgen.run_serving_loop,
# default every --serve-report-every seconds, plus one final record on
# shutdown carrying the extra field `final: true`): the online-serving
# health tuple. Latency percentiles are per-query wall times measured
# submit -> batch-flush-complete (null in a window that served nothing);
# batch_fill is mean served-rows / padded-bucket-rows over the window's
# flushed batches; staleness_age is the max bounded-staleness age (in
# applied update batches) any query in the window was served at — 0
# means every answer reflected every accepted update (docs/SERVING.md).
# v7 grows the parameter-staleness axis + load-shedding accounting:
# param_generation is the checkpoint generation (epoch) of the params
# that served this window (-1 = freshly-initialized, no checkpoint);
# param_staleness counts CRC-verified published generations NEWER than
# the serving one (0 = serving the newest model); shed counts query
# rows explicitly rejected this window (bounded queue / per-ticket
# deadline) instead of silently growing the queue.
SERVING_FIELDS: Dict[str, str] = {
    "event": "string",             # "serving"
    "window_s": "number",          # report window wall-clock length
    "queries": "integer",          # queries answered this window
    "qps": "number",               # queries / window_s
    "batch_fill": "number?",       # mean batch fill ratio in (0, 1]
    "queue_depth": "integer",      # queued rows at snapshot time
    "p50_ms": "number?",           # per-query latency percentiles
    "p95_ms": "number?",
    "p99_ms": "number?",
    "cache_hit_rate": "number?",   # fully-fresh served fraction
    "staleness_age": "integer",    # max served staleness (update batches)
    "shed": "integer",             # rows load-shed this window
    "param_generation": "integer",  # checkpoint gen of served params
    "param_staleness": "integer",  # newer published gens not yet served
}

# one record per serving-fleet lifecycle event (serve/fleet.py +
# serve/router.py): replica death/failover/relaunch/rejoin and
# zero-downtime checkpoint hot-swaps. kind:
#   replica-dead   a replica stopped answering (process exit, stale
#                  heartbeat, or RPC failure); extras: reason
#   failover       in-flight tickets were retried against survivors;
#                  extras: n_retried, to_replica
#   relaunch       the fleet supervisor restarted the replica process;
#                  extras: incarnation, delay_s
#   replica-rejoin the relaunched replica answered health checks and
#                  re-entered routing; extras: incarnation,
#                  rejoin_latency_s
#   hot-swap       the replica swapped to a newer CRC-verified
#                  checkpoint generation without retracing; extras:
#                  param_generation, swap_ms
#   swap-rejected  a corrupt/truncated generation failed verification
#                  and the replica kept (or walked back to) older
#                  params; extras: reason
#   fleet-stop     the supervisor stopped relaunching (max-restarts /
#                  restart-storm brake); extras: reason
#   topo-skew      (v15) the replica reported a topo_generation behind
#                  the fleet maximum — it serves a stale graph and is
#                  routed around until journal replay catches it up;
#                  extras: topo_generation, fleet_generation
#   topo-caught-up (v15) a previously stale replica reported the fleet
#                  generation again and re-entered routing; extras:
#                  topo_generation
FLEET_FIELDS: Dict[str, str] = {
    "event": "string",             # "fleet"
    "kind": "string",              # see above
    "replica": "integer",          # replica id the event concerns
    "window": "integer",           # serving report window index
}

# one record per membership generation of an elastic-supervised run
# (resilience/elastic.py): who owns which partitions and why the
# fleet was (re)launched. assignment is Assignment.as_json() —
# {n_parts, parts_per_node, n_nodes, members, parts: {member:
# [partition ids]}, idle}. trigger: start | rank-death |
# preempt-resume | rejoin | restart-all | supervisor-resume, or the
# stop reasons max-restarts | restart-storm. restart_latency_s is the
# death-detect -> relaunch wall time (null on the initial launch).
# Extras the supervisor adds: n_members.
MEMBERSHIP_FIELDS: Dict[str, str] = {
    "event": "string",             # "membership"
    "generation": "integer",       # monotonic across restarts (ledger)
    "assignment": "object",        # partition -> member mapping
    "trigger": "string",           # what caused this generation
    "restart_latency_s": "number?",
}

# one record per applied graph delta batch (stream/, docs/STREAMING.md)
# — written from the training loop (scheduled --stream-plan entries and
# injected graph-delta faults alike) at the epoch boundary the patch
# landed on. patch_ms is the host-side incremental patch time;
# tables_rebuilt counts per-shard kernel-table rebuilds the delta
# forced (0 on the raw-edge path); slack_remaining maps each padded
# dimension ({"n": rows, "e": edges, "b": send slots}) to the worst-
# shard free-slot count after this patch; repadded=true flags the loud
# slack-exhaustion path (shapes grew, the step recompiled); drift is
# the forced staleness probe's max relative drift across the first
# post-patch step (null when the pipeline is off).
STREAM_FIELDS: Dict[str, str] = {
    "event": "string",             # "stream"
    "epoch": "integer",            # boundary the delta applied at
    "seq": "integer",              # monotonic delta-batch sequence id
    "edges_added": "integer",
    "edges_deleted": "integer",
    "nodes_added": "integer",
    "patch_ms": "number",          # host incremental-patch time
    "tables_rebuilt": "integer",   # per-shard table rebuilds forced
    "repadded": "boolean",         # slack exhausted -> shapes grew
    "slack_remaining": "object",   # {n|e|b: worst-shard free slots}
    "drift": "number?",            # forced probe max_rel_drift
}

# one record per chaos-soak episode (resilience/soak.py +
# scripts/soak.py): the seeded fault schedule the episode composed and
# the per-invariant verdict. schedule is the fault-plan entry list
# (strings, kind@epoch[...] grammar); invariants maps each invariant
# name (checkpoint | ledger | metrics | tickets | resume) to
# {ok: bool, detail: str}; verdict is "green" | "red". Extras:
# episode wall time, restart counts.
SOAK_FIELDS: Dict[str, str] = {
    "event": "string",             # "soak"
    "episode": "integer",          # 0-based episode index
    "seed": "integer",             # the driving soak seed
    "schedule": "array",           # composed fault-plan entries
    "invariants": "object",        # {name: {ok, detail}}
    "verdict": "string",           # green | red
}

# one record per SLO alert EDGE (obs/health.py rule engine, emitted by
# cli.monitor): state flips to "fire" when a rule's predicate first
# holds and to "resolve" when it first stops holding — the engine
# dedupes, so a firing rule writes exactly one record per edge no
# matter how many evaluation ticks it stays red. rule names the
# built-in predicate (epoch-time-regression | shed-rate |
# staleness-age | fault-rate | silent-source); source is the stream
# key the rule evaluated ("*" for run-wide rules); value/threshold are
# the observed number and the rule bound at the edge (null when the
# edge is a resolve with no fresh observation, e.g. a silent source).
ALERT_FIELDS: Dict[str, str] = {
    "event": "string",             # "alert"
    "rule": "string",              # rule id (see above)
    "state": "string",             # fire | resolve
    "severity": "string",          # info | warn | page
    "source": "string",            # stream key evaluated ("*" run-wide)
    "value": "number?",            # observed value at the edge
    "threshold": "number?",        # rule bound at the edge
    "message": "string",           # human-readable one-liner
}

# one record per sampled serving-path span (serve/*, docs/SERVING.md):
# a trace id minted at submit time (--trace-sample-rate) rides the
# ticket through the micro-batcher and — on the fleet path — the RPC
# to the replica and the engine's chunked execution; every hop lands
# one span. op:
#   queue     submit -> batch dispatch (driver)
#   dispatch  batch dispatch -> result complete (driver)
#   shed      submit -> explicit shed (terminal; extras: reason)
#   rpc       router dispatch RPC round-trip (driver; extras: replica)
#   replica   replica-side request handling (replica process)
#   engine    compiled-engine chunk execution (whichever process runs it)
# Exactly one TERMINAL span (dispatch | shed) exists per sampled
# submit — tests/test_monitor.py pins the conservation. t_start is
# unix seconds (cross-process alignable); cli.timeline stitches spans
# sharing a trace_id into Perfetto flow events.
SPAN_FIELDS: Dict[str, str] = {
    "event": "string",             # "span"
    "trace_id": "string",          # minted at submit, shared by all hops
    "span_id": "string",           # unique per span record
    "op": "string",                # queue|dispatch|shed|rpc|replica|engine
    "t_start": "number",           # unix seconds at span start
    "dur_ms": "number",            # span duration, milliseconds
    "status": "string",            # ok | shed | error
}

# one record per black-box flight-recorder dump (obs/flight.py): the
# breadcrumb ring a dying (or stalled, or signalled) process left
# behind, written atomically to blackbox-r<k>.json — and mirrored into
# the metrics stream when a sink is attached. reason: watchdog |
# exception | preemption | signal | stall | fault | manual. crumbs is
# the bounded ring (newest last); last_crumb/open_spans annotate what
# the process was doing (phase, epoch, ring distance, peer rank);
# stacks is faulthandler's all-thread capture (null when the dump path
# had no stack capture, e.g. a clean-exception dump). Extras:
# time_unix, pid, n_crumbs_total, annotation.
BLACKBOX_FIELDS: Dict[str, str] = {
    "event": "string",             # "blackbox"
    "rank": "integer",             # process that wrote the dump
    "reason": "string",            # see above
    "crumbs": "array",             # the breadcrumb ring, newest last
    "last_crumb": "object?",       # newest breadcrumb (null: empty ring)
    "open_spans": "array",         # enter'd-but-never-exit'd spans
    "stacks": "string?",           # all-thread stack text (hang paths)
}

# one record per postmortem verdict (obs/postmortem.py rule engine,
# written by pipegcn-debug / the elastic supervisor / tpu_window's
# failed-step auto-explain): the confidence-ranked root cause of a run.
# verdict names the failure class (wedged-collective | oom |
# fallback-exhausted | corrupt-artifact | config-error | desync |
# sdc | storage-fault | recompile-storm | divergence | preemption |
# clean-exit | unknown); evidence is the citing strings (file: record)
# the rule matched on; deterministic says whether a supervisor should
# fail fast (True: relaunching reproduces the failure) or keep its
# restart/backoff policy. Extras: run_dir, candidates (the full ranked
# list), timeline, generation/member (supervisor path), step
# (tpu_window path).
DIAGNOSIS_FIELDS: Dict[str, str] = {
    "event": "string",             # "diagnosis"
    "verdict": "string",           # failure class (see above)
    "confidence": "number",        # rule confidence in [0, 1]
    "evidence": "array",           # citing strings, most telling first
    "remediation": "string",       # operator hint one-liner
    "deterministic": "boolean",    # fail fast vs restart-and-hope
}

# one record per autoscaler DECISION tick that proposed or refused a
# scale action (serve/autoscale.py, executed by cli/fleet.py's
# FleetManager; docs/SERVING.md "Autoscaling & overload"). Hold ticks
# with nothing to say are NOT recorded — only scale-up | scale-down
# (executed proposals) and refuse (a proposal the brakes vetoed:
# cooldown | storm-brake | max-replicas | min-replicas) land, so the
# stream is the audit ledger of every actuation and every veto.
# evidence carries the triggering telemetry snapshot (queue_depth,
# shed_rate, p99_ms, staleness, firing alert rules, sustain/idle tick
# counts) so a postmortem can replay WHY from the record alone.
AUTOSCALE_FIELDS: Dict[str, str] = {
    "event": "string",             # "autoscale"
    "action": "string",            # scale-up | scale-down | refuse
    "reason": "string",            # queue-pressure | shed-rate | p99-slo
    #                              # | alert:<rule> | idle | cooldown |
    #                              # | storm-brake | max-replicas | ...
    "window": "integer",           # serving report window index
    "n_replicas": "integer",       # fleet size when the decision fired
    "target": "integer",           # proposed fleet size (== n_replicas
    #                              # on refuse)
    "evidence": "object",          # triggering telemetry snapshot
}

# one record per integrity-plane detector verdict (resilience/
# integrity.py, driven by fit() at --integrity-check-every cadence):
# check names the detector (scrub = fletcher digest compare of device
# state against its baseline, freivalds = randomized algebraic SpMM
# verification through the production kernel, wire = the halo
# checksum lane riding each ppermute distance block); outcome is
# "ok" | "mismatch"; target attributes the state class the detector
# guards (params | carry | tables | halo — null when the check spans
# classes); cadence echoes the configured check period so a reader
# can judge detection latency from the record alone; overhead_s is
# the measured host+device cost of THIS check (the bench.py
# integrity_delta_s lever aggregates it). Extras: detail (bounded
# human-readable mismatch description), dirty_shards (shard ids the
# scrubber attributed, drives the dirty-shard rebuild).
INTEGRITY_FIELDS: Dict[str, str] = {
    "event": "string",             # "integrity"
    "epoch": "integer",            # boundary the check ran at
    "check": "string",             # scrub | freivalds | wire
    "outcome": "string",           # ok | mismatch
    "target": "string?",           # params | carry | tables | halo
    "cadence": "integer",          # configured --integrity-check-every
    "overhead_s": "number",        # measured cost of this check
}

# one record per dispatched training block per rank (obs/trainspan.py):
# the rank's wall-clock anchor for the block's harvest barrier. Every
# rank's compiled step for epoch E can only complete once the gradient
# all-reduce has, so the anchors for epoch E mark the same physical
# instant on every rank; trainspan.estimate_offsets folds them into
# per-rank clock offsets and the timeline / straggler attribution /
# overlap math all run on the aligned clock. Extras: source (r<k>).
TRACESYNC_FIELDS: Dict[str, str] = {
    "event": "string",             # "tracesync"
    "rank": "integer",             # process that wrote the anchor
    "epoch": "integer",            # first epoch of the dispatched block
    "t_anchor": "number",          # unix seconds at the harvest barrier
    "generation": "integer",       # membership generation of the run
}

# one record per write-ahead delta-journal lifecycle event
# (stream/journal.py, emitted by the trainer's stream boundary, the
# CLI's resume replay, and serving-replica restarts): op is one of
# append (a batch became durable and was applied; extras: lag_seqs =
# journaled seqs a crash right now would replay), watermark (a
# checkpoint generation landed covering seq), replay (a resume
# re-applied n_records journaled batches; extras: rederived = records
# the torn journal lost and the plan re-derived), truncate (WAL
# rollback past the checkpoint watermark: n_records uncommitted
# entries dropped — the topo-rollback postmortem signature), verify
# (the bit-identity oracle ran post-replay; extras: tables_match),
# degraded / recovered (the journal's own degrade-not-lose queue), and
# skew (the router observed a replica behind the fleet's
# topo_generation). source labels the writer (trainer | resume |
# replica-m<K> | router).
JOURNAL_FIELDS: Dict[str, str] = {
    "event": "string",             # "journal"
    "op": "string",                # append | watermark | replay | ...
    "seq": "integer",              # delta seq the op is about (-1 none)
    "topo_generation": "integer",  # topology generation after the op
    "n_records": "integer",        # records the op touched (0 for point
    #                              # ops like watermark)
    "source": "string",            # trainer | resume | replica-m<K> | …
}

_BY_EVENT = {
    "run": RUN_FIELDS,
    "epoch": EPOCH_FIELDS,
    "eval": EVAL_FIELDS,
    "summary": SUMMARY_FIELDS,
    "fault": FAULT_FIELDS,
    "recovery": RECOVERY_FIELDS,
    "profile": PROFILE_FIELDS,
    "anatomy": ANATOMY_FIELDS,
    "staleness": STALENESS_FIELDS,
    "numerics": NUMERICS_FIELDS,
    "fallback": FALLBACK_FIELDS,
    "tuning": TUNING_FIELDS,
    "serving": SERVING_FIELDS,
    "membership": MEMBERSHIP_FIELDS,
    "fleet": FLEET_FIELDS,
    "stream": STREAM_FIELDS,
    "soak": SOAK_FIELDS,
    "alert": ALERT_FIELDS,
    "span": SPAN_FIELDS,
    "tracesync": TRACESYNC_FIELDS,
    "blackbox": BLACKBOX_FIELDS,
    "diagnosis": DIAGNOSIS_FIELDS,
    "autoscale": AUTOSCALE_FIELDS,
    "integrity": INTEGRITY_FIELDS,
    "journal": JOURNAL_FIELDS,
}

_JSON_TYPES = {
    "string": str,
    "integer": int,
    "number": (int, float),
    "object": dict,
    "array": list,
    "boolean": bool,
}


def validate_record(rec: Mapping) -> None:
    """Raise ValueError when `rec` misses a required field of its event
    kind or carries it with the wrong JSON type. Unknown event kinds
    (free-form ``MetricsLogger.event`` records) and extra fields pass —
    the schema constrains only the contracted record kinds."""
    ev = rec.get("event")
    fields = _BY_EVENT.get(ev)
    if fields is None:
        if not isinstance(ev, str) or not ev:
            raise ValueError(f"record without a string 'event': {rec!r}")
        return
    for name, tag in fields.items():
        nullable = tag.endswith("?")
        if nullable:
            tag = tag[:-1]
        if name not in rec:
            raise ValueError(f"{ev} record missing field {name!r}")
        v = rec[name]
        if v is None:
            if nullable:
                continue
            raise ValueError(f"{ev} record field {name!r} is null")
        py = _JSON_TYPES[tag]
        # bool is an int subclass in python; exclude it from the
        # numeric tags so a True never masquerades as a count
        if isinstance(v, bool) and tag in ("integer", "number"):
            raise ValueError(
                f"{ev} record field {name!r}: expected {tag}, got bool")
        if not isinstance(v, py):
            raise ValueError(
                f"{ev} record field {name!r}: expected {tag}, "
                f"got {type(v).__name__}")
