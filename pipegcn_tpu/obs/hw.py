"""Public per-chip peak dense bf16 FLOP/s, for MFU reporting.

Single source of truth shared by bench.py and the report CLI (the
table previously lived inline in bench.py). Matching is by substring
of `device.device_kind`, most specific first.
"""

from __future__ import annotations

from typing import Optional

# peak dense bf16 FLOP/s per chip, by device_kind substring (public specs)
PEAK_FLOPS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def peak_flops_for(kind: str) -> Optional[float]:
    k = (kind or "").lower()
    for sub, f in PEAK_FLOPS:
        if sub in k:
            return f
    return None
